"""Handle-based shim behind the native C ABI (native/cxxnet_wrapper.cc).

The reference exposes the trainer over a C ABI in
wrapper/cxxnet_wrapper.cpp:10-352; here the native library embeds CPython
and calls these functions. Raw device-independent data crosses the
boundary as integer pointer addresses + shapes (the C side owns the
buffers); objects live in a handle registry so the C side only ever holds
opaque uint64 ids.

Error contract: exceptions propagate to the embed layer, which fetches
them via the CPython error indicator and surfaces the message through
CXNGetLastError (cxxnet_wrapper.cc RecordPyError).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Dict

import numpy as np

from cxxnet_tpu.wrapper import DataIter, Net

_lock = threading.Lock()
_objects: Dict[int, object] = {}
_next_id = 1


def _register(obj: object) -> int:
    global _next_id
    with _lock:
        hid = _next_id
        _next_id += 1
        _objects[hid] = obj
    return hid


def _get(hid: int):
    return _objects[hid]


def _as_f32(addr: int, *shape: int) -> np.ndarray:
    n = 1
    for s in shape:
        n *= int(s)
    buf = (ctypes.c_float * n).from_address(addr)
    return np.frombuffer(buf, dtype=np.float32).reshape(*shape)


def _copy_out(arr: np.ndarray, addr: int) -> int:
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    ctypes.memmove(addr, arr.ctypes.data, arr.nbytes)
    return arr.size


# ---------------------------------------------------------------------------
# object lifecycle
# ---------------------------------------------------------------------------

def net_create(dev: str, cfg: str) -> int:
    return _register(Net(dev=dev, cfg=cfg))


def io_create(cfg: str) -> int:
    return _register(DataIter(cfg))


def free(hid: int) -> None:
    with _lock:
        _objects.pop(hid, None)


# ---------------------------------------------------------------------------
# trainer surface (one function per CXN* entry point)
# ---------------------------------------------------------------------------

def net_set_param(hid: int, name: str, val: str) -> None:
    _get(hid).set_param(name, val)


def net_init_model(hid: int) -> None:
    _get(hid).init_model()


def net_load_model(hid: int, fname: str) -> None:
    _get(hid).load_model(fname)


def net_save_model(hid: int, fname: str) -> None:
    _get(hid).save_model(fname)


def net_start_round(hid: int, r: int) -> None:
    _get(hid).start_round(r)


def net_update_iter(hid: int, iter_hid: int) -> None:
    _get(hid).update(_get(iter_hid))


def net_update_batch(hid: int, daddr: int, b: int, c: int, h: int, w: int,
                     laddr: int, lwidth: int) -> None:
    data = _as_f32(daddr, b, c, h, w)
    label = _as_f32(laddr, b, lwidth)
    _get(hid).update(data, label)


def net_evaluate(hid: int, iter_hid: int, name: str) -> str:
    return _get(hid).evaluate(_get(iter_hid), name)


def net_predict_batch(hid: int, daddr: int, b: int, c: int, h: int, w: int,
                      oaddr: int) -> int:
    """Writes b floats to oaddr; returns count."""
    pred = _get(hid).predict(_as_f32(daddr, b, c, h, w))
    return _copy_out(pred, oaddr)


def net_predict_iter(hid: int, iter_hid: int, oaddr: int, cap: int) -> int:
    preds = []
    it = _get(iter_hid)
    net = _get(hid)
    it.before_first()
    while it.next():
        # NetTrainer.predict already drops num_batch_padd rows (the
        # valid-mask truncation in _forward_nodes)
        preds.append(net.predict(it))
    out = np.concatenate(preds) if preds else np.zeros(0, np.float32)
    if out.size > cap:
        raise ValueError(f"output buffer too small: {out.size} > {cap}")
    return _copy_out(out, oaddr)


def net_extract_batch(hid: int, daddr: int, b: int, c: int, h: int, w: int,
                      node_name: str, oaddr: int, cap: int) -> int:
    feat = _get(hid).extract(_as_f32(daddr, b, c, h, w), node_name)
    if feat.size > cap:
        raise ValueError(f"output buffer too small: {feat.size} > {cap}")
    return _copy_out(feat, oaddr)


def net_get_weight(hid: int, layer_name: str, tag: str, oaddr: int,
                   cap: int, shape_addr: int) -> int:
    """Writes the 2-D flattened weight; shape_addr receives 2 uint64s.

    Returns element count, or 0 when the layer exists but has no weight
    under `tag` (CXNNetGetWeight returns NULL there); unknown layer
    names are errors."""
    net = _get(hid)
    if not net.has_layer(layer_name):
        raise KeyError(f"unknown layer name {layer_name}")
    try:
        w = net.get_weight(layer_name, tag)
    except KeyError:
        return 0
    if w.size > cap:
        raise ValueError(f"output buffer too small: {w.size} > {cap}")
    shp = (ctypes.c_uint64 * 2).from_address(shape_addr)
    shp[0], shp[1] = w.shape
    return _copy_out(w, oaddr)


def net_set_weight(hid: int, daddr: int, rows: int, cols: int,
                   layer_name: str, tag: str) -> None:
    _get(hid).set_weight(_as_f32(daddr, rows, cols), layer_name, tag)


# ---------------------------------------------------------------------------
# iterator surface
# ---------------------------------------------------------------------------

def io_next(hid: int) -> int:
    return 1 if _get(hid).next() else 0


def io_before_first(hid: int) -> None:
    _get(hid).before_first()


def io_get_data_shape(hid: int, shape_addr: int) -> None:
    d = _get(hid).get_data()
    shp = (ctypes.c_uint64 * 4).from_address(shape_addr)
    shp[0], shp[1], shp[2], shp[3] = d.shape


def io_copy_data(hid: int, oaddr: int) -> int:
    return _copy_out(_get(hid).get_data(), oaddr)


def io_get_label_shape(hid: int, shape_addr: int) -> None:
    lab = _get(hid).get_label()
    shp = (ctypes.c_uint64 * 2).from_address(shape_addr)
    shp[0], shp[1] = lab.shape


def io_copy_label(hid: int, oaddr: int) -> int:
    return _copy_out(_get(hid).get_label(), oaddr)
