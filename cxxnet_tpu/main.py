"""CLI task driver.

Behavior parity with CXXNetLearnTask (src/cxxnet_main.cpp:16-478):

    python -m cxxnet_tpu.main <config.conf> [k=v ...]

- tasks: train (default) / finetune / pred / pred_raw / extract /
  serve (pred_raw: raw top-node rows - the reference accepts the task
  name but never dispatches it, cxxnet_main.cpp:77-79 vs :242;
  serve: the pred iterator replayed as a ragged request stream
  through the continuous-batching server, docs/SERVING.md)
- `continue = 1` resumes from the newest `model_dir/%04d.model`
- per-round checkpoints gated by `save_model` period
- eval metrics printed per round to stderr as
  `[round]\\ttrain-metric:x\\tevalname-metric:y`
- `test_io = 1` drives the full data pipeline with Update skipped
- `pred = file` + task=pred writes one prediction per line;
  task=extract with `extract_node_name` dumps features (+ .meta)
"""

from __future__ import annotations

import os
import struct
import sys
import time
from typing import List, Optional, Tuple

from cxxnet_tpu import telemetry
from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.nnet.trainer import NetTrainer, StagedChunk
from cxxnet_tpu.utils.config import parse_config_file
from cxxnet_tpu.utils.fault import DivergenceError, atomic_writer


def _eval_values(text: str) -> dict:
    """Parse a reference-format eval string ('\\tname-metric:value'
    repeated) into {name-metric: float} for structured eval events.
    Unparseable tokens are skipped - the event is best-effort, the
    stderr text is the ground truth."""
    out = {}
    for tok in text.split("\t"):
        tok = tok.strip()
        if not tok or ":" not in tok:
            continue
        key, _, val = tok.rpartition(":")
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out


class LearnTask:
    def __init__(self) -> None:
        self.task = "train"
        self.net_type = 0
        self.net_trainer: Optional[NetTrainer] = None
        self.itr_train = None
        self.itr_pred = None
        self.itr_evals = []
        self.eval_names: List[str] = []
        self.name_model_dir = "models"
        self.num_round = 10
        self.test_io = 0
        # depth of the H2D staging prefetch for the train loop
        # (io/prefetch.py); 0 streams batches on the update thread
        self.prefetch_stage = 1
        # fused multi-step dispatch: K staged batches scan through ONE
        # jitted executable per dispatch (docs/PERFORMANCE.md); 1 =
        # per-step dispatch, byte-for-byte today's behavior
        self.steps_per_dispatch = 1
        self.batch_size = 0
        self.silent = 0
        self.start_counter = 0
        self.max_round = 1 << 31
        self.continue_training = 0
        self.save_period = 1
        # checkpoint rotation: keep the newest k %04d.model files
        # (0 = keep everything, the reference behavior)
        self.keep_latest = 0
        # serving publish hook (docs/SERVING.md "Hot-swap runbook"):
        # after every saved round, atomically copy the checkpoint to
        # this path - the file a live Server's swap_watch= poller
        # picks up for a zero-downtime weight swap ("" = off)
        self.name_publish = ""
        self.name_model_in = "NULL"
        self.name_pred = "pred.txt"
        self.print_step = 100
        self.extract_node_name = ""
        self.output_format = 1
        # telemetry sinks (docs/OBSERVABILITY.md): empty = disabled,
        # and the CLI's stdout/stderr stay byte-identical to the
        # pre-telemetry behavior
        self.log_file = ""
        self.metrics_file = ""
        self.log_format = "json"
        self.heartbeat_secs = 0.0
        # live observability plane (docs/OBSERVABILITY.md): /metrics +
        # /healthz + /varz HTTP exposition, declarative alert rules,
        # hang watchdog. All off by default - unarmed runs never
        # import the plane, keeping CLI output byte-identical
        self.metrics_port = 0
        self.metrics_host = ""
        self.alert_rules = ""
        self.alert_cmd = ""
        self.watchdog_secs = 0.0
        # dispatch flight recorder (docs/OBSERVABILITY.md "Flight
        # recorder"): armed automatically with any sink / metrics_port
        # / watchdog_secs / alert_rules; flight_recorder = 1 arms the
        # in-memory ring alone (forensics without any other plane).
        # 0 (the default) adds nothing - byte-parity preserved
        self.flight_recorder = 0
        self.device = "tpu"
        self.eval_train = 1
        self.test_on_server = 0
        # elastic pod training (docs/FAULT_TOLERANCE.md "Elastic
        # pod"): elastic=1 arms the coordinated-checkpoint barrier at
        # every round boundary - the pod elects a leader over the
        # coord_dir control plane (default <model_dir>/coord), ONLY
        # the leader publishes the round's checkpoint, and an absent
        # member is convicted so the supervisor
        # (parallel/elastic.py) can roll back + reshape
        self.elastic = 0
        self.barrier_secs = 30.0
        self.leader_lease_secs = 10.0
        self.coord_dir = ""
        self._coordinator = None
        # config schema gate (docs/STATIC_ANALYSIS.md): unknown keys
        # error with a did-you-mean suggestion instead of silently
        # configuring nothing; schema_check = 0 bypasses
        self.schema_check = 1
        # TVM-style per-platform tuning cache (nnet/tuning.py,
        # tools/autotune.py, docs/GRAPH_PASSES.md): tuned values are
        # DEFAULTS for the task-level knobs below (prefetch_stage,
        # steps_per_dispatch) and the trainer's own tunables -
        # explicitly-set config keys always win
        self.tuning_cache = ""
        # task=serve load shape (docs/SERVING.md): rows per submitted
        # request when replaying the pred iterator through the server
        # (0 = a deterministic ragged size cycle, the bucket-coverage
        # mode the serve-smoke CI job uses)
        self.serve_rows = 1
        # explicit fold-calibration source (docs/GRAPH_PASSES.md
        # multi-batch calibration): which iterator feeds
        # `pass_calibration_batches` batches - "pred" (default),
        # "train", or an eval block's name. With N = 1 and no
        # iterator named, the lazy first-inference-batch path keeps
        # its pinned single-batch behavior
        self.pass_calibration_iter = ""
        self.pass_calibration_batches = 1
        self.cfg: List[Tuple[str, str]] = []
        # index of the first command-line override pair in self.cfg
        # (None = everything is file-like); _split_blocks uses it to
        # keep CLI pairs out of iterator-block scanning
        self._n_file_pairs: Optional[int] = None

    # ------------------------------------------------------------------
    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            telemetry.stdout("Usage: <config> [k=v ...]")
            return 0
        for name, val in parse_config_file(argv[0]):
            self.set_param(name, val)
        n_file_pairs = self._n_file_pairs = len(self.cfg)
        for arg in argv[1:]:
            if "=" in arg:
                name, val = arg.split("=", 1)
                self.set_param(name.strip(), val.strip())
        if self.schema_check:
            # fail BEFORE any backend/iterator is touched: a typo'd
            # key must cost a ConfigError with a suggestion, not a
            # silently-default run (valid configs print nothing, so
            # the CLI byte-parity contract is untouched). File pairs
            # and argv overrides are labeled separately - "in
            # my.conf" for a typo that was actually on the command
            # line sends the user grepping the wrong place
            from cxxnet_tpu.utils.config import validate_known_keys
            validate_known_keys(self.cfg[:n_file_pairs],
                                source=argv[0])
            validate_known_keys(self.cfg[n_file_pairs:],
                                source="command-line override")
        # an explicit JAX_PLATFORMS env always beats the conf's `dev`
        # kind (which is advisory - parallel/mesh.py): without this, a
        # `dev = tpu` conf run under JAX_PLATFORMS=cpu still initializes
        # every registered plugin and can hang on an absent tunnel
        from cxxnet_tpu.utils.platform import ensure_env_platform
        ensure_env_platform()
        if self.device.split(":")[0] == "cpu":
            # honor `dev = cpu` before any backend is touched: skip
            # accelerator-platform init entirely (matters when the TPU
            # tunnel is absent/unreachable - the CLI must still work)
            import jax
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass  # backend already initialized
        # arm telemetry before init() so resume walk-backs and model
        # loads are already on the record; with no sink keys set this
        # returns the process to the disabled (byte-parity) state
        telemetry.configure(
            log_file=self.log_file, metrics_file=self.metrics_file,
            log_format=self.log_format,
            heartbeat_secs=self.heartbeat_secs,
            tags={"device": self.device})
        # live observability plane (docs/OBSERVABILITY.md): watchdog,
        # alert rules, /metrics-/healthz-/varz HTTP exposition. With
        # all four keys unset this is a no-op that imports nothing;
        # metrics_port=0 means OFF on the CLI (an ephemeral bind is a
        # programmatic-only mode - an operator could never find it)
        telemetry.arm_observability(
            metrics_port=(self.metrics_port if self.metrics_port > 0
                          else None),
            metrics_host=self.metrics_host,
            alert_rules=self.alert_rules, alert_cmd=self.alert_cmd,
            watchdog_secs=self.watchdog_secs)
        if self.flight_recorder:
            # in-memory dispatch ring alone (no sink, no thread, no
            # socket): the cheapest forensics mode - a later watchdog
            # or /varz consumer reads what already accumulated
            telemetry.get().flight.arm()
        if self.tuning_cache:
            # AFTER the telemetry sinks armed (the apply_task event
            # must reach the stream), BEFORE init() builds anything
            # from the knobs; the trainer applies its own tunables
            # from the same cache (the `tuning_cache` pair reaches it
            # with the rest of the config) under the same
            # explicit-keys-win rule - so the two consumers can never
            # disagree on a shared knob like steps_per_dispatch
            self._apply_tuning_cache()
        telemetry.event("run_start", task=self.task, conf=argv[0],
                        num_round=self.num_round)
        t_run = time.monotonic()
        try:
            self.init()
            if telemetry.enabled():
                # distributed init (if any) happened inside init():
                # refine the process tag so multi-host streams merge
                import jax
                telemetry.set_tags(proc=jax.process_index())
            if not self.silent:
                telemetry.stdout("initializing end, start working")
            if self.task in ("train", "finetune"):
                self.task_train()
            elif self.task == "pred":
                self.task_predict()
            elif self.task == "pred_raw":
                self.task_predict_raw()
            elif self.task == "extract":
                self.task_extract_feature()
            elif self.task == "serve":
                self.task_serve()
            else:
                raise ValueError(f"unknown task {self.task}")
            return 0
        finally:
            if self._coordinator is not None:
                self._coordinator.close()
            # final snapshot + clean close even on an aborting task, so
            # the stream explains the crash (heartbeat stops with it)
            telemetry.event("run_end", task=self.task,
                            secs=time.monotonic() - t_run)
            telemetry.emit_metrics(kind="final", task=self.task)
            telemetry.close()

    def set_param(self, name: str, val: str) -> None:
        if val == "default":
            return
        if name == "net_type":
            self.net_type = int(val)
        if name == "print_step":
            self.print_step = int(val)
        if name == "continue":
            self.continue_training = int(val)
        if name == "save_model":
            self.save_period = int(val)
        if name == "keep_latest":
            self.keep_latest = int(val)
        if name == "publish_model":
            self.name_publish = val
        if name == "start_counter":
            self.start_counter = int(val)
        if name == "model_in":
            self.name_model_in = val
        if name == "model_dir":
            self.name_model_dir = val
        if name == "num_round":
            self.num_round = int(val)
        if name == "max_round":
            self.max_round = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "task":
            self.task = val
        if name == "dev":
            self.device = val
        if name == "test_io":
            self.test_io = int(val)
        if name == "prefetch_stage":
            self.prefetch_stage = int(val)
        if name == "steps_per_dispatch":
            self.steps_per_dispatch = int(val)
        if name == "batch_size":
            self.batch_size = int(val)
        if name == "eval_train":
            self.eval_train = int(val)
        if name == "test_on_server":
            self.test_on_server = int(val)
        if name == "elastic":
            self.elastic = int(val)
        if name == "barrier_secs":
            self.barrier_secs = float(val)
        if name == "leader_lease_secs":
            self.leader_lease_secs = float(val)
        if name == "coord_dir":
            self.coord_dir = val
        if name == "extract_node_name":
            self.extract_node_name = val
        if name == "output_format":
            self.output_format = 1 if val == "txt" else 0
        if name == "log_file":
            self.log_file = val
        if name == "metrics_file":
            self.metrics_file = val
        if name == "log_format":
            self.log_format = val
        if name == "heartbeat_secs":
            self.heartbeat_secs = float(val)
        if name == "metrics_port":
            self.metrics_port = int(val)
        if name == "metrics_host":
            self.metrics_host = val
        if name == "alert_rules":
            self.alert_rules = val
        if name == "alert_cmd":
            self.alert_cmd = val
        if name == "watchdog_secs":
            self.watchdog_secs = float(val)
        if name == "flight_recorder":
            self.flight_recorder = int(val)
        if name == "schema_check":
            self.schema_check = int(val)
        if name == "serve_rows":
            self.serve_rows = int(val)
        if name == "tuning_cache":
            self.tuning_cache = val
        if name == "pass_calibration_iter":
            self.pass_calibration_iter = val
        if name == "pass_calibration_batches":
            if int(val) < 1:
                raise ValueError(
                    "pass_calibration_batches must be >= 1")
            self.pass_calibration_batches = int(val)
        self.cfg.append((name, val))

    def _apply_tuning_cache(self) -> None:
        """Apply tuned task-level knob defaults from `tuning_cache =`
        (nnet/tuning.py): only knobs no config pair set explicitly.
        A cache with no entry for this platform applies nothing."""
        from cxxnet_tpu.nnet import tuning
        knobs = tuning.tuned_knobs(self.tuning_cache)
        explicit = {k for k, _ in self.cfg}
        applied = {}
        # tuning.int_knob is THE shared apply rule (explicit keys
        # win, malformed values skip) - the trainer consumes the same
        # cache through the same helper
        v = tuning.int_knob(knobs, "prefetch_stage", explicit, 0)
        if v is not None:
            self.prefetch_stage = applied["prefetch_stage"] = v
        v = tuning.int_knob(knobs, "steps_per_dispatch", explicit, 1)
        if v is not None:
            self.steps_per_dispatch = applied["steps_per_dispatch"] = v
        if applied and not self.silent:
            telemetry.stdout(
                "tuning_cache: applied "
                + " ".join(f"{k}={v}"
                           for k, v in sorted(applied.items())))
        if applied:
            telemetry.event("tuning", op="apply_task",
                            cache=self.tuning_cache, **applied)

    # ------------------------------------------------------------------
    def _split_blocks(self):
        """Segment the flat conf into (defcfg, train, evals, pred):
        defcfg = keys outside any iterator block, train/pred = that
        block's keys, evals = [(eval_name, keys), ...]. The ONE
        scanner both _create_net and _create_iterators consume - the
        two previous hand-rolled copies had already drifted (pred
        folded into eval, train keys in/out of defcfg). Also records
        self.name_pred from the `pred =` line."""
        defcfg: List[Tuple[str, str]] = []
        train = None
        evals: List[Tuple[str, List[Tuple[str, str]]]] = []
        pred = None
        cur: Optional[List[Tuple[str, str]]] = None
        evname = ""
        flag = 0
        for idx, (name, val) in enumerate(self.cfg):
            cli = (self._n_file_pairs is not None
                   and idx >= self._n_file_pairs)
            if name == "data":
                if cli:
                    continue  # a CLI pair is never a block marker
                flag, cur = 1, []
                continue
            if name == "eval":
                if cli:
                    continue
                flag, cur, evname = 2, [], val
                continue
            if name == "pred":
                self.name_pred = val
                if cli:
                    # `pred=file.txt` on the command line renames the
                    # output; opening an (unterminated) pred iterator
                    # block here would silently swallow every override
                    # after it - serve_max_batch=8 after pred= used to
                    # configure nothing
                    continue
                flag, cur = 3, []
                continue
            if name == "iter" and val == "end":
                assert flag != 0, "wrong configuration file"
                if flag == 1:
                    assert train is None, "can only have one data"
                    train = cur
                elif flag == 2:
                    evals.append((evname, cur))
                else:
                    assert pred is None, "can only have one data:test"
                    pred = cur
                flag, cur = 0, None
                continue
            (defcfg if cur is None else cur).append((name, val))
        return defcfg, train, evals, pred

    @staticmethod
    def _daug_spec(pairs) -> dict:
        """Canonical device-augment normalization spec from conf pairs
        (last-writer-wins): divideby folds into scale exactly as the
        trainer's own alias does, and defaults are filled so an
        explicit `mirror = 0` compares equal to an absent one."""
        spec = {"scale": 1.0, "mirror": "0", "crop_y_start": "-1",
                "crop_x_start": "-1", "image_mean": "", "mean_value": "",
                "input_shape": "", "device_augment": "0"}
        for k, v in pairs:
            if k == "divideby":
                spec["scale"] = 1.0 / float(v)
            elif k == "scale":
                spec["scale"] = float(v)
            elif k == "mean_value":
                # parse so `0, 0, 0` == `0,0,0`, and all-zero == OFF
                # == absent (make_device_augment's own rule)
                vals = tuple(float(t) for t in v.split(","))
                spec[k] = "" if not any(vals) else \
                    ",".join(f"{t:g}" for t in vals)
            elif k in spec:
                spec[k] = v
        return spec

    def _create_net(self) -> NetTrainer:
        """Build the trainer from the global section + the train data
        block (every task - the historic spec source), plus the pred
        block layered last UNDER task=pred/extract only (so the
        feeding iterator's image_mean/scale reaches the
        device_augment eval spec). The pred block must NOT feed under
        task=train - iterator-scoped keys like a pred batch_size
        would silently clobber the train configuration - and eval
        blocks never feed (an eval block without rand_crop must not
        erase the train block's crop)."""
        defcfg, train, evals, pred = self._split_blocks()
        feed = defcfg + (train or [])
        if self.task in ("pred", "pred_raw", "extract", "serve"):
            feed = feed + (pred or [])
        net = NetTrainer()
        for k, v in feed:
            net.set_param(k, v)
        self._check_daug_blocks(net, feed, defcfg, train, evals, pred)
        return net

    def _check_daug_blocks(self, net, feed, defcfg, train, evals, pred):
        """device_augment bakes ONE normalization spec into the jitted
        step, but every iterator block feeds it raw pixels. A block
        whose effective spec diverges from the trainer's would be
        silently normalized with the WRONG spec - fail loudly instead.
        Only blocks the CURRENT task instantiates are checked (a conf
        shared between train and pred must not be rejected for a
        divergence in a block the task never uses). `feed` is exactly
        what _create_net fed the trainer, so eff IS the compiled
        spec."""
        active = []
        if self.task in ("pred", "pred_raw", "extract", "serve"):
            if pred is not None:
                active.append(("pred", pred))
        else:
            if train is not None:
                active.append(("data", train))
            active.extend((name or "eval", keys) for name, keys in evals)
        eff = self._daug_spec(feed)
        want = "1" if net.device_augment else "0"
        for tag, keys in active:
            bs = self._daug_spec(defcfg + keys)
            flag = "1" if int(bs["device_augment"] or "0") else "0"
            if flag != want:
                raise ValueError(
                    f"device_augment mismatch: the trainer compiled "
                    f"with device_augment={want} but iterator block "
                    f"'{tag}' has device_augment={flag} - raw pixels "
                    "and the in-step augment must agree. Set "
                    "device_augment globally, not per block.")
            if not net.device_augment:
                continue
            for k in ("scale", "mirror", "crop_y_start", "crop_x_start",
                      "image_mean", "mean_value", "input_shape"):
                if bs[k] != eff[k]:
                    raise ValueError(
                        f"device_augment: block '{tag}' has {k}="
                        f"{bs[k]!r} but the trainer's compiled spec "
                        f"has {k}={eff[k]!r}; the in-step augment is "
                        "compiled once - per-block normalization "
                        "divergence cannot be honored (use the host "
                        "pipeline, device_augment=0, for that)")

    def init(self) -> None:
        # param_server=dist: join the multi-controller job up front so
        # every later path (model load, iterators, mesh) sees the global
        # device view (idempotent; trainer.init_model also calls it)
        from cxxnet_tpu.parallel import distributed
        distributed.init_from_config(self.cfg)
        if self.elastic and self.task in ("train", "finetune"):
            self._start_coordinator()
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model():
                telemetry.stdout(f"Init: Continue training from round "
                                 f"{self.start_counter}")
                telemetry.event("checkpoint", op="resume",
                                round=self.start_counter)
                self._create_iterators()
                return
            # reference aborts here (cxxnet_main.cpp:109-113)
            raise FileNotFoundError(
                "Init: cannot find models for continue training; "
                "specify model_in instead")
        if self.name_model_in == "NULL":
            assert self.task == "train", \
                "must specify model_in if not training"
            self.net_trainer = self._create_net()
            self.net_trainer.init_model()
        elif self.task == "finetune":
            self._copy_model()
        else:
            self._load_model()
        self._create_iterators()

    def _start_coordinator(self) -> None:
        """Arm the elastic coordinator (parallel/coordinator.py):
        membership comes from the supervisor's generation.json when
        present (the record names this pod generation's members; this
        worker's member id arrives in CXN_MEMBER_ID), and degrades to
        rank-as-member for a pod launched without a supervisor."""
        import jax
        from cxxnet_tpu.parallel import distributed
        from cxxnet_tpu.parallel.coordinator import (ControlPlane,
                                                     Coordinator)
        coord_dir = self.coord_dir or os.path.join(
            self.name_model_dir, "coord")
        os.makedirs(coord_dir, exist_ok=True)
        generation, members = 0, list(range(jax.process_count()))
        if os.path.exists(os.path.join(coord_dir, "generation.json")):
            rec = distributed.read_membership(coord_dir)
            generation = int(rec.get("generation", 0))
            members = [int(m) for m in rec["members"]]
        member_env = os.environ.get("CXN_MEMBER_ID")
        if member_env is not None:
            member = int(member_env)
        else:
            member = members[jax.process_index()]
        plane = ControlPlane(coord_dir)
        self._coordinator = Coordinator(
            plane, member, members, generation=generation,
            barrier_secs=self.barrier_secs,
            lease_secs=self.leader_lease_secs)
        self._coordinator.start()
        telemetry.event("coord", op="start", member=member,
                        generation=generation, members=members)

    def _model_name(self, counter: int) -> str:
        return os.path.join(self.name_model_dir, f"{counter:04d}.model")

    def _model_counters(self) -> List[int]:
        """Sorted %04d.model counters present in model_dir (the pattern
        accepts 5+ digits: %04d renders them past round 9999)."""
        import re
        try:
            names = os.listdir(self.name_model_dir)
        except OSError:
            return []
        return sorted(int(m.group(1)) for m in
                      (re.fullmatch(r"(\d{4,})\.model", n) for n in names)
                      if m)

    def _sync_latest_model(self) -> bool:
        """Load the newest VALID checkpoint at or past start_counter,
        walking backward past corrupt/truncated files (each skip is
        logged). A crash mid-save or disk corruption must cost at most
        the lost rounds, never the whole run - and never silently
        resume from garbage (the reference loads whatever bytes are
        there, cxxnet_main.cpp:100-113). The scan is listdir-based, not
        an ascending existence probe, so keep_latest rotation having
        deleted the early checkpoints does not hide the survivors."""
        from cxxnet_tpu.nnet import checkpoint
        counters = [c for c in self._model_counters()
                    if c >= self.start_counter]
        while counters:
            c = counters.pop()
            path = self._model_name(c)
            t0 = time.perf_counter()
            err = checkpoint.validate_file(path)
            if err is None:
                try:
                    self.net_trainer = self._create_net()
                    with open(path, "rb") as fi:
                        self.net_trainer.load_model(fi)
                except (OSError, ValueError, KeyError,
                        struct.error) as e:
                    # validate_file can pass formats it cannot cheaply
                    # check (legacy binaries, whose loader raises
                    # struct.error/KeyError on garbage); a failed load
                    # walks back like any other invalid file
                    err = str(e)
                    self.net_trainer = None
            if err is not None:
                # crc-skip walk-back: countable, not just a stderr line
                telemetry.inc("checkpoint.walkback")
                telemetry.stderr(
                    f"Init: skipping invalid checkpoint {path}: {err}\n",
                    event_kind="checkpoint", op="skip_invalid",
                    path=path, error=err)
                continue
            secs = time.perf_counter() - t0
            telemetry.observe("checkpoint.load_s", secs)
            telemetry.event("checkpoint", op="load", path=path,
                            round=c, secs=secs)
            # the next save overwrites the first invalid/missing slot,
            # re-training the lost rounds
            self.start_counter = c + 1
            return True
        return False

    def _newest_model_counter(self) -> Optional[int]:
        """Largest %04d.model counter present in model_dir, if any."""
        hits = self._model_counters()
        return hits[-1] if hits else None

    def _load_model(self) -> None:
        base = os.path.basename(self.name_model_in)
        try:
            self.start_counter = int(base.split(".")[0]) + 1
        except ValueError:
            # default to one past the newest existing checkpoint so the
            # next save can never overwrite one (a stale start_counter
            # here used to clobber existing %04d.model files)
            newest = self._newest_model_counter()
            self.start_counter = (newest + 1 if newest is not None
                                  else self.start_counter + 1)
            telemetry.stdout(
                f"WARNING: cannot infer start_counter from model name; "
                f"using {self.start_counter} (one past the newest "
                f"checkpoint in {self.name_model_dir})")
        self.net_trainer = self._create_net()
        t0 = time.perf_counter()
        with open(self.name_model_in, "rb") as fi:
            self.net_trainer.load_model(fi)
        secs = time.perf_counter() - t0
        telemetry.observe("checkpoint.load_s", secs)
        telemetry.event("checkpoint", op="load", path=self.name_model_in,
                        secs=secs)

    def _copy_model(self) -> None:
        self.net_trainer = self._create_net()
        self.net_trainer.init_model()
        with open(self.name_model_in, "rb") as fi:
            self.net_trainer.copy_model_from(fi)

    def _save_model(self) -> None:
        # quirk parity: the modulo check uses the POST-incremented counter
        # (cxxnet_main.cpp:173-176), so with save_model=k the rounds saved
        # are k-1, 2k-1, ... — e.g. save_model=num_round=15 writes only
        # 0014.model. Kept so round numbering matches the reference.
        counter = self.start_counter
        self.start_counter += 1
        barrier = None
        if self._coordinator is not None:
            # elastic pod: EVERY round boundary is a barrier (absent
            # members must be convicted promptly, not only on save
            # rounds), and on save rounds only the elected leader
            # writes - ending the N-independent-writers race on the
            # shared %04d.model path
            barrier = self._pod_barrier(counter)
        if self.save_period == 0 or self.start_counter % self.save_period:
            return
        if barrier is not None and not barrier.is_leader:
            telemetry.event("checkpoint", op="skip_nonleader",
                            round=counter, leader=barrier.leader)
            return
        os.makedirs(self.name_model_dir, exist_ok=True)
        path = self._model_name(counter)
        t0 = time.perf_counter()
        # durable save: tmp + fsync + os.replace, so a kill mid-write
        # leaves at most a *.tmp - %04d.model is complete or absent
        with atomic_writer(path) as fo:
            self.net_trainer.save_model(fo)
        # end-to-end save cost incl. fsync + rename (serialization-only
        # time is checkpoint.write_s, kept by nnet/checkpoint.py)
        secs = time.perf_counter() - t0
        telemetry.inc("checkpoint.saves")
        telemetry.observe("checkpoint.save_s", secs)
        # progress beacon: a round spent fsyncing a huge checkpoint is
        # slow, not hung - the watchdog must not page on it
        telemetry.beacon("checkpoint.save")
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            nbytes = -1
        telemetry.event("checkpoint", op="save", round=counter,
                        path=path, secs=secs, bytes=nbytes)
        if barrier is not None:
            # pod-wide publish manifest: the checkpoint the pod agrees
            # on, stamped with the monotonically increasing pod epoch
            # (what a restarted/reshaped generation resumes from)
            from cxxnet_tpu.parallel.coordinator import file_sha256
            self._coordinator.publish(barrier, counter, path,
                                      file_sha256(path), nbytes)
        self._rotate_models(counter)
        if self.name_publish:
            # serving publish hook: atomic copy to the swap_watch'd
            # path AFTER the round file is durable - a live Server
            # sees complete checkpoints appear, never partial ones
            from cxxnet_tpu.nnet import checkpoint
            checkpoint.publish_model(path, self.name_publish)

    def _pod_barrier(self, counter: int):
        """One coordinated checkpoint barrier; a conviction exits this
        worker with RESHAPE_EXIT_CODE so the supervisor rolls the pod
        back to the published checkpoint and rebuilds it around the
        missing member (docs/FAULT_TOLERANCE.md "Elastic pod")."""
        from cxxnet_tpu.parallel.coordinator import PodReshapeRequired
        from cxxnet_tpu.utils.fault import RESHAPE_EXIT_CODE
        try:
            return self._coordinator.barrier(counter)
        except PodReshapeRequired as e:
            telemetry.stderr(
                f"elastic: {e}; exiting for pod reshape\n",
                event_kind="coord", op="reshape_exit", round=counter,
                missing=e.missing, dead=e.dead)
            sys.stderr.flush()
            sys.exit(RESHAPE_EXIT_CODE)

    def _rotate_models(self, saved: int) -> None:
        """keep_latest=k: bound the checkpoint set to the k newest
        %04d.model files (rescue.model and foreign files untouched).
        Counters past the one just saved are left alone: a stale
        higher-counter file (e.g. corrupt debris a resume walked back
        over) must not push fresh valid checkpoints out of the keep
        window - it is skipped by resume and overwritten in place when
        the counter catches up."""
        if self.keep_latest <= 0:
            return
        live = [c for c in self._model_counters() if c <= saved]
        for c in live[:-self.keep_latest]:
            try:
                os.remove(self._model_name(c))
            except OSError:
                pass  # concurrent cleanup / permissions: rotation is
                # best-effort, the save itself already succeeded

    def _save_rescue(self) -> str:
        """Final rescue checkpoint on divergence abort: the last good
        (rolled-back) params, in a file resume will not probe."""
        os.makedirs(self.name_model_dir, exist_ok=True)
        path = os.path.join(self.name_model_dir, "rescue.model")
        with atomic_writer(path) as fo:
            self.net_trainer.save_model(fo)
        return path

    # ------------------------------------------------------------------
    def _create_iterators(self) -> None:
        defcfg, train, evals, pred = self._split_blocks()
        if self.task in ("pred", "pred_raw", "extract", "serve"):
            if pred is not None:
                self.itr_pred = create_iterator(pred)
        else:
            if train is not None:
                self.itr_train = create_iterator(train)
            for evname, itcfg in evals:
                self.itr_evals.append(create_iterator(itcfg))
                self.eval_names.append(evname)

        def init_iter(it):
            for k, v in defcfg:
                it.set_param(k, v)
            # multi-controller: each worker feeds the batch rows its
            # devices OWN under the mesh (auto-wired unless the config
            # sets dist_num_worker explicitly). Mesh-aware: on a pure
            # data mesh that is batch/nproc rows from a per-worker data
            # shard; on a mesh whose batch dim is replicated across
            # processes (e.g. a cross-host 'seq' axis - the batch
            # splits over the sequence dim instead), every worker must
            # feed the SAME full batch, so no data shard is applied.
            import jax
            if jax.process_count() > 1:
                lb = self.net_trainer._local_batch
                it.set_param("batch_size", str(lb))
                nshard = self.batch_size // lb
                if nshard > 1 and not any(
                        k == "dist_num_worker" for k, _ in self.cfg):
                    shard = self.net_trainer._local_row_start // lb
                    it.set_param("dist_num_worker", str(nshard))
                    it.set_param("dist_worker_rank", str(shard))
            it.init()

        for it in filter(None, [self.itr_train, self.itr_pred]):
            init_iter(it)
        for it in self.itr_evals:
            init_iter(it)

    # ------------------------------------------------------------------
    def task_train(self) -> None:
        # monotonic: elapsed reporting must survive NTP step/slew of
        # the wall clock (a backwards jump under time.time() printed
        # negative/garbage durations)
        start = time.monotonic()
        if self.continue_training == 0 and self.name_model_in == "NULL":
            self._save_model()
        else:
            line = "".join(self.net_trainer.evaluate(it, name)
                           for it, name in zip(self.itr_evals,
                                               self.eval_names))
            telemetry.stderr(line + "\n", event_kind="eval",
                            round=self.start_counter - 1,
                            values=_eval_values(line))
            sys.stderr.flush()

        if self.itr_train is None:
            return
        if self.test_io:
            telemetry.stdout("start I/O test")
        cc = self.max_round
        try:
            self._train_rounds(cc, start)
        except DivergenceError:
            # abort, but not empty-handed: the state is the last good
            # (rolled-back) params - worth a rescue checkpoint
            path = self._save_rescue()
            telemetry.inc("fault.divergence_abort")
            telemetry.stderr(
                f"divergence guard: training aborted; rescue checkpoint "
                f"saved to {path}\n",
                event_kind="fault", type="divergence_abort",
                rescue=path)
            raise
        final_profile = self.net_trainer.profile_summary()
        if final_profile:
            telemetry.stderr(final_profile + "\n")
            sys.stderr.flush()
        if not self.silent:
            telemetry.stdout(
                f"\nupdating end, {int(time.monotonic() - start)} "
                "sec in all")

    def _train_rounds(self, cc: int, start: float) -> None:
        while self.start_counter <= self.num_round and cc > 0:
            cc -= 1
            if not self.silent:
                telemetry.stdout(f"update round {self.start_counter - 1}")
            telemetry.event("round_start", round=self.start_counter)
            sample_counter = 0
            self.net_trainer.start_round(self.start_counter)
            itr = self.itr_train
            prefetched = self.test_io == 0 and self.prefetch_stage > 0
            # fused dispatch (docs/PERFORMANCE.md): K batches per
            # jitted scan; test_io keeps per-batch accounting (it
            # measures the pipeline, nothing dispatches)
            fused_k = (self.steps_per_dispatch if self.test_io == 0
                       else 1)
            if prefetched:
                # stage batch k+1 (pad+cast+H2D) on a worker thread
                # while step k runs (io/prefetch.py); chunk=K makes
                # the worker assemble fused chunks; test_io keeps the
                # raw iterator - it measures the pipeline, not staging
                itr = self.net_trainer.prefetch(
                    itr, self.prefetch_stage, chunk=fused_k)
            pending = []  # fused, non-prefetched: batches awaiting K

            def tick(n_micro):
                # per-TRAINED-microstep progress accounting: fused
                # paths tick only after their chunk dispatched, so the
                # progress line never claims samples a failed chunk
                # would leave untrained (and K=1 keeps the historic
                # per-batch print cadence byte-for-byte)
                nonlocal sample_counter
                for _ in range(n_micro):
                    sample_counter += 1
                    if (sample_counter % self.print_step == 0
                            and not self.silent):
                        elapsed = int(time.monotonic() - start)
                        telemetry.stdout(
                            f"round {self.start_counter - 1:8d}:"
                            f"[{sample_counter:8d}] {elapsed} sec "
                            "elapsed")

            try:
                itr.before_first()
                while itr.next():
                    v = itr.value()
                    n_micro = 1
                    if self.test_io == 0:
                        if fused_k > 1 and not prefetched:
                            pending.append(v)
                            n_micro = 0
                            if len(pending) >= fused_k:
                                n_micro = len(pending)
                                self.net_trainer.update_chunk(pending)
                                pending = []
                        else:
                            # a StagedChunk (prefetched fused mode)
                            # routes to update_chunk inside update()
                            if isinstance(v, StagedChunk):
                                n_micro = v.n_steps
                            self.net_trainer.update(v)
                    tick(n_micro)
                if pending:
                    # round-boundary flush: the pass ended mid-chunk -
                    # a SHORT fused chunk trains the tail batches this
                    # round instead of silently dropping them
                    n_micro = len(pending)
                    self.net_trainer.update_chunk(pending)
                    pending = []
                    tick(n_micro)
            finally:
                if prefetched:
                    # an update() error mid-round must not leak the
                    # worker + its staged device batches
                    itr.close()
            self.net_trainer.finish_round_profile()
            stats = self.net_trainer.round_stats()
            round_label = self.start_counter
            if self.test_on_server:
                # CheckWeight_ analog (async_updater-inl.hpp:144-153):
                # every round, verify that replicated weights really are
                # identical on every device/process; abort on divergence
                bad = self.net_trainer.check_weights()
                if bad:
                    raise RuntimeError(
                        "test_on_server: weight consistency check "
                        "failed:\n" + "\n".join(bad))
            if self.test_io == 0:
                line = f"[{self.start_counter}]"
                if self.eval_train:
                    line += self.net_trainer.eval_train_metric()
                for it, name in zip(self.itr_evals, self.eval_names):
                    line += self.net_trainer.evaluate(it, name)
                # one write, same bytes as the historic piecewise
                # writes; the mirrored event carries the parsed values
                telemetry.stderr(line + "\n", event_kind="eval",
                                 round=self.start_counter,
                                 values=_eval_values(line))
                sys.stderr.flush()
            self._save_model()
            if stats is not None:
                # per-round throughput/latency record: one `round`
                # event on the log stream and one registry snapshot on
                # the metrics stream (what tools/metrics_report.py
                # tabulates). Emitted AFTER _save_model so the round's
                # own checkpoint save cost lands in its row, not the
                # next round's (_save_model already bumped
                # start_counter - round_label pins the finished round).
                telemetry.event("round", round=round_label, **stats)
                telemetry.emit_metrics(kind="round", round=round_label,
                                       **stats)

    def _calibration_source(self):
        """(iterator, name) behind `pass_calibration_iter` - "pred"
        (default), "train", or an eval block's name."""
        name = self.pass_calibration_iter
        if name in ("", "pred"):
            return self.itr_pred, "pred"
        if name == "train":
            return self.itr_train, "train"
        for it, nm in zip(self.itr_evals, self.eval_names):
            if nm == name:
                return it, nm
        raise ValueError(
            f"pass_calibration_iter={name!r}: no such iterator "
            f"(have: train, pred"
            + ("".join(", " + n for n in self.eval_names)) + ")")

    def _calibrate_passes(self) -> bool:
        """Explicit fold calibration (docs/GRAPH_PASSES.md): pull
        `pass_calibration_batches` batches from the named calibration
        iterator and average the frozen moments over them. A no-op -
        returning False so callers keep the pinned lazy
        first-inference-batch path - when nothing needs calibration,
        or when neither multi-batch nor an explicit iterator was
        requested."""
        tr = self.net_trainer
        if not tr.passes_need_calibration():
            return False
        n = self.pass_calibration_batches
        if n <= 1 and not self.pass_calibration_iter:
            return False
        import numpy as np
        from cxxnet_tpu.io.data import DataBatch
        it, src = self._calibration_source()
        assert it is not None, \
            f"pass_calibration_iter={src!r}: iterator not configured"
        batches = []
        it.before_first()
        while len(batches) < n and it.next():
            b = it.value()
            # iterators may reuse their batch buffers across next():
            # snapshot the arrays for the multi-batch moment pool
            batches.append(DataBatch(
                data=(None if b.data is None else np.array(b.data)),
                label=np.array(b.label),
                inst_index=(None if b.inst_index is None
                            else np.array(b.inst_index)),
                num_batch_padd=b.num_batch_padd,
                extra_data=[np.array(e) for e in b.extra_data],
                sparse_row_ptr=(None if b.sparse_row_ptr is None
                                else np.array(b.sparse_row_ptr)),
                sparse_findex=(None if b.sparse_findex is None
                               else np.array(b.sparse_findex)),
                sparse_fvalue=(None if b.sparse_fvalue is None
                               else np.array(b.sparse_fvalue))))
        it.before_first()
        if not batches:
            return False
        self.net_trainer.calibrate_graph_passes(
            batches if len(batches) > 1 else batches[0])
        telemetry.stdout(
            f"graph_passes: calibrated on {len(batches)} batch(es) "
            f"from the {src} iterator")
        return True

    def task_predict(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        self._calibrate_passes()
        telemetry.stdout("start predicting...")
        # tmp + os.replace: a crash mid-run cannot leave a truncated
        # prediction file behind (same protocol as checkpoint saves)
        with atomic_writer(self.name_pred, "w") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                pred = self.net_trainer.predict(batch)
                for v in pred:
                    fo.write(f"{v:g}\n")
        telemetry.stdout(
            f"finished prediction, write into {self.name_pred}")

    def task_predict_raw(self) -> None:
        """task=pred_raw: one line of raw top-node outputs (e.g. the
        full softmax probability row) per instance. The reference
        ACCEPTS this task when wiring iterators (cxxnet_main.cpp:242)
        but never dispatches it (:77-79), so its shipped
        kaggle_bowl/pred.conf silently did nothing; here it does what
        that conf intended."""
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        self._calibrate_passes()
        telemetry.stdout("start predicting...")
        with atomic_writer(self.name_pred, "w") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                # padding rows already trimmed (_forward_nodes keeps
                # mask.sum() rows, the reference's num_batch_padd trim)
                flat = self.net_trainer.predict_dist(batch)
                for row in flat:
                    fo.write(" ".join(f"{v:g}" for v in row) + "\n")
        telemetry.stdout(
            f"finished prediction, write into {self.name_pred}")

    def _serve_request_sizes(self):
        """Row count of each submitted request (task=serve load
        shape): serve_rows>0 = fixed; serve_rows=0 = a deterministic
        ragged cycle 1,2,3,5,7,... capped at the largest bucket, so a
        single pass exercises every bucket size (the serve-smoke CI
        job's mode)."""
        if self.serve_rows > 0:
            while True:
                yield self.serve_rows
        cycle = [1, 2, 3, 5, 7, 4, 6, 8]
        i = 0
        while True:
            yield cycle[i % len(cycle)]
            i += 1

    def task_serve(self) -> None:
        """task=serve: the continuous-batching server (docs/SERVING.md)
        warmed over its bucket executables, then the pred iterator
        replayed as a request stream - the CLI's serving surface and
        its own load generator. Output file matches task=pred line for
        line (the parity the serve-smoke CI job asserts)."""
        assert self.itr_pred is not None, \
            "must specify a predict iterator to drive task = serve"
        import numpy as np
        from cxxnet_tpu.serve import (
            QueueFullError, Server, predictions_from_rows)
        if (not self._calibrate_passes()
                and self.net_trainer.passes_need_calibration()):
            # fold_conv_bn needs statistics BEFORE the bucket
            # executables compile (they are frozen per Server): use
            # the first pred batch - the same source the predict
            # path calibrates from (docs/GRAPH_PASSES.md); the
            # explicit multi-batch/named-iterator path above takes
            # precedence when configured
            self.itr_pred.before_first()
            if self.itr_pred.next():
                self.net_trainer.calibrate_graph_passes(
                    self.itr_pred.value())
                telemetry.stdout(
                    "serve: calibrated graph passes on the first "
                    "pred batch")
        srv = Server(self.net_trainer)
        telemetry.stdout(
            f"serve: warming {len(srv.buckets)} bucket executables "
            f"{list(srv.buckets)}")
        srv.warmup()
        telemetry.stdout("serve: warmup done, start serving")
        import collections
        import signal
        import threading
        # graceful drain on SIGTERM (docs/SERVING.md "Connection
        # limits & drain"): the handler only flips an Event - the
        # serving loop notices it between submissions, stops feeding,
        # resolves everything already admitted, and exits 0 with the
        # output file complete for the rows served
        term = threading.Event()
        old_term = None
        try:
            old_term = signal.signal(
                signal.SIGTERM, lambda signum, frame: term.set())
        except ValueError:
            pass  # not the main thread (embedded run): no handler
        sizes = self._serve_request_sizes()
        t0 = time.monotonic()
        # bounded in-flight window: futures resolve in submission
        # order, so results drain to the output file DURING iteration
        # - task=pred streams in constant memory and task=serve must
        # too (an unbounded submit-then-drain would hold the whole
        # dataset's inputs and results in RAM)
        futures = collections.deque()
        max_inflight = 4 * srv.max_batch
        srv.start()
        try:
            with atomic_writer(self.name_pred, "w") as fo:
                def drain(down_to: int) -> None:
                    while len(futures) > down_to:
                        rows = futures.popleft().result()
                        for v in predictions_from_rows(rows):
                            fo.write(f"{v:g}\n")

                self.itr_pred.before_first()
                while not term.is_set() and self.itr_pred.next():
                    batch = self.itr_pred.value()
                    if batch.is_sparse():
                        c, y, x = self.net_trainer.net_cfg.input_shape
                        data = batch.to_dense(c * y * x).reshape(
                            batch.batch_size, c, y, x)
                    else:
                        data = np.asarray(batch.data)
                    valid = batch.batch_size - batch.num_batch_padd
                    data = data[:valid]
                    extras = [np.asarray(e)[:valid]
                              for e in batch.extra_data[
                                  :self.net_trainer.net_cfg
                                  .extra_data_num]]
                    lo = 0
                    while lo < valid and not term.is_set():
                        n = min(next(sizes), valid - lo)
                        try:
                            futures.append(srv.submit(
                                data[lo:lo + n],
                                [e[lo:lo + n] for e in extras]))
                        except QueueFullError as e:
                            # serve_queue_limit armed below the
                            # in-flight window: this driver is the
                            # well-behaved client - honor the advice,
                            # drain, resubmit (no row may drop; the
                            # output must stay line-for-line pred)
                            drain(max_inflight // 2)
                            time.sleep(min(e.retry_after_s, 0.5))
                            continue
                        lo += n
                        drain(max_inflight)
                # reached on completion AND on SIGTERM: every future
                # already admitted resolves into the output file -
                # zero drops of admitted work either way
                drain(0)
        finally:
            if old_term is not None:
                signal.signal(signal.SIGTERM, old_term)
            if term.is_set():
                telemetry.stdout(
                    "serve: SIGTERM - draining queued requests")
                stats = srv.drain()
            else:
                stats = srv.stop()
        dt = time.monotonic() - t0
        qps = stats["requests"] / dt if dt > 0 else 0.0
        telemetry.stdout(
            f"serve: {stats['requests']} requests ({stats['rows']} "
            f"rows) in {dt:.2f} sec, {qps:.1f} req/s, "
            f"p50 {stats['latency_p50_ms']} ms, "
            f"p99 {stats['latency_p99_ms']} ms, "
            f"{stats['padding_rows']} padding rows over "
            f"{stats['batches']} batches")
        telemetry.event("serve", op="summary", secs=dt, qps=qps, **{
            k: v for k, v in stats.items() if not isinstance(v, dict)})
        telemetry.emit_metrics(kind="serve")
        telemetry.stdout(
            f"finished serving, write into {self.name_pred}")

    def task_extract_feature(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        assert self.extract_node_name, \
            "extract node name must be specified in task extract"
        self._calibrate_passes()
        telemetry.stdout("start predicting...")
        nrow = 0
        dshape = None
        mode = "w" if self.output_format else "wb"
        with atomic_writer(self.name_pred, mode) as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                feat = self.net_trainer.extract_feature(
                    batch, self.extract_node_name)
                nrow += feat.shape[0]
                dshape = feat.shape[1:]
                flat = feat.reshape(feat.shape[0], -1)
                if self.output_format:
                    for row in flat:
                        fo.write(" ".join(f"{v:g}" for v in row) + "\n")
                else:
                    flat.astype("float32").tofile(fo)
            if dshape is None:
                # raising inside the atomic_writer discards the tmp, so
                # no empty artifact appears (and a pre-existing output
                # from an earlier run is left untouched)
                raise ValueError(
                    "task=extract: the pred iterator yielded no data "
                    "(empty list file or dataset smaller than one batch)")
        with atomic_writer(self.name_pred + ".meta", "w") as fm:
            fm.write(f"{nrow},{dshape[0]},{dshape[1]},{dshape[2]}\n")
        telemetry.stdout(
            f"finished prediction, write into {self.name_pred}")


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    return LearnTask().run(argv)


if __name__ == "__main__":
    sys.exit(main())
