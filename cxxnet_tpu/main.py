"""CLI task driver.

Behavior parity with CXXNetLearnTask (src/cxxnet_main.cpp:16-478):

    python -m cxxnet_tpu.main <config.conf> [k=v ...]

- tasks: train (default) / finetune / pred / extract
- `continue = 1` resumes from the newest `model_dir/%04d.model`
- per-round checkpoints gated by `save_model` period
- eval metrics printed per round to stderr as
  `[round]\\ttrain-metric:x\\tevalname-metric:y`
- `test_io = 1` drives the full data pipeline with Update skipped
- `pred = file` + task=pred writes one prediction per line;
  task=extract with `extract_node_name` dumps features (+ .meta)
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_file


class LearnTask:
    def __init__(self) -> None:
        self.task = "train"
        self.net_type = 0
        self.net_trainer: Optional[NetTrainer] = None
        self.itr_train = None
        self.itr_pred = None
        self.itr_evals = []
        self.eval_names: List[str] = []
        self.name_model_dir = "models"
        self.num_round = 10
        self.test_io = 0
        self.batch_size = 0
        self.silent = 0
        self.start_counter = 0
        self.max_round = 1 << 31
        self.continue_training = 0
        self.save_period = 1
        self.name_model_in = "NULL"
        self.name_pred = "pred.txt"
        self.print_step = 100
        self.extract_node_name = ""
        self.output_format = 1
        self.device = "tpu"
        self.eval_train = 1
        self.test_on_server = 0
        self.cfg: List[Tuple[str, str]] = []

    # ------------------------------------------------------------------
    def run(self, argv: List[str]) -> int:
        if len(argv) < 1:
            print("Usage: <config> [k=v ...]")
            return 0
        for name, val in parse_config_file(argv[0]):
            self.set_param(name, val)
        for arg in argv[1:]:
            if "=" in arg:
                name, val = arg.split("=", 1)
                self.set_param(name.strip(), val.strip())
        # an explicit JAX_PLATFORMS env always beats the conf's `dev`
        # kind (which is advisory - parallel/mesh.py): without this, a
        # `dev = tpu` conf run under JAX_PLATFORMS=cpu still initializes
        # every registered plugin and can hang on an absent tunnel
        from cxxnet_tpu.utils.platform import ensure_env_platform
        ensure_env_platform()
        if self.device.split(":")[0] == "cpu":
            # honor `dev = cpu` before any backend is touched: skip
            # accelerator-platform init entirely (matters when the TPU
            # tunnel is absent/unreachable - the CLI must still work)
            import jax
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass  # backend already initialized
        self.init()
        if not self.silent:
            print("initializing end, start working")
        if self.task in ("train", "finetune"):
            self.task_train()
        elif self.task == "pred":
            self.task_predict()
        elif self.task == "extract":
            self.task_extract_feature()
        else:
            raise ValueError(f"unknown task {self.task}")
        return 0

    def set_param(self, name: str, val: str) -> None:
        if val == "default":
            return
        if name == "net_type":
            self.net_type = int(val)
        if name == "print_step":
            self.print_step = int(val)
        if name == "continue":
            self.continue_training = int(val)
        if name == "save_model":
            self.save_period = int(val)
        if name == "start_counter":
            self.start_counter = int(val)
        if name == "model_in":
            self.name_model_in = val
        if name == "model_dir":
            self.name_model_dir = val
        if name == "num_round":
            self.num_round = int(val)
        if name == "max_round":
            self.max_round = int(val)
        if name == "silent":
            self.silent = int(val)
        if name == "task":
            self.task = val
        if name == "dev":
            self.device = val
        if name == "test_io":
            self.test_io = int(val)
        if name == "batch_size":
            self.batch_size = int(val)
        if name == "eval_train":
            self.eval_train = int(val)
        if name == "test_on_server":
            self.test_on_server = int(val)
        if name == "extract_node_name":
            self.extract_node_name = val
        if name == "output_format":
            self.output_format = 1 if val == "txt" else 0
        self.cfg.append((name, val))

    # ------------------------------------------------------------------
    def _create_net(self) -> NetTrainer:
        """Build the trainer from the global + TRAIN-data sections.

        The reference feeds every conf line to every component; we keep
        that for the global and data sections but EXCLUDE eval/pred
        iterator blocks: their keys are iterator-scoped (an eval block
        without rand_crop must not clobber the train block's
        device_augment crop spec - the blocks appear later in the file,
        so a flat last-writer-wins scan would take the eval values)."""
        net = NetTrainer()
        flag = 0
        for k, v in self.cfg:
            if k == "data":
                flag = 1
                continue
            if k in ("eval", "pred"):
                flag = 2
                continue
            if k == "iter" and v == "end":
                flag = 0
                continue
            if flag != 2:
                net.set_param(k, v)
        return net

    def init(self) -> None:
        # param_server=dist: join the multi-controller job up front so
        # every later path (model load, iterators, mesh) sees the global
        # device view (idempotent; trainer.init_model also calls it)
        from cxxnet_tpu.parallel import distributed
        distributed.init_from_config(self.cfg)
        if self.task == "train" and self.continue_training:
            if self._sync_latest_model():
                print(f"Init: Continue training from round "
                      f"{self.start_counter}")
                self._create_iterators()
                return
            # reference aborts here (cxxnet_main.cpp:109-113)
            raise FileNotFoundError(
                "Init: cannot find models for continue training; "
                "specify model_in instead")
        if self.name_model_in == "NULL":
            assert self.task == "train", \
                "must specify model_in if not training"
            self.net_trainer = self._create_net()
            self.net_trainer.init_model()
        elif self.task == "finetune":
            self._copy_model()
        else:
            self._load_model()
        self._create_iterators()

    def _model_name(self, counter: int) -> str:
        return os.path.join(self.name_model_dir, f"{counter:04d}.model")

    def _sync_latest_model(self) -> bool:
        """Probe model_dir/%04d.model ascending, load the newest."""
        s = self.start_counter
        last = None
        while os.path.exists(self._model_name(s)):
            last = self._model_name(s)
            s += 1
        if last is None:
            return False
        self.net_trainer = self._create_net()
        with open(last, "rb") as fi:
            self.net_trainer.load_model(fi)
        self.start_counter = s
        return True

    def _load_model(self) -> None:
        base = os.path.basename(self.name_model_in)
        try:
            self.start_counter = int(base.split(".")[0])
        except ValueError:
            print("WARNING: cannot infer start_counter from model name.")
        self.net_trainer = self._create_net()
        with open(self.name_model_in, "rb") as fi:
            self.net_trainer.load_model(fi)
        self.start_counter += 1

    def _copy_model(self) -> None:
        self.net_trainer = self._create_net()
        self.net_trainer.init_model()
        with open(self.name_model_in, "rb") as fi:
            self.net_trainer.copy_model_from(fi)

    def _save_model(self) -> None:
        # quirk parity: the modulo check uses the POST-incremented counter
        # (cxxnet_main.cpp:173-176), so with save_model=k the rounds saved
        # are k-1, 2k-1, ... — e.g. save_model=num_round=15 writes only
        # 0014.model. Kept so round numbering matches the reference.
        counter = self.start_counter
        self.start_counter += 1
        if self.save_period == 0 or self.start_counter % self.save_period:
            return
        os.makedirs(self.name_model_dir, exist_ok=True)
        with open(self._model_name(counter), "wb") as fo:
            self.net_trainer.save_model(fo)

    # ------------------------------------------------------------------
    def _create_iterators(self) -> None:
        flag = 0
        evname = ""
        itcfg: List[Tuple[str, str]] = []
        defcfg: List[Tuple[str, str]] = []
        for name, val in self.cfg:
            if name == "data":
                flag = 1
                continue
            if name == "eval":
                evname = val
                flag = 2
                continue
            if name == "pred":
                flag = 3
                self.name_pred = val
                continue
            if name == "iter" and val == "end":
                assert flag != 0, "wrong configuration file"
                if flag == 1 and self.task not in ("pred", "extract"):
                    assert self.itr_train is None, "can only have one data"
                    self.itr_train = create_iterator(itcfg)
                if flag == 2 and self.task not in ("pred", "extract"):
                    self.itr_evals.append(create_iterator(itcfg))
                    self.eval_names.append(evname)
                if flag == 3 and self.task in ("pred", "extract"):
                    assert self.itr_pred is None, \
                        "can only have one data:test"
                    self.itr_pred = create_iterator(itcfg)
                flag = 0
                itcfg = []
                continue
            if flag == 0:
                defcfg.append((name, val))
            else:
                itcfg.append((name, val))

        def init_iter(it):
            for k, v in defcfg:
                it.set_param(k, v)
            # multi-controller: each worker feeds the batch rows its
            # devices OWN under the mesh (auto-wired unless the config
            # sets dist_num_worker explicitly). Mesh-aware: on a pure
            # data mesh that is batch/nproc rows from a per-worker data
            # shard; on a mesh whose batch dim is replicated across
            # processes (e.g. a cross-host 'seq' axis - the batch
            # splits over the sequence dim instead), every worker must
            # feed the SAME full batch, so no data shard is applied.
            import jax
            if jax.process_count() > 1:
                lb = self.net_trainer._local_batch
                it.set_param("batch_size", str(lb))
                nshard = self.batch_size // lb
                if nshard > 1 and not any(
                        k == "dist_num_worker" for k, _ in self.cfg):
                    shard = self.net_trainer._local_row_start // lb
                    it.set_param("dist_num_worker", str(nshard))
                    it.set_param("dist_worker_rank", str(shard))
            it.init()

        for it in filter(None, [self.itr_train, self.itr_pred]):
            init_iter(it)
        for it in self.itr_evals:
            init_iter(it)

    # ------------------------------------------------------------------
    def task_train(self) -> None:
        start = time.time()
        if self.continue_training == 0 and self.name_model_in == "NULL":
            self._save_model()
        else:
            for it, name in zip(self.itr_evals, self.eval_names):
                sys.stderr.write(self.net_trainer.evaluate(it, name))
            sys.stderr.write("\n")
            sys.stderr.flush()

        if self.itr_train is None:
            return
        if self.test_io:
            print("start I/O test")
        cc = self.max_round
        while self.start_counter <= self.num_round and cc > 0:
            cc -= 1
            if not self.silent:
                print(f"update round {self.start_counter - 1}")
            sample_counter = 0
            self.net_trainer.start_round(self.start_counter)
            self.itr_train.before_first()
            while self.itr_train.next():
                if self.test_io == 0:
                    self.net_trainer.update(self.itr_train.value())
                sample_counter += 1
                if sample_counter % self.print_step == 0 and not self.silent:
                    elapsed = int(time.time() - start)
                    print(f"round {self.start_counter - 1:8d}:"
                          f"[{sample_counter:8d}] {elapsed} sec elapsed")
            self.net_trainer.finish_round_profile()
            if self.test_on_server:
                # CheckWeight_ analog (async_updater-inl.hpp:144-153):
                # every round, verify that replicated weights really are
                # identical on every device/process; abort on divergence
                bad = self.net_trainer.check_weights()
                if bad:
                    raise RuntimeError(
                        "test_on_server: weight consistency check "
                        "failed:\n" + "\n".join(bad))
            if self.test_io == 0:
                sys.stderr.write(f"[{self.start_counter}]")
                if self.eval_train:
                    sys.stderr.write(
                        self.net_trainer.eval_train_metric())
                for it, name in zip(self.itr_evals, self.eval_names):
                    sys.stderr.write(self.net_trainer.evaluate(it, name))
                sys.stderr.write("\n")
                sys.stderr.flush()
            self._save_model()
        final_profile = self.net_trainer.profile_summary()
        if final_profile:
            sys.stderr.write(final_profile + "\n")
            sys.stderr.flush()
        if not self.silent:
            print(f"\nupdating end, {int(time.time() - start)} sec in all")

    def task_predict(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        print("start predicting...")
        with open(self.name_pred, "w") as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                pred = self.net_trainer.predict(batch)
                for v in pred:
                    fo.write(f"{v:g}\n")
        print(f"finished prediction, write into {self.name_pred}")

    def task_extract_feature(self) -> None:
        assert self.itr_pred is not None, \
            "must specify a predict iterator to generate predictions"
        assert self.extract_node_name, \
            "extract node name must be specified in task extract"
        print("start predicting...")
        nrow = 0
        dshape = None
        mode = "w" if self.output_format else "wb"
        with open(self.name_pred, mode) as fo:
            self.itr_pred.before_first()
            while self.itr_pred.next():
                batch = self.itr_pred.value()
                feat = self.net_trainer.extract_feature(
                    batch, self.extract_node_name)
                nrow += feat.shape[0]
                dshape = feat.shape[1:]
                flat = feat.reshape(feat.shape[0], -1)
                if self.output_format:
                    for row in flat:
                        fo.write(" ".join(f"{v:g}" for v in row) + "\n")
                else:
                    flat.astype("float32").tofile(fo)
        if dshape is None:
            os.remove(self.name_pred)  # no stale empty artifact
            raise ValueError(
                "task=extract: the pred iterator yielded no data "
                "(empty list file or dataset smaller than one batch)")
        with open(self.name_pred + ".meta", "w") as fm:
            fm.write(f"{nrow},{dshape[0]},{dshape[1]},{dshape[2]}\n")
        print(f"finished prediction, write into {self.name_pred}")


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    return LearnTask().run(argv)


if __name__ == "__main__":
    sys.exit(main())
