"""graftlint tier 1: framework-aware AST lint (stdlib only, no jax).

Rules (stable ids - the waiver/CI contract; docs/STATIC_ANALYSIS.md):

- **GL001 rng-key-reuse**: a ``PRNGKey``/``fold_in`` result consumed
  by two jax.random sampling calls without a new fold/split between
  them - the two draws are IDENTICAL, the classic silent-correlation
  bug the (seed, step_counter) stream discipline exists to prevent.
- **GL002 host-sync-in-hot-path**: ``float()`` / ``int()`` /
  ``np.asarray`` / ``.item()`` / ``block_until_ready`` /
  ``device_get`` inside a jit-traced function (a trace-time error or
  a constant-folding trap) or a ``# graftlint: hot-path`` marked
  function (a device sync that serializes async dispatch - every one
  must be deliberate and waived with its reason).
- **GL003 tracer-branch**: Python ``if``/``while`` branching on a
  value derived from a jit-traced function's arguments (tracers) -
  trace-time error, or silent specialization via weak typing. Static
  projections (``.shape``/``.ndim``/``.dtype``/``len()``/
  ``isinstance``) are exempt.
- **GL004 wallclock-duration**: ``time.time()`` - durations must use
  ``time.monotonic()`` (NTP step/slew makes wall-clock deltas lie);
  genuine wall-clock TIMESTAMPS carry a waiver naming that purpose.
- **GL005 donated-arg-reuse**: an argument passed in a
  ``donate_argnums`` position of a jitted callable is read again
  before being reassigned - donation hands XLA the buffer; the read
  sees freed/aliased memory (jax only *warns*, at runtime, sometimes).
- **GL006 unknown-config-key**: a string-literal subscript or
  ``.get`` on a cfg-like dict whose key the config schema registry
  (schema.py) does not recognize - a typo'd key silently reads the
  default forever.
- **GL008 metric-name-style**: a string-literal metric/beacon name
  passed to a telemetry instrument call (``telemetry.inc`` /
  ``set_gauge`` / ``observe`` / ``span`` / ``counter`` / ``gauge`` /
  ``histogram`` / ``beacon``) that does not match the dotted-lowercase
  grammar ``[a-z0-9_]+(\\.[a-z0-9_]+)+`` - the registry creates
  instruments on first use, so a typo'd or off-grammar name silently
  opens a PARALLEL series every dashboard and alert rule misses.
- **GL007 unsharded-large-intermediate**: a jit-traced function in a
  mesh-aware module (one importing Mesh/NamedSharding/PartitionSpec
  or the parallel package) allocates a weight-tree-sized temporary -
  ``zeros_like``/``ones_like``/``full_like``/``empty_like`` on a
  params/grads/state tree, directly or as the mapped function of a
  ``tree.map`` - without a sharding constraint on the same statement.
  Under a multi-device mesh such a temporary materializes FULLY
  REPLICATED on every device unless its layout is pinned (by
  ``with_sharding_constraint``, or structurally by the jit's
  out_shardings/donation - which is what a waiver documents): the
  exact accidental-full-materialization the ZeRO stages exist to
  remove (docs/parallel.md).
- **GL010..GL016 concurrency tier** (docs/STATIC_ANALYSIS.md
  "Concurrency analysis"): lock-discipline rules over the runtime's
  threading surface - bare ``.acquire()`` outside ``with``/
  try-finally (GL010), ``threading.Thread`` that never sets
  ``daemon=`` (GL011), a thread target/``run`` method writing
  instance or module state with no lock in scope (GL012),
  ``.join()`` with no timeout on a thread (GL013), ``Condition.wait``
  not wrapped in a predicate ``while`` loop (GL014), blocking calls
  (``queue.get`` / ``accept`` / un-timeouted ``wait`` / ``sleep`` /
  subprocess waits) made while a lock is held (GL015), and the
  ``# guarded-by: <lock>`` annotation convention - every write to an
  annotated attribute must sit inside a ``with <lock>`` block in the
  same function (GL016). The runtime half of the tier is
  ``analysis/lock_audit.py``.
- **GL090 bad-waiver**: a waiver without a reason, or naming an
  unknown rule id. Waivers are documentation; undocumented ones are
  findings themselves.
- **GL091 unused-waiver**: a waiver that suppressed nothing - stale
  after the code it excused was fixed; delete it.

Waiver syntax, per line::

    x = time.time()  # graftlint: disable=GL004 epoch timestamp
    # graftlint: disable=GL002,GL005 readback is the guard's cost
    ok = bool(np.asarray(flag))

(a standalone waiver comment applies to the next line). Functions are
marked hot-path with ``# graftlint: hot-path`` on the ``def`` line or
the line above.
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cxxnet_tpu.analysis import schema

RULES: Dict[str, str] = {
    "GL001": "rng-key-reuse",
    "GL002": "host-sync-in-hot-path",
    "GL003": "tracer-branch",
    "GL004": "wallclock-duration",
    "GL005": "donated-arg-reuse",
    "GL006": "unknown-config-key",
    "GL007": "unsharded-large-intermediate",
    "GL008": "metric-name-style",
    "GL010": "bare-acquire",
    "GL011": "thread-daemon-missing",
    "GL012": "unlocked-thread-shared-write",
    "GL013": "join-no-timeout",
    "GL014": "condition-wait-no-predicate",
    "GL015": "blocking-call-under-lock",
    "GL016": "guarded-by-violation",
    "GL090": "bad-waiver",
    "GL091": "unused-waiver",
}

# the GL01x subset: the concurrency tier the CI `concurrency-audit`
# job gates on (together with waiver hygiene, which cannot be waived)
CONCURRENCY_RULES = ("GL010", "GL011", "GL012", "GL013", "GL014",
                     "GL015", "GL016")

_WAIVE_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_,\s]*?)(?:\s+(.*))?$")
_HOT_RE = re.compile(r"graftlint:\s*hot-path\b")
# the guarded-by annotation grammar (docs/STATIC_ANALYSIS.md): names
# the lock expression protecting the attribute whose initialization
# the comment sits on (or above) - `self._lock`, or a bare module
# lock name
_GUARDED_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

# jax.random calls that CONSUME a key (one draw per key). fold_in /
# split / PRNGKey / key / key_data DERIVE - deriving twice is the
# sanctioned pattern, drawing twice is the bug.
_SAMPLERS = frozenset({
    "uniform", "normal", "bernoulli", "randint", "permutation",
    "shuffle", "categorical", "gumbel", "truncated_normal", "beta",
    "gamma", "dirichlet", "choice", "exponential", "laplace",
    "logistic", "poisson", "rademacher", "cauchy", "maxwell",
    "bits", "ball", "orthogonal", "t", "loggamma", "binomial",
})
_KEY_MAKERS = frozenset({"PRNGKey", "fold_in", "key"})

# attribute projections of a tracer that are static at trace time
_STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "aval", "sharding", "weak_type",
})
_STATIC_CALLS = frozenset({"len", "isinstance", "type", "callable"})

_SYNC_METHODS = frozenset({"item", "block_until_ready"})
_NP_NAMES = frozenset({"np", "numpy", "onp"})
_CAST_BUILTINS = frozenset({"float", "int", "bool"})

# GL007: allocators that clone a (possibly weight-sized) layout, and
# the value names that mark a tree as weight-sized. Mesh-awareness is
# per MODULE (imports of the sharding machinery) - a mesh-less module
# cannot replicate anything across devices.
_ALLOCATORS = frozenset({"zeros_like", "ones_like", "full_like",
                         "empty_like"})
_WEIGHTY_RE = re.compile(
    r"param|grad|accum|ustate|state|weight|moment", re.IGNORECASE)
_MESH_IMPORT_NAMES = frozenset({"Mesh", "NamedSharding",
                                "PartitionSpec", "shard_map"})


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    waived: bool = False
    reason: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule, "name": RULES.get(self.rule, ""),
            "path": self.path, "line": self.line, "col": self.col,
            "message": self.message, "waived": self.waived,
            "reason": self.reason,
        }


@dataclass
class _Waiver:
    rules: List[str]
    reason: str
    src_line: int      # where the comment sits
    target_line: int   # the line it excuses
    used: bool = False


@dataclass
class _FileCtx:
    path: str
    rel: str
    tree: ast.AST
    mesh_aware: bool = False
    waivers: List[_Waiver] = field(default_factory=list)
    hot_lines: Set[int] = field(default_factory=set)
    # raw `# guarded-by:` notes: (target_line, lock_text, comment_line)
    guard_notes: List[Tuple[int, str, int]] = field(
        default_factory=list)
    jitted: Set[str] = field(default_factory=set)
    donated: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=node.lineno,
            col=node.col_offset, message=message))


# ---------------------------------------------------------------------------
# comments: waivers + hot-path markers
# ---------------------------------------------------------------------------
def _scan_comments(ctx: _FileCtx, source: str) -> None:
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return
    lines = source.splitlines()
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        line_no = tok.start[0]
        before = lines[line_no - 1][:tok.start[1]]
        standalone = not before.strip()
        # a standalone waiver/marker comment applies to the NEXT line
        target = line_no + 1 if standalone else line_no
        if _HOT_RE.search(tok.string):
            ctx.hot_lines.add(target)
            continue
        g = _GUARDED_RE.search(tok.string)
        if g:
            ctx.guard_notes.append((target, g.group(1), line_no))
            continue
        m = _WAIVE_RE.search(tok.string)
        if not m:
            continue
        ids = [r.strip() for r in m.group(1).split(",") if r.strip()]
        reason = (m.group(2) or "").strip()
        w = _Waiver(rules=ids, reason=reason, src_line=line_no,
                    target_line=target)
        ctx.waivers.append(w)
        bad = [r for r in ids if r not in RULES]
        if bad or not ids:
            ctx.findings.append(Finding(
                "GL090", ctx.rel, line_no, tok.start[1],
                f"waiver names unknown rule id(s) {bad or ids}"))
        elif not reason:
            ctx.findings.append(Finding(
                "GL090", ctx.rel, line_no, tok.start[1],
                f"waiver for {','.join(ids)} has no reason - say why "
                "the finding is intended"))


def _apply_waivers(ctx: _FileCtx) -> None:
    for f in ctx.findings:
        if f.rule in ("GL090", "GL091"):
            continue  # waiver hygiene cannot be waived away
        for w in ctx.waivers:
            if f.line == w.target_line and f.rule in w.rules:
                f.waived, f.reason = True, w.reason
                w.used = True
                break
    for w in ctx.waivers:
        if not w.used and all(r in RULES for r in w.rules) and w.rules:
            ctx.findings.append(Finding(
                "GL091", ctx.rel, w.src_line, 0,
                f"waiver for {','.join(w.rules)} suppresses nothing - "
                "stale, delete it"))


# ---------------------------------------------------------------------------
# module pass: jitted function names + donated-arg registry
# ---------------------------------------------------------------------------
def _last_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_jit_call(call: ast.Call) -> bool:
    return _last_name(call.func) == "jit"


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
    return ()


def _module_pass(ctx: _FileCtx) -> None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if ("sharding" in mod or "parallel" in mod
                    or any(al.name in _MESH_IMPORT_NAMES
                           for al in node.names)):
                ctx.mesh_aware = True
        elif isinstance(node, ast.Import):
            if any("sharding" in al.name or "parallel" in al.name
                   for al in node.names):
                ctx.mesh_aware = True
        if isinstance(node, ast.Call) and _is_jit_call(node):
            if node.args and isinstance(node.args[0], ast.Name):
                ctx.jitted.add(node.args[0].id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if _last_name(d) == "jit":
                    ctx.jitted.add(node.name)
        elif isinstance(node, ast.Assign):
            v = node.value
            if not (isinstance(v, ast.Call) and _is_jit_call(v)):
                continue
            donate = _donate_positions(v)
            if not donate:
                continue
            for tgt in node.targets:
                name = _last_name(tgt) if isinstance(
                    tgt, (ast.Name, ast.Attribute)) else ""
                if name:
                    ctx.donated[name] = donate


# ---------------------------------------------------------------------------
# expression helpers
# ---------------------------------------------------------------------------
def _walk_no_funcs(node: ast.AST):
    """ast.walk that does not descend into nested def/lambda (each
    function is analyzed in its own visit, with its own scope)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _dynamic_names(expr: ast.expr) -> Set[str]:
    """Names whose runtime VALUE the expression depends on - name
    loads not shielded by a static projection (.shape, len(), ...)."""
    if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.In, ast.NotIn)) for op in expr.ops):
        # `k in params` on a pytree dict tests static KEYS, not
        # values - only the left operand's value matters
        return _dynamic_names(expr.left)
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return set()
        return _dynamic_names(expr.value)
    if isinstance(expr, ast.Call):
        if (isinstance(expr.func, ast.Name)
                and expr.func.id in _STATIC_CALLS):
            return set()
        out: Set[str] = set()
        for child in ast.iter_child_nodes(expr):
            if child is not expr.func:
                out |= _dynamic_names(child)
        out |= _dynamic_names(expr.func)
        return out
    if isinstance(expr, ast.Name):
        return {expr.id} if isinstance(expr.ctx, ast.Load) else set()
    if isinstance(expr, (ast.Lambda, ast.FunctionDef)):
        return set()
    out = set()
    for child in ast.iter_child_nodes(expr):
        if isinstance(child, ast.expr):
            out |= _dynamic_names(child)
        elif isinstance(child, ast.AST):
            for sub in ast.walk(child):
                if isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, ast.Load):
                    out.add(sub.id)
    return out


def _assigned_names(target: ast.expr) -> Set[str]:
    out = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
    return out


def _expr_text(e: ast.expr) -> str:
    try:
        return ast.unparse(e)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return ""


# ---------------------------------------------------------------------------
# GL001 rng-key-reuse
# ---------------------------------------------------------------------------
def _rule_rng_reuse(ctx: _FileCtx, fn: ast.AST) -> None:
    # key var -> times consumed since last (re)derivation
    consumed: Dict[str, int] = {}

    def scan_expr(e: ast.expr) -> None:
        for n in _walk_no_funcs_inclusive(e):
            if not isinstance(n, ast.Call):
                continue
            name = _last_name(n.func)
            if name not in _SAMPLERS:
                continue
            args = list(n.args) + [kw.value for kw in n.keywords]
            for a in args:
                if (isinstance(a, ast.Name) and a.id in consumed):
                    consumed[a.id] += 1
                    if consumed[a.id] == 2:
                        ctx.emit(
                            "GL001", n,
                            f"rng key '{a.id}' consumed twice "
                            f"without a new fold_in/split - the two "
                            f"draws are identical")

    def scan_stmts(stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Assign):
                scan_expr(st.value)
                tgts = set()
                for t in st.targets:
                    tgts |= _assigned_names(t)
                maker = (isinstance(st.value, ast.Call)
                         and _last_name(st.value.func) in _KEY_MAKERS)
                for t in tgts:
                    if maker and len(tgts) == 1:
                        consumed[t] = 0       # fresh key
                    else:
                        consumed.pop(t, None)  # reassigned to non-key
                continue
            if isinstance(st, (ast.AugAssign, ast.AnnAssign)):
                if st.value is not None:
                    scan_expr(st.value)
                consumed.pop(
                    next(iter(_assigned_names(st.target)), ""), None)
                continue
            if isinstance(st, (ast.If, ast.While)):
                scan_expr(st.test)
                snap = dict(consumed)
                scan_stmts(st.body)
                after_body = dict(consumed)
                consumed.clear()
                consumed.update(snap)
                scan_stmts(st.orelse)
                for k, v in after_body.items():
                    if k in consumed:
                        consumed[k] = max(consumed[k], v)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                scan_expr(st.iter)
                for t in _assigned_names(st.target):
                    consumed.pop(t, None)
                scan_stmts(st.body)
                scan_stmts(st.orelse)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    scan_expr(item.context_expr)
                scan_stmts(st.body)
                continue
            if isinstance(st, ast.Try):
                scan_stmts(st.body)
                for h in st.handlers:
                    scan_stmts(h.body)
                scan_stmts(st.orelse)
                scan_stmts(st.finalbody)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    scan_expr(child)

    body = fn.body if isinstance(
        fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else []
    scan_stmts(body)


def _walk_no_funcs_inclusive(node: ast.AST):
    yield node
    yield from _walk_no_funcs(node)


# ---------------------------------------------------------------------------
# GL002 host-sync-in-hot-path
# ---------------------------------------------------------------------------
def _rule_host_sync(ctx: _FileCtx, fn: ast.AST, kind: str) -> None:
    fname = getattr(fn, "name", "<lambda>")
    for n in _walk_no_funcs(fn):
        if not isinstance(n, ast.Call):
            continue
        func = n.func
        what = ""
        if (isinstance(func, ast.Name)
                and func.id in _CAST_BUILTINS and len(n.args) == 1
                and not isinstance(n.args[0], ast.Constant)):
            # hot-path (plain python) functions: a cast of a bare
            # name/attr/subscript is host arithmetic, not a readback -
            # only casts of a COMPUTED value (the float(np.asarray(
            # fetch_local(x))) shape) sync. Under jit every cast of a
            # tracer is a trace-time error, so all of them flag.
            if kind == "hot-path" and not any(
                    isinstance(sub, ast.Call)
                    for sub in ast.walk(n.args[0])):
                what = ""
            else:
                what = f"{func.id}()"
        elif isinstance(func, ast.Attribute):
            if func.attr in _SYNC_METHODS:
                what = f".{func.attr}()"
            elif (func.attr in ("asarray", "array")
                  and isinstance(func.value, ast.Name)
                  and func.value.id in _NP_NAMES):
                what = f"{func.value.id}.{func.attr}()"
            elif func.attr == "device_get":
                what = "device_get()"
        if what:
            ctx.emit(
                "GL002", n,
                f"{what} in {kind} function '{fname}' forces a host "
                f"sync (or a trace-time error under jit)")


# ---------------------------------------------------------------------------
# GL003 tracer-branch (jit-traced functions only)
# ---------------------------------------------------------------------------
def _rule_tracer_branch(ctx: _FileCtx, fn: ast.AST) -> None:
    a = fn.args
    tainted: Set[str] = {x.arg for x in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs))}
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            tainted.add(extra.arg)

    def scan(stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Assign):
                dyn = _dynamic_names(st.value) & tainted
                for t in st.targets:
                    for name in _assigned_names(t):
                        if dyn:
                            tainted.add(name)
                        else:
                            tainted.discard(name)
            elif isinstance(st, (ast.If, ast.While)):
                hits = _dynamic_names(st.test) & tainted
                if hits:
                    kw = ("while" if isinstance(st, ast.While)
                          else "if")
                    ctx.emit(
                        "GL003", st,
                        f"python `{kw}` branches on traced value(s) "
                        f"{sorted(hits)} inside jit-traced function "
                        f"'{fn.name}' - use lax.cond/lax.while_loop "
                        f"(or a static .shape/.dtype test)")
                scan(st.body)
                scan(st.orelse)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                for name in _assigned_names(st.target):
                    if _dynamic_names(st.iter) & tainted:
                        tainted.add(name)
                scan(st.body)
                scan(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                scan(st.body)
            elif isinstance(st, ast.Try):
                scan(st.body)
                for h in st.handlers:
                    scan(h.body)
                scan(st.orelse)
                scan(st.finalbody)

    scan(fn.body)


# ---------------------------------------------------------------------------
# GL004 wallclock-duration
# ---------------------------------------------------------------------------
def _rule_wallclock(ctx: _FileCtx) -> None:
    # both alias forms: `from time import time as t` (bare-name call)
    # and `import time as _time` (module-attribute call)
    fn_aliases: Set[str] = set()
    mod_aliases: Set[str] = {"time"}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for al in node.names:
                if al.name == "time":
                    fn_aliases.add(al.asname or al.name)
        elif isinstance(node, ast.Import):
            for al in node.names:
                if al.name == "time":
                    mod_aliases.add(al.asname or al.name)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Attribute) and f.attr == "time"
               and isinstance(f.value, ast.Name)
               and f.value.id in mod_aliases) or (
            isinstance(f, ast.Name) and f.id in fn_aliases)
        if hit:
            ctx.emit(
                "GL004", node,
                "time.time() - durations must use time.monotonic() "
                "(wall clock steps/slews under NTP); a genuine "
                "timestamp needs a waiver naming that purpose")


# ---------------------------------------------------------------------------
# GL005 donated-arg-reuse
# ---------------------------------------------------------------------------
def _rule_donated_reuse(ctx: _FileCtx, fn: ast.AST) -> None:
    if not ctx.donated:
        return

    # dead expr text -> (donating callee, line it was donated)
    dead: Dict[str, Tuple[str, int]] = {}

    def donations_in(stmt: ast.stmt) -> List[Tuple[str, ast.Call]]:
        out = []
        for n in _walk_no_funcs_inclusive(stmt):
            if isinstance(n, ast.Call):
                name = _last_name(n.func)
                if name in ctx.donated:
                    out.append((name, n))
        return out

    def loads_stores(stmt: ast.stmt, text: str):
        """(first-load-node, stored?) of `text` in the statement."""
        first_load = None
        stored = False
        for n in _walk_no_funcs_inclusive(stmt):
            if not isinstance(n, (ast.Name, ast.Attribute)):
                continue
            if _expr_text(n) != text:
                continue
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                stored = True
            elif first_load is None:
                first_load = n
        return first_load, stored

    def scan(stmts: Sequence[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            # compound statements only recurse - a donation inside a
            # branch must not leak into the sibling branch's scan.
            # if/else branches are EXCLUSIVE: each scans from the
            # pre-branch state; only expressions dead on both paths
            # stay dead after the join
            if isinstance(st, ast.If):
                snap = dict(dead)
                scan(st.body)
                after_body = dict(dead)
                dead.clear()
                dead.update(snap)
                scan(st.orelse)
                for text in list(dead):
                    if text not in after_body:
                        del dead[text]
                continue
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While,
                               ast.With, ast.AsyncWith, ast.Try)):
                for body in (getattr(st, "body", None),
                             getattr(st, "orelse", None),
                             getattr(st, "finalbody", None)):
                    if body:
                        scan(body)
                for h in getattr(st, "handlers", []) or []:
                    scan(h.body)
                continue
            new_dead: Dict[str, Tuple[str, int]] = {}
            dons = donations_in(st)
            for callee, call in dons:
                for pos in ctx.donated[callee]:
                    if pos >= len(call.args):
                        continue
                    arg = call.args[pos]
                    if isinstance(arg, (ast.Name, ast.Attribute,
                                        ast.Subscript)):
                        t = _expr_text(arg)
                        if t:
                            new_dead[t] = (callee, call.lineno)
            # reads of already-dead exprs in this statement
            for text, (callee, dline) in list(dead.items()):
                load, stored = loads_stores(st, text)
                # the donating statement itself re-registers below;
                # here only prior donations are live
                if load is not None:
                    ctx.emit(
                        "GL005", load,
                        f"'{text}' read after being DONATED to "
                        f"{callee}() at line {dline} - the buffer "
                        f"belongs to XLA now; rebind it from the "
                        f"call's result first")
                    del dead[text]
                elif stored:
                    del dead[text]
            # register this statement's donations, then let its own
            # assignment targets revive them (result rebinding)
            dead.update(new_dead)
            if isinstance(st, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)):
                targets = (st.targets
                           if isinstance(st, ast.Assign)
                           else [st.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, (ast.Name, ast.Attribute)):
                            dead.pop(_expr_text(n), None)
    scan(fn.body)


# ---------------------------------------------------------------------------
# GL006 unknown-config-key
# ---------------------------------------------------------------------------
def _cfg_like(expr: ast.expr, aliases: Set[str]) -> bool:
    if isinstance(expr, ast.Name):
        low = expr.id.lower()
        return "cfg" in low or "conf" in low or expr.id in aliases
    if isinstance(expr, ast.Attribute):
        low = expr.attr.lower()
        return "cfg" in low or "conf" in low
    return False


def _rule_cfg_keys(ctx: _FileCtx, fn: ast.AST) -> None:
    reg = schema.get_registry()
    # one-hop aliases: dc = self._daug_cfg
    aliases: Set[str] = set()
    for n in _walk_no_funcs(fn):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and _cfg_like(n.value, set())):
            aliases.add(n.targets[0].id)

    def check_key(node: ast.AST, key: str) -> None:
        if reg.recognizes(key):
            return
        hint = reg.suggest(key)
        extra = f" (did you mean '{hint}'?)" if hint else ""
        ctx.emit(
            "GL006", node,
            f"config key '{key}' is not in the schema registry - no "
            f"set_param handler consumes it{extra}")

    for n in _walk_no_funcs(fn):
        if (isinstance(n, ast.Subscript)
                and isinstance(n.slice, ast.Constant)
                and isinstance(n.slice.value, str)
                and _cfg_like(n.value, aliases)):
            check_key(n, n.slice.value)
        elif (isinstance(n, ast.Call)
              and isinstance(n.func, ast.Attribute)
              and n.func.attr == "get"
              and _cfg_like(n.func.value, aliases)
              and n.args
              and isinstance(n.args[0], ast.Constant)
              and isinstance(n.args[0].value, str)):
            check_key(n, n.args[0].value)


# ---------------------------------------------------------------------------
# GL007 unsharded-large-intermediate (jit-traced, mesh-aware modules)
# ---------------------------------------------------------------------------
def _rule_unsharded_intermediate(ctx: _FileCtx, fn: ast.AST) -> None:
    if not ctx.mesh_aware:
        return
    fname = getattr(fn, "name", "<lambda>")

    def weighty(exprs: Sequence[ast.expr]) -> str:
        for e in exprs:
            for name in sorted(_dynamic_names(e)):
                if _WEIGHTY_RE.search(name):
                    return name
        return ""

    def stmt_has_constraint(st: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Call)
            and _last_name(n.func) == "with_sharding_constraint"
            for n in _walk_no_funcs_inclusive(st))

    for st in _walk_no_funcs_inclusive(fn):
        # simple statements only: the smallest enclosing statement is
        # the waiver/constraint granularity, and walking compound
        # statements too would double-count their bodies
        if not isinstance(st, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign, ast.Return, ast.Expr)):
            continue
        if stmt_has_constraint(st):
            continue
        for n in _walk_no_funcs_inclusive(st):
            if not isinstance(n, ast.Call):
                continue
            call = _last_name(n.func)
            args = list(n.args) + [kw.value for kw in n.keywords]
            if call in _ALLOCATORS:
                src = weighty(args)
                if src:
                    ctx.emit(
                        "GL007", n,
                        f"{call}('{src}') builds a weight-sized "
                        f"temporary in jit-traced function '{fname}' "
                        f"with no sharding constraint - under a "
                        f"multi-device mesh it materializes fully "
                        f"replicated; pin it with "
                        f"with_sharding_constraint (or waive naming "
                        f"the out_shardings/donation that shards it)")
            elif call == "map" and any(
                    isinstance(a, (ast.Name, ast.Attribute))
                    and _last_name(a) in _ALLOCATORS for a in n.args):
                # jax.tree.map(jnp.zeros_like, tree): the mapped
                # allocator clones every leaf of the tree
                src = weighty(n.args[1:])
                if src:
                    ctx.emit(
                        "GL007", n,
                        f"tree.map of an allocator over '{src}' builds "
                        f"a weight-tree-sized temporary in jit-traced "
                        f"function '{fname}' with no sharding "
                        f"constraint - under a multi-device mesh it "
                        f"materializes fully replicated; pin it with "
                        f"with_sharding_constraint (or waive naming "
                        f"the out_shardings/donation that shards it)")


# ---------------------------------------------------------------------------
# GL008 metric-name-style (module-wide, like GL004)
# ---------------------------------------------------------------------------
# the dotted-lowercase metric naming grammar (docs/OBSERVABILITY.md):
# at least two [a-z0-9_]+ segments joined by dots
_METRIC_NAME_RE = re.compile(r"[a-z0-9_]+(\.[a-z0-9_]+)+")
# span() names nest into "outer/inner" registry paths at runtime (the
# API's documented idiom uses short segment names like "round" /
# "step"), so a span segment may be a SINGLE lowercase token - the
# style bugs (uppercase, spaces, dashes) still flag
_SPAN_NAME_RE = re.compile(r"[a-z0-9_]+(\.[a-z0-9_]+)*")
# telemetry calls whose first string argument IS a series name
_METRIC_CALLS = frozenset({
    "inc", "set_gauge", "observe", "span", "counter", "gauge",
    "histogram", "beacon",
})


def _tel_name(name: str) -> bool:
    """Exact telemetry-identifier match: `telemetry`, `tel`, `_tel`,
    `_TEL`, `self._tel`, `my_tel` - NOT substring hits like `hotel`
    or `intel` (a substring rule would fail CI on unrelated APIs)."""
    low = name.lower()
    return (low.lstrip("_") in ("tel", "telemetry")
            or low.endswith(("_tel", "_telemetry")))


def _tel_receiver(expr: ast.expr) -> bool:
    """Is this call receiver telemetry-flavored? Covers the repo's
    idioms - `telemetry.inc`, `tel.observe`, `self._tel.span`,
    `telemetry.get().inc` - without dragging unrelated `.observe()`
    APIs into the rule."""
    if isinstance(expr, ast.Name):
        return _tel_name(expr.id)
    if isinstance(expr, ast.Attribute):
        return _tel_name(expr.attr) or _tel_receiver(expr.value)
    if isinstance(expr, ast.Call):
        return _tel_receiver(expr.func)
    return False


def _rule_metric_names(ctx: _FileCtx) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _METRIC_CALLS
                and _tel_receiver(func.value)):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue  # dynamic names are the caller's responsibility
        name = node.args[0].value
        rx = (_SPAN_NAME_RE if func.attr == "span"
              else _METRIC_NAME_RE)
        if not rx.fullmatch(name):
            what = ("span segment" if func.attr == "span"
                    else "metric name")
            ctx.emit(
                "GL008", node,
                f"{what} {name!r} in telemetry.{func.attr}() does "
                f"not match the dotted-lowercase naming grammar - "
                f"off-grammar names silently create parallel series "
                f"no dashboard or alert rule watches")


# ---------------------------------------------------------------------------
# GL010-GL016: the concurrency tier (lock discipline)
# ---------------------------------------------------------------------------
# receiver-name fallbacks: a lock PASSED into a function has no
# visible construction, but the repo's naming is consistent enough
# that the suffix identifies it
_LOCKNAME_RE = re.compile(r"(^|_)(lock|mutex)s?$", re.IGNORECASE)
_CONDNAME_RE = re.compile(r"(^|_)cond(ition)?$", re.IGNORECASE)


def _dotted_text(e: ast.expr) -> str:
    """`self._cond` / `mod.lock` -> their dotted text, WITHOUT the
    ast.unparse cost; "" for anything that is not a plain Name/
    Attribute chain (such receivers are never lock-flavored)."""
    parts = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if not isinstance(e, ast.Name):
        return ""
    parts.append(e.id)
    parts.reverse()
    return ".".join(parts)
_LOCK_FACTORIES = frozenset({"Lock", "RLock"})
_COND_FACTORIES = frozenset({"Condition"})
_EVENT_FACTORIES = frozenset({"Event", "Semaphore", "BoundedSemaphore",
                              "Barrier"})
_QUEUE_FACTORIES = frozenset({"Queue", "LifoQueue", "PriorityQueue",
                              "SimpleQueue"})
_SUBPROC_BLOCKERS = frozenset({"run", "check_call", "check_output",
                               "call"})


@dataclass
class _ConcInfo:
    """Module-wide concurrency flavor map: which expression texts are
    locks, conditions, events, queues, threads - collected from their
    construction sites, like the donated-arg registry."""
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)
    events: Set[str] = field(default_factory=set)
    queues: Set[str] = field(default_factory=set)
    threads: Set[str] = field(default_factory=set)
    thread_classes: Set[str] = field(default_factory=set)
    # attr name -> (lock expr text, declaration line)
    guarded: Dict[str, Tuple[str, int]] = field(default_factory=dict)

    def lockish(self, text: str) -> bool:
        if not text:
            return False
        if text in self.locks or text in self.conds:
            return True
        if text in self.events or text in self.queues:
            return False
        last = text.rsplit(".", 1)[-1]
        return bool(_LOCKNAME_RE.search(last)
                    or _CONDNAME_RE.search(last))

    def condish(self, text: str) -> bool:
        if text in self.conds:
            return True
        if (text in self.events or text in self.locks
                or text in self.queues):
            return False
        return bool(_CONDNAME_RE.search(text.rsplit(".", 1)[-1]))

    def queueish(self, text: str) -> bool:
        if text in self.queues:
            return True
        last = text.rsplit(".", 1)[-1].lower().lstrip("_")
        return last in ("q", "queue") or last.endswith("_q") \
            or "queue" in last

    def threadish(self, text: str) -> bool:
        if text in self.threads:
            return True
        return "thread" in text.rsplit(".", 1)[-1].lower()


def _is_thread_base(base: ast.expr) -> bool:
    return _last_name(base) == "Thread"


def _conc_collect(ctx: _FileCtx,
                  nodes: Sequence[ast.AST]) -> _ConcInfo:
    conc = _ConcInfo()
    # pass 0: Thread subclasses (their constructors are thread
    # factories too)
    for node in nodes:
        if isinstance(node, ast.ClassDef) and any(
                _is_thread_base(b) for b in node.bases):
            conc.thread_classes.add(node.name)
    # pass 1: factory assignments
    for node in nodes:
        targets: List[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not (isinstance(value, ast.Call) and targets):
            continue
        name = _last_name(value.func)
        dest = (conc.locks if name in _LOCK_FACTORIES
                else conc.conds if name in _COND_FACTORIES
                else conc.events if name in _EVENT_FACTORIES
                else conc.queues if name in _QUEUE_FACTORIES
                else conc.threads if (name == "Thread"
                                      or name in conc.thread_classes)
                else None)
        if dest is None:
            continue
        for t in targets:
            if isinstance(t, (ast.Name, ast.Attribute)):
                text = _dotted_text(t)
                if text:
                    dest.add(text)
    # pass 2: thread collections (`self._threads.append(t)`) and loop
    # variables over them (`for t in self._threads:`)
    coll: Set[str] = set()
    for node in nodes:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append" and node.args
                and _dotted_text(node.args[0]) in conc.threads):
            coll.add(_dotted_text(node.func.value))
    for node in nodes:
        if isinstance(node, (ast.For, ast.AsyncFor)):
            it = _dotted_text(node.iter)
            if it in coll or conc.threadish(it):
                for n in _assigned_names(node.target):
                    conc.threads.add(n)
    # guarded-by notes -> attribute registry (GL016). The note must
    # sit on (or above) an attribute assignment - a dangling note is
    # itself a finding, not silently-ignored documentation
    decl_lines: Dict[int, str] = {}
    for node in nodes:
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        if isinstance(tgt, ast.Attribute) and isinstance(
                tgt.ctx, ast.Store):
            decl_lines.setdefault(node.lineno, tgt.attr)
    for target_line, lock_text, src_line in ctx.guard_notes:
        attr = decl_lines.get(target_line)
        if attr is None:
            ctx.findings.append(Finding(
                "GL016", ctx.rel, src_line, 0,
                f"guarded-by annotation '{lock_text}' matches no "
                f"attribute assignment on line {target_line}"))
            continue
        conc.guarded[attr] = (lock_text, target_line)
    return conc


def _has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or any(kw.arg == "timeout"
                                  for kw in call.keywords)


def _releases(stmts: Sequence[ast.stmt], text: str) -> bool:
    for st in stmts:
        for n in _walk_no_funcs_inclusive(st):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "release"
                    and _dotted_text(n.func.value) == text):
                return True
    return False


def _store_attr_targets(st: ast.stmt) -> List[ast.expr]:
    """Store targets of a simple statement, with subscripts unwrapped
    to their base (`self._hits[k] = v` writes `self._hits`)."""
    if isinstance(st, ast.Assign):
        targets = list(st.targets)
    elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
        targets = [st.target]
    else:
        return []
    out = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, (ast.Name, ast.Attribute)) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                out.append(n)
            elif isinstance(n, ast.Subscript) and isinstance(
                    n.ctx, (ast.Store, ast.Del)):
                base = n.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, (ast.Name, ast.Attribute)):
                    out.append(base)
    return out


def _guard_matches(held: Sequence[str], base_text: str,
                   lock_text: str) -> bool:
    """Does any held `with` context satisfy the guarded-by note? The
    note is written against the declaring object (`self._lock`); a
    write through another base (`_TEL._beacons`) must hold the SAME
    lock attribute on ITS base (`_TEL._beacon_lock`)."""
    if lock_text in held:
        return True
    if "." in lock_text and base_text:
        expected = base_text + "." + lock_text.rsplit(".", 1)[-1]
        return expected in held
    return False


def _scan_concurrency_scope(ctx: _FileCtx, conc: _ConcInfo,
                            body: Sequence[ast.stmt],
                            fname: str) -> None:
    """GL010/GL011/GL013/GL014/GL015/GL016 over one scope (a function
    body or the module body), tracking the lexical `with <lock>` stack
    and predicate-loop nesting."""
    in_init = fname == "__init__"

    def check_call(n: ast.Call, held: List[str], in_while: bool,
                   sibling_try: Optional[ast.Try],
                   fin_releases: Set[str]) -> None:
        func = n.func
        if not isinstance(func, ast.Attribute):
            return
        recv = _dotted_text(func.value)
        attr = func.attr
        if attr == "acquire" and conc.lockish(recv):
            ok = (recv in fin_releases
                  or (sibling_try is not None
                      and _releases(sibling_try.finalbody, recv)))
            if not ok:
                ctx.emit(
                    "GL010", n,
                    f"bare {recv}.acquire() with no try/finally "
                    f"release - an exception here leaks the lock "
                    f"forever; use `with {recv}:`")
        elif (attr == "wait" and conc.condish(recv)
                and not in_while):
            ctx.emit(
                "GL014", n,
                f"{recv}.wait() outside a predicate `while` loop - "
                f"condition waits wake spuriously and on stale "
                f"notifies; re-check the predicate in a loop "
                f"(`while not <pred>: {recv}.wait(...)`)")
        elif (attr == "join" and not n.args and not n.keywords
                and conc.threadish(recv)):
            ctx.emit(
                "GL013", n,
                f"{recv}.join() with no timeout - a wedged thread "
                f"hangs shutdown forever; join with a timeout and "
                f"handle the still-alive case")
        if not held:
            return
        # --- GL015: blocking while a lock is held ---
        what = ""
        if (attr == "get" and conc.queueish(recv)
                and not (n.args
                         and isinstance(n.args[0], ast.Constant)
                         and n.args[0].value is False)):
            what = f"{recv}.get()"
        elif attr == "accept" and not n.args:
            what = f"{recv}.accept()"
        elif (attr in ("wait", "communicate")
                and recv not in held       # cond.wait on the HELD
                and not _has_timeout(n)):  # lock releases it
            what = f"{recv}.{attr}()"
        elif attr == "join" and not n.args and not n.keywords \
                and conc.threadish(recv):
            what = f"{recv}.join()"
        elif attr == "sleep":
            what = f"{recv}.sleep()"
        elif (attr in _SUBPROC_BLOCKERS
                and isinstance(func.value, ast.Name)
                and func.value.id == "subprocess"
                and not any(kw.arg == "timeout" for kw in n.keywords)):
            what = f"subprocess.{attr}()"
        if what:
            ctx.emit(
                "GL015", n,
                f"blocking {what} while holding {held[-1]} - every "
                f"other thread needing the lock stalls behind this "
                f"wait (and a producer/consumer pair deadlocks); "
                f"move the blocking call outside the `with` block")

    def check_thread_ctor(n: ast.Call, st: ast.stmt,
                          body_: Sequence[ast.stmt], idx: int) -> None:
        if _last_name(n.func) != "Thread":
            return
        if any(kw.arg == "daemon" for kw in n.keywords):
            return
        # `t = Thread(...)` followed by `t.daemon = ...` in the same
        # scope counts
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            tname = st.targets[0].id
            for later in body_[idx + 1:]:
                for sub in _walk_no_funcs_inclusive(later):
                    if (isinstance(sub, ast.Attribute)
                            and sub.attr == "daemon"
                            and isinstance(sub.ctx, ast.Store)
                            and _dotted_text(sub.value) == tname):
                        return
        ctx.emit(
            "GL011", n,
            "threading.Thread() without daemon= - an undecided "
            "lifetime either blocks interpreter exit (non-daemon "
            "leak) or dies mid-write (accidental daemon); decide "
            "explicitly")

    def check_guarded_stores(st: ast.stmt, held: List[str]) -> None:
        if in_init:
            return  # construction precedes publication
        for tgt in _store_attr_targets(st):
            if not isinstance(tgt, ast.Attribute):
                continue
            note = conc.guarded.get(tgt.attr)
            if note is None:
                continue
            lock_text, decl_line = note
            if st.lineno == decl_line:
                continue
            base_text = _dotted_text(tgt.value)
            if not _guard_matches(held, base_text, lock_text):
                ctx.emit(
                    "GL016", tgt,
                    f"write to '{_dotted_text(tgt)}' outside `with "
                    f"{lock_text}` - the field is annotated "
                    f"guarded-by: {lock_text} (declared line "
                    f"{decl_line})")

    def exprs_of(st: ast.stmt) -> List[ast.expr]:
        out = []
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                out.append(child)
        return out

    def check_exprs(exprs: Sequence[ast.expr], held: List[str],
                    in_while: bool, sibling_try: Optional[ast.Try],
                    fin_releases: Set[str], body_: Sequence[ast.stmt],
                    idx: int, st: ast.stmt) -> None:
        for e in exprs:
            for n in _walk_no_funcs_inclusive(e):
                if isinstance(n, ast.Call):
                    check_call(n, held, in_while, sibling_try,
                               fin_releases)
                    check_thread_ctor(n, st, body_, idx)

    def scan(body_: Sequence[ast.stmt], held: List[str],
             in_while: bool, fin_releases: Set[str]) -> None:
        for idx, st in enumerate(body_):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            nxt = body_[idx + 1] if idx + 1 < len(body_) else None
            sibling_try = nxt if isinstance(nxt, ast.Try) else None
            check_guarded_stores(st, held)
            if isinstance(st, (ast.With, ast.AsyncWith)):
                pushed = list(held)
                ctx_exprs = []
                for item in st.items:
                    ctx_exprs.append(item.context_expr)
                    t = _dotted_text(item.context_expr)
                    if conc.lockish(t):
                        pushed = pushed + [t]
                check_exprs(ctx_exprs, held, in_while, sibling_try,
                            fin_releases, body_, idx, st)
                scan(st.body, pushed, in_while, fin_releases)
            elif isinstance(st, ast.While):
                # a wait in the loop TEST is the predicate-loop idiom
                # too (`while not ev.wait(t): ...`)
                check_exprs([st.test], held, True, sibling_try,
                            fin_releases, body_, idx, st)
                scan(st.body, held, True, fin_releases)
                scan(st.orelse, held, in_while, fin_releases)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                check_exprs([st.iter], held, in_while, sibling_try,
                            fin_releases, body_, idx, st)
                scan(st.body, held, in_while, fin_releases)
                scan(st.orelse, held, in_while, fin_releases)
            elif isinstance(st, ast.If):
                check_exprs([st.test], held, in_while, sibling_try,
                            fin_releases, body_, idx, st)
                scan(st.body, held, in_while, fin_releases)
                scan(st.orelse, held, in_while, fin_releases)
            elif isinstance(st, ast.Try):
                # an acquire in the try body excused by this try's own
                # finally-release (the acquire-then-try idiom)
                fin = set(fin_releases)
                for fin_st in st.finalbody:
                    for n in _walk_no_funcs_inclusive(fin_st):
                        if (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Attribute)
                                and n.func.attr == "release"):
                            fin.add(_dotted_text(n.func.value))
                scan(st.body, held, in_while, fin)
                for h in st.handlers:
                    scan(h.body, held, in_while, fin_releases)
                scan(st.orelse, held, in_while, fin_releases)
                scan(st.finalbody, held, in_while, fin_releases)
            else:
                check_exprs(exprs_of(st), held, in_while, sibling_try,
                            fin_releases, body_, idx, st)

    scan(body, [], False, set())


def _thread_target_functions(
        ctx: _FileCtx, conc: _ConcInfo,
        nodes: Sequence[ast.AST]) -> List[ast.AST]:
    """Functions that run ON a spawned thread: `target=` of a Thread
    construction (bare name, local closure, or `self._method`), and
    the `run` method of every Thread subclass."""
    by_name: Dict[str, List[ast.AST]] = {}
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    out: List[ast.AST] = []
    seen: Set[int] = set()

    def add(fn: ast.AST) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(fn)

    for node in nodes:
        if isinstance(node, ast.ClassDef) and any(
                _is_thread_base(b) for b in node.bases):
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "run":
                    add(item)
        if not (isinstance(node, ast.Call)
                and (_last_name(node.func) == "Thread"
                     or _last_name(node.func) in conc.thread_classes)):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            name = ""
            if isinstance(kw.value, ast.Name):
                name = kw.value.id
            elif (isinstance(kw.value, ast.Attribute)
                  and isinstance(kw.value.value, ast.Name)
                  and kw.value.value.id in ("self", "cls")):
                name = kw.value.attr
            for fn in by_name.get(name, ()):
                add(fn)
    return out


def _rule_unlocked_thread_writes(ctx: _FileCtx, conc: _ConcInfo,
                                 fn: ast.AST) -> None:
    """GL012 over one thread-target function: stores to instance
    attributes (`self.x = ...`) or declared-global names with no lock
    held are cross-thread data races waiting for a reader. Fields
    carrying a guarded-by annotation are GL016's responsibility; the
    fix is a lock, a queue handoff, or the annotation."""
    fname = getattr(fn, "name", "<lambda>")
    args = getattr(fn, "args", None)
    self_name = ""
    if args is not None:
        pos = list(args.posonlyargs) + list(args.args)
        if pos and pos[0].arg in ("self", "cls"):
            self_name = pos[0].arg
    declared_globals: Set[str] = set()
    for n in _walk_no_funcs(fn):
        if isinstance(n, ast.Global):
            declared_globals.update(n.names)

    def scan(body: Sequence[ast.stmt], held: bool) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                locked = held or any(
                    conc.lockish(_dotted_text(i.context_expr))
                    for i in st.items)
                scan(st.body, locked)
                continue
            if not held:
                for tgt in _store_attr_targets(st):
                    what = ""
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == self_name
                            and self_name):
                        if tgt.attr in conc.guarded:
                            continue  # GL016 checks those
                        what = _dotted_text(tgt)
                    elif (isinstance(tgt, ast.Name)
                          and tgt.id in declared_globals):
                        what = f"global {tgt.id}"
                    if what:
                        ctx.emit(
                            "GL012", tgt,
                            f"thread target '{fname}' writes shared "
                            f"state '{what}' with no lock in scope - "
                            f"a concurrent reader sees torn/stale "
                            f"state; guard it with a lock, hand it "
                            f"over a queue, or annotate the field "
                            f"guarded-by its lock")
            for sub in (getattr(st, "body", None),
                        getattr(st, "orelse", None),
                        getattr(st, "finalbody", None)):
                if sub:
                    scan(sub, held)
            for h in getattr(st, "handlers", []) or []:
                scan(h.body, held)

    scan(getattr(fn, "body", []), False)


def _rule_thread_subclass_daemon(ctx: _FileCtx,
                                 nodes: Sequence[ast.AST]) -> None:
    """GL011's class form: a Thread subclass must decide daemon-ness
    in its __init__ (super().__init__(daemon=...) or self.daemon=)."""
    for node in nodes:
        if not (isinstance(node, ast.ClassDef)
                and any(_is_thread_base(b) for b in node.bases)):
            continue
        init = next((f for f in node.body
                     if isinstance(f, ast.FunctionDef)
                     and f.name == "__init__"), None)
        decided = False
        if init is not None:
            for n in _walk_no_funcs(init):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "__init__"
                        and any(kw.arg == "daemon"
                                for kw in n.keywords)):
                    decided = True
                elif (isinstance(n, ast.Attribute)
                      and n.attr == "daemon"
                      and isinstance(n.ctx, ast.Store)):
                    decided = True
        if not decided:
            ctx.emit(
                "GL011", node,
                f"Thread subclass '{node.name}' never sets daemon= "
                f"(inherits non-daemon: a leaked instance blocks "
                f"interpreter exit); pass daemon= to "
                f"super().__init__ or set self.daemon in __init__")


def _concurrency_pass(ctx: _FileCtx) -> None:
    # one pre-walked node list shared by every sub-pass (ast.walk is
    # the dominant cost of walking the same tree nine times)
    nodes = list(ast.walk(ctx.tree))
    conc = _conc_collect(ctx, nodes)
    _rule_thread_subclass_daemon(ctx, nodes)
    # every scope: module body + each function body
    _scan_concurrency_scope(ctx, conc, ctx.tree.body, "<module>")
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_concurrency_scope(ctx, conc, node.body, node.name)
    for fn in _thread_target_functions(ctx, conc, nodes):
        _rule_unlocked_thread_writes(ctx, conc, fn)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def _function_visits(ctx: _FileCtx) -> None:
    """Visit every function with its jit/hot scope resolved."""

    def visit(node: ast.AST, in_jit: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                decorated = any(
                    _last_name(d.func if isinstance(d, ast.Call)
                               else d) == "jit"
                    for d in child.decorator_list)
                jitted = in_jit or child.name in ctx.jitted or decorated
                hot = child.lineno in ctx.hot_lines
                _rule_rng_reuse(ctx, child)
                _rule_donated_reuse(ctx, child)
                _rule_cfg_keys(ctx, child)
                if jitted:
                    _rule_host_sync(ctx, child, "jit-traced")
                    _rule_tracer_branch(ctx, child)
                    _rule_unsharded_intermediate(ctx, child)
                elif hot:
                    _rule_host_sync(ctx, child, "hot-path")
                visit(child, jitted)
            else:
                visit(child, in_jit)

    visit(ctx.tree, False)


def lint_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    rel = rel or path
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source)
    except (OSError, SyntaxError) as e:
        return [Finding("GL090", rel, getattr(e, "lineno", 0) or 0, 0,
                        f"file does not parse: {e}")]
    ctx = _FileCtx(path=path, rel=rel, tree=tree)
    _scan_comments(ctx, source)
    _module_pass(ctx)
    _rule_wallclock(ctx)
    _rule_metric_names(ctx)
    _concurrency_pass(ctx)
    _function_visits(ctx)
    _apply_waivers(ctx)
    ctx.findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return ctx.findings


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"
                           and not d.startswith(".")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames)
                       if f.endswith(".py"))
    return out


def lint_paths(
        paths: Sequence[str],
        rules: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], int, float]:
    """Lint every .py under `paths`. Returns (findings, n_files,
    elapsed_s). `rules` filters to a subset of rule ids."""
    t0 = time.monotonic()
    findings: List[Finding] = []
    files = iter_py_files(paths)
    for path in files:
        findings.extend(lint_file(path, os.path.relpath(path)))
    if rules:
        keep = set(rules)
        findings = [f for f in findings if f.rule in keep]
    return findings, len(files), time.monotonic() - t0


def render_text(findings: Sequence[Finding], n_files: int,
                elapsed_s: float, show_waived: bool = False) -> str:
    lines = []
    unwaived = [f for f in findings if not f.waived]
    for f in unwaived:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                     f"[{RULES.get(f.rule, '?')}] {f.message}")
    n_waived = sum(1 for f in findings if f.waived)
    if show_waived:
        for f in findings:
            if f.waived:
                lines.append(
                    f"{f.path}:{f.line}: {f.rule} waived: {f.reason}")
    lines.append(
        f"graftlint: {len(unwaived)} finding(s), {n_waived} waived, "
        f"{n_files} files in {elapsed_s:.2f}s")
    return "\n".join(lines)
