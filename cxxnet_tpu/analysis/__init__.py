"""graftlint: framework-aware static analysis (docs/STATIC_ANALYSIS.md).

The compiler never checks the invariants this framework's correctness
hangs on - RNG streams folded from (seed, step_counter), donated
buffers that must not be reused, host syncs on the dispatch hot path,
and `key = value` configs where a typo silently changes a run. Two
tiers verify them statically:

- **tier 1, AST lint** (astlint.py): stdlib-``ast`` rules over the
  Python source, with stable GLxxx ids, per-line waivers
  (``# graftlint: disable=GL004 reason``) and text/JSON reporters.
  No jax import - runs anywhere in well under the 10 s CI budget.
- **tier 2, jaxpr/HLO audit** (jaxpr_audit.py): trace the REAL
  train/eval executables for a representative config and assert on
  the lowered artifact - no f64 leaks, no host callbacks, buffer
  donation actually applied, no weight-sized captured constants, and
  a stable recompile count across a round with a short final chunk.
- **concurrency tier** (the GL01x rules in astlint.py +
  lock_audit.py): lock discipline linted in the source (bare
  acquires, daemon-less threads, unlocked thread-target writes,
  timeout-less joins, predicate-less Condition.wait, blocking calls
  under a lock, ``# guarded-by:`` annotations), then verified LIVE -
  a Lock/RLock construction shim records per-thread acquisition
  sequences over the real serve/prefetch/watchdog paths, fails on a
  cyclic lock-order graph or a lock held across a jax dispatch
  boundary, and reports contention.

Plus the **config schema registry** (schema.py): every recognized
config key, generated from the source tree's ``set_param`` handlers,
with did-you-mean suggestions for unknown keys. The CLI wires it into
normal config parsing (main.py); ``--check-configs`` sweeps conf
trees.

CLI: ``python -m cxxnet_tpu.analysis [paths] [--check-configs DIR]
[--jaxpr-audit] [--lock-audit] [--json FILE]`` - exit 0 iff zero
unwaived findings and every audit check passed. CI runs it as the
blocking ``static-analysis`` and ``concurrency-audit`` jobs.
"""

from cxxnet_tpu.analysis.astlint import (
    Finding, RULES, lint_paths, render_text)
from cxxnet_tpu.analysis.schema import (
    KeyRegistry, get_registry, suggest, unknown_keys, validate_pairs)

__all__ = [
    "Finding", "RULES", "lint_paths", "render_text",
    "KeyRegistry", "get_registry", "suggest", "unknown_keys",
    "validate_pairs",
]
