"""graftlint tier 2: audit the LOWERED artifact, not the source.

Tier 1 trusts what the Python says; this tier inspects what we
actually dispatch (the TVM/Relay argument - PAPERS.md): trace the
real jitted executables of a representative trainer and assert on
the jaxpr + StableHLO + compiled HLO:

- **no-f64**: no float64 anywhere in the lowered module. An
  accidental x64 leak (np.float64 scalar, JAX_ENABLE_X64 drift)
  doubles bandwidth and silently changes trajectories.
- **no-host-callback**: no `custom_call` to a python/io callback and
  no infeed/outfeed - a host round-trip inside the step caps
  throughput at the host, invisibly.
- **donation-applied**: `donate_argnums` plumbed all the way through:
  donated params carry `tf.aliasing_output` in the lowered module AND
  the compiled HLO has a non-empty `input_output_alias` table. jax
  only *warns* when donation is dropped; this makes it a CI failure.
  (Non-donating executables are asserted alias-free, so the check
  cannot pass vacuously.)
- **no-captured-consts**: no weight-sized arrays baked into the
  executable as constants (params must arrive as ARGUMENTS - a
  captured weight re-embeds per compile and defeats donation).
- **recompile-audit**: the executable count stays bounded across a
  simulated round WITH a short final chunk - the PR 3 program-shape
  trap: `steps_per_dispatch=K` retraces once per distinct chunk
  length, so a round of 4+4+1 must cost exactly 2 `_train_chunk`
  lowering cache entries (K=4 and the K=1 flush), stable across
  rounds; padded short batches must NOT add `train_step`/eval
  entries.

- **zero-audit**: the ZeRO stage-2/3 and tensor-parallel executables
  (docs/parallel.md) audited on a REAL 8-device mesh (forced CPU host
  platform; in a subprocess when the current process has fewer
  devices): the compiled stage-2 HLO must contain a literal
  `reduce-scatter` of the gradients and an `all-gather` of the fresh
  weights, must NOT all-reduce any eligible weight's full-gradient
  shape (the accidental full-gradient materialization ZeRO removes),
  and every eligible weight's shard shape must appear as a
  reduce-scatter output (the update really runs on 1/N shards).
  Stage 3 additionally proves the weights are STORED sharded: no
  eligible full weight shape among the entry parameters - full
  shapes appear only as all-gather results (the just-in-time
  per-layer gathers). This closes the audit-coverage gap for the
  parallel executables the ROADMAP called out.

- **serve-audit**: the continuous-batching serving layer
  (serve/server.py, docs/SERVING.md) audited at the executable level:
  after warmup the inference executable's compiled-program count
  equals the bucket count and stays FLAT over 100 mixed-size
  requests (zero steady-state recompiles - the serving SLO depends
  on it); each bucket executable is additionally put through the
  artifact checks with donation asserted ABSENT (a donated param
  would free the weights a concurrent replica still needs).

- **pass-audit**: the graph-pass pipeline (nnet/passes.py,
  docs/GRAPH_PASSES.md) audited at the traced-program level on a
  fullc+batch_norm trainer with
  `graph_passes = fold_conv_bn,dead_layer_elim`: the FOLDED
  infer_step jaxpr contains no BN moment/variance pipeline (zero
  rsqrt - the stats are frozen host constants - and strictly fewer
  equations than the unfolded trace, which is asserted to contain
  the rsqrt so the check cannot pass vacuously); the dead-layer-
  eliminated early-node extract contains none of the pruned
  subgraph's matmuls; and the fold adds ZERO new steady-state
  executables - after the one-time calibration, repeated full+short
  padded predicts and extracts leave every per-node infer cache at
  exactly 1 (the recompile audit stays flat).

- **quant-audit**: the int8 post-training-quantization path
  (quantize_int8 pass + ops/int8.py, docs/GRAPH_PASSES.md
  "Quantization") audited at the traced-program level: the quantized
  infer trace's DATA-PATH matmuls (output leading dim = the batch)
  all carry int8 operand dtypes with int32 accumulation and ZERO
  float data-path dots remain, vacuity-guarded against the float
  trace (which must carry the f32 dots, or the comparison proves
  nothing - the GRAPH_PASSES.md key finding that wins are measured
  at the traced-jaxpr level); an explicit `layer_quant = float` pin
  keeps exactly its layer's dot float; and quantized SERVING stays
  zero-recompile - calibrate first, then a warmed Server's
  executable count equals the bucket count and stays flat over a
  mixed-size request storm, with each bucket executable's trace
  int8-engaged.

Audited executables: `train_step`, `_train_chunk` (K=1 and K=4), the
eval pair (`eval_step`, `eval_metric_step`) and the dedicated
`infer_step` (predict/extract/serve share it), over the tiny-MLP
config the fused-dispatch smoke uses, plus the zero-audit set
(stage-2 `train_step`/`_train_chunk[K=4]` on `data:8`, stage-3
`train_step` on `data:8`, stage-2 `train_step` on `data:4,model:2`),
the serve bucket set, the pass-audit pair and the quant-audit set.
Run under `JAX_PLATFORMS=cpu` in CI; the checks are artifact-level,
so they hold for any backend that compiles the same programs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# weight-sized constant bound: the tiny net's legitimate lowering
# constants (iota tables, padding masks) stay well under this; its
# smallest weight (fc1: 36x16 f32) is 2.3 KiB and a captured one
# grows with the model - 4 KiB separates the two populations
_CONST_BYTES_MAX = 4096

_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
metric = error
eval_train = 1
silent = 1
seed = 7
"""


def _make_trainer():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    tr = NetTrainer()
    for k, v in parse_config_string(_CONF):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _batch(i: int, b: int = 32):
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(100 + i)
    return DataBatch(
        data=rng.rand(b, 1, 1, 36).astype(np.float32),
        label=(rng.randint(0, 3, size=(b, 1))
               .astype(np.float32)))


# ---------------------------------------------------------------------------
# artifact checks
# ---------------------------------------------------------------------------
def _check(target: str, check: str, ok: bool,
           detail: str = "") -> Dict[str, Any]:
    return {"target": target, "check": check, "ok": bool(ok),
            "detail": detail}


_F64_RE = re.compile(r"\bf64\b|xf64>|tensor<f64>")
_CALLBACK_RE = re.compile(
    r"custom_call[^\n]*(callback|py_func)|infeed|outfeed",
    re.IGNORECASE)


def _audit_executable(target: str, jitfn, args: Tuple,
                      donated: bool) -> List[Dict[str, Any]]:
    checks: List[Dict[str, Any]] = []
    lowered = jitfn.lower(*args)
    text = lowered.as_text()

    hits = _F64_RE.findall(text)
    checks.append(_check(
        target, "no-f64", not hits,
        f"{len(hits)} f64 type(s) in lowered module" if hits else ""))

    cb = _CALLBACK_RE.search(text)
    checks.append(_check(
        target, "no-host-callback", cb is None,
        f"host transfer in lowered module: {cb.group(0)[:60]}"
        if cb else ""))

    n_alias = text.count("tf.aliasing_output")
    ctext = lowered.compile().as_text()
    has_compiled_alias = ("input_output_alias={" in ctext
                          and "input_output_alias={}" not in ctext)
    if donated:
        checks.append(_check(
            target, "donation-applied",
            n_alias > 0 and has_compiled_alias,
            f"{n_alias} aliased params in lowered module; compiled "
            f"alias table {'present' if has_compiled_alias else 'MISSING'}"))
    else:
        checks.append(_check(
            target, "no-spurious-donation",
            n_alias == 0,
            f"{n_alias} aliased params on a non-donating executable"
            if n_alias else ""))

    consts: List = []
    try:
        consts = list(jitfn.trace(*args).jaxpr.consts)
    except AttributeError:
        # .trace needs jax >= 0.4.27; fall back to "unverifiable"
        checks.append(_check(
            target, "no-captured-consts", False,
            "jit .trace() unavailable on this jax - cannot audit "
            "captured constants"))
        return checks
    big = [c for c in consts
           if getattr(c, "nbytes", 0) > _CONST_BYTES_MAX]
    checks.append(_check(
        target, "no-captured-consts", not big,
        (f"{len(big)} constant(s) over {_CONST_BYTES_MAX} B captured "
         f"(largest {max(c.nbytes for c in big)} B) - weights must "
         "be arguments") if big else
        f"{len(consts)} small consts"))
    return checks


# ---------------------------------------------------------------------------
# zero-audit: ZeRO stage-2/3 + tensor-parallel executables
# ---------------------------------------------------------------------------
def _hlo_lhs(txt: str, op: str) -> List[str]:
    """LHS (shapes incl. combined-tuple members) of every `op`
    instruction in an HLO text dump."""
    out = []
    for line in txt.splitlines():
        s = line.strip()
        if f" {op}(" in s and "=" in s:
            out.append(s.split(f" {op}(")[0])
    return out


def _shape_tokens(tr, mesh_sizes) -> Tuple[set, set]:
    """(device_full, device_shard) HLO shape tokens of every
    zero-ELIGIBLE weight: full = the per-device shape with the zero
    cut restored (global divided by any tensor-parallel placement),
    shard = full with the eligible dim further cut by the data-axis
    size. Computed from the same parallel/sharding.py helpers the
    trainer compiles with, so the audit cannot drift from the rule."""
    import jax
    from cxxnet_tpu.parallel.sharding import zero_partition_dims
    dims = zero_partition_dims(tr.mesh, tr.net, tr._pshard)
    shapes = jax.eval_shape(tr.net.init_params, jax.random.PRNGKey(0))
    dsize = mesh_sizes.get("data", 1)
    full, shard = set(), set()
    for lk, d in dims.items():
        for pn, i in d.items():
            if i is None:
                continue
            gshape = list(shapes[lk][pn].shape)
            spec = list(tr._pshard[lk][pn].spec)
            spec += [None] * (len(gshape) - len(spec))
            dev_full = [s // mesh_sizes.get(ax, 1) if ax else s
                        for s, ax in zip(gshape, spec)]
            dev_shard = list(dev_full)
            dev_shard[i] //= dsize
            full.add("f32[" + ",".join(map(str, dev_full)) + "]")
            shard.add("f32[" + ",".join(map(str, dev_shard)) + "]")
    return full, shard


def _zero_collective_checks(target: str, txt: str, full: set,
                            shard: set, exact: bool,
                            stored_sharded: bool
                            ) -> List[Dict[str, Any]]:
    checks = []
    rs = _hlo_lhs(txt, "reduce-scatter")
    ag = _hlo_lhs(txt, "all-gather")
    ar = _hlo_lhs(txt, "all-reduce")
    checks.append(_check(
        target, "zero-reduce-scatter-present", bool(rs),
        "" if rs else "no reduce-scatter in compiled HLO - gradients "
        "are not being reduce-scattered"))
    gathered = {tok for tok in full if any(tok in l for l in ag)}
    checks.append(_check(
        target, "zero-weight-all-gather-present",
        bool(gathered) if not exact else gathered == full,
        f"all-gather restores {len(gathered)}/{len(full)} eligible "
        f"weight shapes" if gathered != full else ""))
    bad_ar = {tok for tok in full if any(tok in l for l in ar)}
    checks.append(_check(
        target, "zero-no-full-grad-allreduce", not bad_ar,
        f"full-gradient all-reduce of shapes {sorted(bad_ar)} - the "
        f"gradient materializes unsharded" if bad_ar else ""))
    if exact:
        missing = {tok for tok in shard
                   if not any(tok in l for l in rs)}
        checks.append(_check(
            target, "zero-sharded-update", not missing,
            f"shard shapes {sorted(missing)} missing from "
            f"reduce-scatter outputs - their update is not running "
            f"on 1/N shards" if missing else ""))
    if stored_sharded:
        entry = txt.split("ENTRY", 1)[-1]
        params = _hlo_lhs(entry, "parameter")
        leaked = {tok for tok in full
                  if any(tok in l for l in params)}
        checks.append(_check(
            target, "zero3-params-stored-sharded", not leaked,
            f"entry parameters carry full weight shapes "
            f"{sorted(leaked)} - stage 3 must store shards between "
            f"steps" if leaked else ""))
    return checks


def zero_audit_checks() -> List[Dict[str, Any]]:
    """Build the stage-2/3 and tensor-parallel trainers on the live
    mesh and audit their compiled HLO. Requires >= 8 devices (the
    run_audit entry arranges that via subprocess when needed)."""
    import jax
    from cxxnet_tpu.parallel import distributed
    checks: List[Dict[str, Any]] = []
    rng = jax.random.PRNGKey(0)

    def build(extra: str):
        from cxxnet_tpu.nnet.trainer import NetTrainer
        from cxxnet_tpu.utils.config import parse_config_string
        tr = NetTrainer()
        for k, v in parse_config_string(_CONF + extra):
            tr.set_param(k, v)
        tr.init_model()
        sizes = dict(zip(tr.mesh.axis_names, tr.mesh.devices.shape))
        full, shard = _shape_tokens(tr, sizes)
        return tr, full, shard

    # stage 2 on a pure data:8 mesh - exact coverage assertions
    tr, full, shard = build("mesh = data:8\nzero_stage = 2\n")
    sb = tr.stage_batch(_batch(0))
    args = (tr.state, sb.data, sb.extras, sb.labels, sb.mask, rng)
    txt = tr._train_step.lower(*args).compile().as_text()
    checks += _zero_collective_checks(
        "zero2[data:8]/train_step", txt, full, shard, exact=True,
        stored_sharded=False)
    checks += _audit_executable(
        "zero2[data:8]/train_step", tr._train_step, args, donated=True)
    # fused composition: the K=4 chunk must keep the same collectives
    chunk = tr.stage_chunk([_batch(i) for i in range(4)])
    step_idx = distributed.put_global(
        np.arange(4, dtype=np.int32), tr._replicated)
    ctxt = tr._train_chunk.lower(
        tr.state, chunk.data, chunk.extras, chunk.labels, chunk.mask,
        step_idx, rng).compile().as_text()
    checks += _zero_collective_checks(
        "zero2[data:8]/train_chunk[K=4]", ctxt, full, shard,
        exact=True, stored_sharded=False)

    # stage 3: params stored sharded, gathered just-in-time
    tr3, full3, shard3 = build("mesh = data:8\nzero_stage = 3\n")
    sb3 = tr3.stage_batch(_batch(0))
    txt3 = tr3._train_step.lower(
        tr3.state, sb3.data, sb3.extras, sb3.labels, sb3.mask,
        rng).compile().as_text()
    checks += _zero_collective_checks(
        "zero3[data:8]/train_step", txt3, full3, shard3, exact=True,
        stored_sharded=True)

    # tensor-parallel composition: collectives present, no eligible
    # full-gradient all-reduce (activation all-reduces over 'model'
    # are legitimate, so coverage stays presence-level here)
    trt, fullt, shardt = build(
        "mesh = data:4,model:2\nzero_stage = 2\n")
    sbt = trt.stage_batch(_batch(0))
    txtt = trt._train_step.lower(
        trt.state, sbt.data, sbt.extras, sbt.labels, sbt.mask,
        rng).compile().as_text()
    checks += _zero_collective_checks(
        "zero2[data:4,model:2]/train_step", txtt, fullt, shardt,
        exact=False, stored_sharded=False)
    return checks


def _zero_audit(checks: List[Dict[str, Any]]) -> None:
    """Run zero_audit_checks on >= 8 devices: in-process when this
    process already has them (the test suite's forced host platform),
    else in a CPU subprocess with 8 forced devices (the CI CLI). A
    subprocess failure is a FAILING check - the gate must not pass
    vacuously."""
    import jax
    if (jax.default_backend() == "cpu"
            and jax.device_count() >= 8
            and jax.process_count() == 1):
        checks.extend(zero_audit_checks())
        return
    import json
    import os
    import subprocess
    import sys
    flags = [t for t in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in t]
    flags.append("--xla_force_host_platform_device_count=8")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=" ".join(flags))
    code = ("import json\n"
            "from cxxnet_tpu.analysis.jaxpr_audit import "
            "zero_audit_checks\n"
            "print('ZEROAUDIT=' + json.dumps(zero_audit_checks()))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=540)
        payload = [line for line in r.stdout.splitlines()
                   if line.startswith("ZEROAUDIT=")]
        if r.returncode != 0 or not payload:
            checks.append(_check(
                "zero-audit", "subprocess", False,
                f"rc={r.returncode}: {r.stderr[-300:]}"))
            return
        checks.extend(json.loads(payload[0][len("ZEROAUDIT="):]))
    except (subprocess.TimeoutExpired, OSError) as e:
        checks.append(_check("zero-audit", "subprocess", False,
                             str(e)[:300]))


# ---------------------------------------------------------------------------
# recompile audit (the PR 3 program-shape trap)
# ---------------------------------------------------------------------------
def _cache_size(jitfn) -> Optional[int]:
    fn = getattr(jitfn, "_cache_size", None)
    return fn() if callable(fn) else None


# ---------------------------------------------------------------------------
# serve audit: warmed bucket executables, zero steady-state recompiles
# ---------------------------------------------------------------------------
def _serve_audit(checks: List[Dict[str, Any]]) -> Dict[str, int]:
    """Build the continuous-batching server over a FRESH tiny trainer
    (predict would pre-populate the shared infer cache and muddy the
    bucket count) and assert the serving SLO's compile-time story:
    bucket executables all compiled at warmup, none after."""
    from cxxnet_tpu.serve import Server
    tr = _make_trainer()
    srv = Server(tr, max_batch=8, max_wait_ms=1.0, replicas=2)
    if _cache_size(srv._fn) is None:
        checks.append(_check(
            "serve", "cache-size-api", False,
            "jit._cache_size unavailable on this jax version"))
        return {}
    srv.warmup()
    n_warm = _cache_size(srv._fn)
    checks.append(_check(
        "serve", "bucket-executables==bucket-count",
        n_warm == len(srv.buckets),
        f"cache={n_warm} buckets={list(srv.buckets)}"))
    # 100 mixed-size requests over every bucket: the executable count
    # must not move (steady-state serving performs zero recompiles)
    srv.start()
    rng = np.random.RandomState(7)
    futs = [srv.submit(rng.rand(1 + int(rng.randint(8)), 1, 1, 36)
                       .astype(np.float32))
            for _ in range(100)]
    for f in futs:
        f.result(timeout=120)
    stats = srv.stop()
    n_after = _cache_size(srv._fn)
    checks.append(_check(
        "serve", "no-recompile-over-100-mixed-requests",
        n_after == n_warm,
        f"cache {n_warm} -> {n_after} after {stats['batches']} "
        f"batches / {stats['rows']} rows"))
    checks.append(_check(
        "serve", "no-dispatch-errors", stats["errors"] == 0,
        f"{stats['errors']} dispatch errors"))
    # executable introspection plane (telemetry/flight.py,
    # docs/OBSERVABILITY.md "/executables"): warmup must have
    # registered exactly one registry entry per bucket executable,
    # each stamped with its compile wall-time and counting the storm's
    # dispatches - an empty or stale registry would blind the stall
    # dump to the serving path
    from cxxnet_tpu import telemetry
    by_fp = {e["fingerprint"]: e
             for e in telemetry.executables().snapshot()}
    want = {b: srv._exec_fp.get(b) for b in srv.buckets}
    missing = [b for b, fp in want.items() if fp not in by_fp]
    checks.append(_check(
        "serve", "executables-registry-lists-buckets", not missing,
        f"buckets missing from /executables registry: {missing}"
        if missing else f"{len(want)} bucket entries registered"))
    if not missing:
        no_compile = [b for b, fp in want.items()
                      if by_fp[fp]["compile_s"] is None]
        checks.append(_check(
            "serve", "executables-compile-walltime-recorded",
            not no_compile,
            f"buckets with no compile_s: {no_compile}" if no_compile
            else ""))
        n_disp = sum(by_fp[fp]["dispatches"] for fp in want.values())
        checks.append(_check(
            "serve", "executables-dispatch-counts-accumulate",
            n_disp >= stats["batches"],
            f"registry counts {n_disp} dispatches over "
            f"{stats['batches']} storm batches"))
    # artifact checks per bucket executable - donation asserted ABSENT
    # (a donated weight buffer would be freed under a concurrent
    # replica's dispatch); run AFTER the flatness checks so .lower()
    # cannot perturb the counted cache
    for b in srv.buckets:
        data = np.zeros((b, 1, 1, 36), np.float32)
        gdata, gextras = tr.stage_infer_rows(data, ())
        checks += _audit_executable(
            f"serve[b={b}]", srv._fn,
            (tr.state["params"], gdata, gextras), donated=False)
    return {"serve_infer_warm": n_warm, "serve_infer_after": n_after}


_CONF_BN = _CONF.replace(
    "layer[+1:sg1] = tanh",
    "layer[+1:bn1] = batch_norm:bn1\nlayer[+1:sg1] = tanh")


def _traced(jitfn, args):
    """(jaxpr_text, eqn_count, dot_count) of a jit's PRE-DCE trace -
    the program the pass pipeline is responsible for (jax's own jit
    DCE already prunes the LOWERED module, so lowered-size checks
    would pass with the passes off; measured in pass_smoke)."""
    tr = jitfn.trace(*args)
    eqns = tr.jaxpr.jaxpr.eqns
    return (str(tr.jaxpr), len(eqns),
            sum(1 for e in eqns
                if e.primitive.name == "dot_general"))


def _traced_prims(jitfn, args) -> Tuple[int, Dict[str, int]]:
    """(eqn_count, {primitive: count}) of a jit's PRE-DCE trace."""
    eqns = jitfn.trace(*args).jaxpr.jaxpr.eqns
    prims: Dict[str, int] = {}
    for e in eqns:
        prims[e.primitive.name] = prims.get(e.primitive.name, 0) + 1
    return len(eqns), prims


def _pass_audit(checks: List[Dict[str, Any]]) -> Dict[str, int]:
    """Audit the graph-pass pipeline: build the BN trainer twice
    (passes off / fold+dle on), calibrate the fold on a fixed batch,
    and assert the docstring's pass-audit contract."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    def build(extra: str = ""):
        tr = NetTrainer()
        for k, v in parse_config_string(_CONF_BN + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    off = build()
    on = build("graph_passes = fold_conv_bn,dead_layer_elim\n")
    on.calibrate_graph_passes(_batch(0))
    final = on.net_cfg.num_nodes - 1
    early = on.net.node_index("fc1")
    data = np.zeros((32, 1, 1, 36), np.float32)
    gdata, gextras = on.stage_infer_rows(data)
    fold_fn = on._infer_fn(final)
    args_on = (on.state["params"], gdata, gextras)
    gdo, geo = off.stage_infer_rows(data)
    args_off = (off.state["params"], gdo, geo)
    ftxt, feqns, fdots = _traced(fold_fn, args_on)
    utxt, ueqns, udots = _traced(off._infer_fn(final), args_off)
    checks.append(_check(
        "passes/fold", "no-bn-moment-ops",
        "rsqrt" not in ftxt and "rsqrt" in utxt,
        f"folded rsqrt={ftxt.count('rsqrt')}, unfolded "
        f"rsqrt={utxt.count('rsqrt')} (unfolded must carry it or "
        "this check is vacuous)"))
    checks.append(_check(
        "passes/fold", "strictly-smaller-traced-program",
        feqns < ueqns and fdots == udots,
        f"folded {feqns} eqns/{fdots} dots vs unfolded {ueqns}/"
        f"{udots} (fold removes the BN pipeline, never a matmul)"))
    dtxt, deqns, ddots = _traced(on._infer_fn(early), args_on)
    checks.append(_check(
        "passes/dle", "pruned-subgraph-absent",
        ddots == 1 and deqns < ueqns,
        f"early-node extract traces {ddots} matmul(s)/{deqns} eqns "
        f"(full graph: {udots}/{ueqns}) - the dead fc2/softmax tail "
        "must not be traced"))
    sizes: Dict[str, int] = {}
    if _cache_size(fold_fn) is None:
        checks.append(_check(
            "passes", "cache-size-api", False,
            "jit._cache_size unavailable on this jax version"))
        return sizes
    # steady state: full + padded-short predicts and repeated
    # extracts add no executables past the per-shape compile
    on.predict(_batch(70))
    on.predict(_batch(71, b=20))
    on.predict(_batch(72))
    on.extract_feature(_batch(73), "fc1")
    on.extract_feature(_batch(74, b=20), "fc1")
    sizes["pass_infer_final"] = _cache_size(on._infer_fn(final))
    sizes["pass_infer_early"] = _cache_size(on._infer_fn(early))
    checks.append(_check(
        "passes/fold", "zero-new-steady-state-executables",
        sizes["pass_infer_final"] == 1
        and sizes["pass_infer_early"] == 1,
        f"final-node cache={sizes['pass_infer_final']}, early-node "
        f"cache={sizes['pass_infer_early']} after full+short "
        "predicts and extracts (want 1 each - padding keeps the "
        "program shape static, folding adds nothing per dispatch)"))
    _new_pattern_audit(checks)
    return sizes


# fuse_activation workload: fullc + separate bias layer + relu - the
# chain whose standalone elementwise equations the fused node removes
_CONF_ACT = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+0] = bias:bs1
  init_bias = 0.05
layer[+1:r1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
silent = 1
seed = 7
"""

# merge_conv_1x1 workload: 3x3 conv feeding a 1x1 conv
_CONF_1X1 = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 4
  kernel_size = 3
  pad = 1
layer[+1:c2] = conv:c2
  nchannel = 6
  kernel_size = 1
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 5
"""

# cse_share workload: a primary and its share[...] sibling reading the
# SAME input node - provably identical, the dedupable duplicate
_CONF_CSE = """
netconfig=start
layer[0->a] = fullc:fc1
  nhidden = 8
  init_sigma = 0.1
layer[0->b] = share[fc1]
layer[a,b->c] = concat
layer[+1:fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,12
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
seed = 3
"""


def _new_pattern_audit(checks: List[Dict[str, Any]]) -> None:
    """Pass-audit legs for the PR-11 patterns (fuse_activation,
    merge_conv_1x1, cse_share), each asserted at the traced-jaxpr
    level against the same pipeline WITHOUT the pattern pass, and
    each vacuity-guarded: the off-trace must actually contain the
    pattern (the rsqrt-style guard) or the comparison proves
    nothing."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    def build(conf, extra=""):
        tr = NetTrainer()
        for k, v in parse_config_string(conf + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    def traces(conf, passes, shape):
        off = build(conf, "graph_passes = dead_layer_elim\n")
        on = build(conf, f"graph_passes = dead_layer_elim,{passes}\n")
        node = on.net_cfg.num_nodes - 1
        data = np.zeros(shape, np.float32)
        g, ge = on.stage_infer_rows(data)
        g2, ge2 = off.stage_infer_rows(data)
        e_on, p_on = _traced_prims(on._infer_fn(node),
                                   (on.state["params"], g, ge))
        e_off, p_off = _traced_prims(off._infer_fn(node),
                                     (off.state["params"], g2, ge2))
        gm_on = on._build_infer_graph(node)[2]
        gm_off = off._build_infer_graph(node)[2]
        return e_off, p_off, gm_off, e_on, p_on, gm_on

    # fuse_activation: strictly fewer equations, equal matmul count
    e_off, p_off, gm_off, e_on, p_on, gm_on = traces(
        _CONF_ACT, "fuse_activation", (32, 1, 1, 36))
    checks.append(_check(
        "passes/fuse_activation", "pattern-matched",
        len(gm_on.cfg.layers) < len(gm_off.cfg.layers),
        f"fused graph keeps {len(gm_on.cfg.layers)} layers vs "
        f"{len(gm_off.cfg.layers)} unfused - the bias+relu chain "
        "must actually fuse (vacuity guard)"))
    checks.append(_check(
        "passes/fuse_activation", "fewer-eqns-equal-matmuls",
        e_on < e_off and p_on.get("dot_general", 0)
        == p_off.get("dot_general", 0),
        f"fused {e_on} eqns/{p_on.get('dot_general', 0)} dots vs "
        f"unfused {e_off}/{p_off.get('dot_general', 0)} (fusion "
        "removes the standalone elementwise eqns, never a matmul)"))

    # merge_conv_1x1: exactly one data-path conv fewer
    _e_off, p_off, _gm_off, _e_on, p_on, gm_on = traces(
        _CONF_1X1, "merge_conv_1x1", (8, 3, 8, 8))
    co = p_off.get("conv_general_dilated", 0)
    cn = p_on.get("conv_general_dilated", 0)
    checks.append(_check(
        "passes/merge_conv_1x1", "one-conv-fewer",
        co >= 2 and cn == co - 1 and gm_on.merges,
        f"merged trace carries {cn} convs vs {co} unmerged (want "
        "exactly one fewer, with the unmerged trace carrying >= 2 - "
        "the vacuity guard - and a recorded merge site)"))

    # cse_share: the duplicate share's matmul disappears
    e_off, p_off, gm_off, e_on, p_on, gm_on = traces(
        _CONF_CSE, "cse_share", (8, 1, 1, 12))
    do = p_off.get("dot_general", 0)
    dn = p_on.get("dot_general", 0)
    checks.append(_check(
        "passes/cse_share", "duplicate-matmul-deduped",
        do >= 3 and dn == do - 1 and e_on < e_off
        and len(gm_on.cfg.layers) < len(gm_off.cfg.layers),
        f"deduped trace carries {dn} dots/{e_on} eqns vs {do}/"
        f"{e_off} undeduped (want one dot fewer; the undeduped "
        "trace must carry the duplicate - vacuity guard)"))

    # elim_reshape: the flatten layer's reshape equation disappears,
    # matmul/conv counts unchanged (pure graph cleanup)
    e_off, p_off, gm_off, e_on, p_on, gm_on = traces(
        _CONF_1X1, "elim_reshape", (8, 3, 8, 8))
    ro = p_off.get("reshape", 0)
    rn = p_on.get("reshape", 0)
    checks.append(_check(
        "passes/elim_reshape", "fewer-eqns-equal-matmuls",
        e_on < e_off and rn == ro - 1 and ro >= 1
        and p_on.get("dot_general", 0) == p_off.get("dot_general", 0)
        and p_on.get("conv_general_dilated", 0)
        == p_off.get("conv_general_dilated", 0)
        and len(gm_on.cfg.layers) < len(gm_off.cfg.layers),
        f"elim trace carries {rn} reshapes/{e_on} eqns vs {ro}/"
        f"{e_off} (want one reshape fewer at equal matmul/conv "
        "counts; the off trace must carry the flatten - vacuity "
        "guard)"))


def _data_path_dots(jitfn, args, batch: int) -> Tuple[int, int]:
    """(int8_dots, float_dots) among the DATA-PATH contractions of a
    jit's PRE-DCE trace: dot_general/conv_general_dilated equations
    whose output's leading dim is the batch. Weight-side dots (the
    1x1-merge contraction, fold arithmetic) are weight-shaped and
    excluded - quantization's claim is about the data path only."""
    eqns = jitfn.trace(*args).jaxpr.jaxpr.eqns
    i8 = fp = 0
    for e in eqns:
        if e.primitive.name not in ("dot_general",
                                    "conv_general_dilated"):
            continue
        out = e.outvars[0].aval
        if not out.shape or out.shape[0] != batch:
            continue
        dts = {str(v.aval.dtype) for v in e.invars}
        if dts == {"int8"} and str(out.dtype) == "int32":
            i8 += 1
        elif any(d.startswith(("float", "bfloat")) for d in dts):
            fp += 1
    return i8, fp


_QUANT_PASSES = "dead_layer_elim,fold_conv_bn,quantize_int8"


def _quant_audit(checks: List[Dict[str, Any]]) -> Dict[str, int]:
    """Audit the int8 PTQ path (module docstring): int8 operands +
    int32 accumulation on every eligible data-path matmul of the
    quantized trace, zero float data-path dots (vacuity-guarded
    against the float trace), `layer_quant = float` pin honored, and
    quantized serving zero-recompile after calibration."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve import Server
    from cxxnet_tpu.utils.config import parse_config_string

    def build(extra: str = "", conf: str = _CONF_BN):
        tr = NetTrainer()
        for k, v in parse_config_string(conf + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    off = build("graph_passes = dead_layer_elim,fold_conv_bn\n")
    on = build(f"graph_passes = {_QUANT_PASSES}\n")
    pin = build(f"graph_passes = {_QUANT_PASSES}\n",
                conf=_CONF_BN.replace(
                    "nhidden = 3",
                    "nhidden = 3\n  layer_quant = float"))
    cal = _batch(0)
    for tr in (off, on, pin):
        tr.calibrate_graph_passes(cal)
    final = on.net_cfg.num_nodes - 1
    data = np.zeros((32, 1, 1, 36), np.float32)

    def dots(tr):
        g, ge = tr.stage_infer_rows(data)
        return _data_path_dots(tr._infer_fn(final),
                               (tr.state["params"], g, ge), 32)

    i8_on, fp_on = dots(on)
    i8_off, fp_off = dots(off)
    checks.append(_check(
        "quant", "int8-data-path-engaged",
        i8_on == 2 and fp_on == 0,
        f"quantized trace: {i8_on} int8/int32 data-path dots, "
        f"{fp_on} float (want 2 and 0 - both fullc layers must "
        "route through ops/int8.py)"))
    checks.append(_check(
        "quant", "float-trace-vacuity-guard",
        i8_off == 0 and fp_off == 2,
        f"float (fold-only) trace: {i8_off} int8 / {fp_off} float "
        "data-path dots (want 0 and 2, or the engagement check "
        "proves nothing)"))
    i8_pin, fp_pin = dots(pin)
    checks.append(_check(
        "quant", "layer_quant-float-pin-honored",
        i8_pin == 1 and fp_pin == 1,
        f"pinned trace: {i8_pin} int8 / {fp_pin} float data-path "
        "dots (want 1 each - fc2's explicit float pin must survive "
        "while fc1 quantizes)"))

    # quantized serving: calibrate BEFORE the Server pins its
    # executable, then the warmed bucket set must stay flat over a
    # mixed-size storm (the serve-audit contract on the int8 path)
    sizes: Dict[str, int] = {}
    srv = Server(on, max_batch=8, max_wait_ms=1.0, replicas=2)
    if _cache_size(srv._fn) is None:
        checks.append(_check(
            "quant/serve", "cache-size-api", False,
            "jit._cache_size unavailable on this jax version"))
        return sizes
    srv.warmup()
    n_warm = _cache_size(srv._fn)
    b8, ge8 = on.stage_infer_rows(np.zeros((8, 1, 1, 36), np.float32))
    i8_srv, fp_srv = _data_path_dots(
        srv._fn, (on.state["params"], b8, ge8), 8)
    checks.append(_check(
        "quant/serve", "bucket-executables-int8-engaged",
        i8_srv == 2 and fp_srv == 0
        and _cache_size(srv._fn) == n_warm,
        f"bucket-8 trace: {i8_srv} int8 / {fp_srv} float data-path "
        "dots (tracing must not add executables either)"))
    srv.start()
    rng = np.random.RandomState(11)
    futs = [srv.submit(rng.rand(1 + int(rng.randint(8)), 1, 1, 36)
                       .astype(np.float32))
            for _ in range(30)]
    for f in futs:
        f.result(timeout=120)
    stats = srv.stop()
    n_after = _cache_size(srv._fn)
    checks.append(_check(
        "quant/serve", "zero-recompile-after-calibration",
        n_warm == len(srv.buckets) and n_after == n_warm
        and stats["errors"] == 0,
        f"cache {n_warm} -> {n_after} over {stats['batches']} "
        f"batches (buckets={list(srv.buckets)}, "
        f"errors={stats['errors']})"))
    sizes["quant_serve_warm"] = n_warm
    sizes["quant_serve_after"] = n_after
    return sizes


def _recompile_audit(checks: List[Dict[str, Any]]) -> Dict[str, int]:
    tr = _make_trainer()
    if _cache_size(tr._train_step) is None:
        checks.append(_check(
            "recompile", "cache-size-api", False,
            "jit._cache_size unavailable on this jax version"))
        return {}

    def round_of(k: int, n: int) -> None:
        """One training pass: n batches dispatched in chunks of k
        with the round-boundary short-chunk flush (main.py's loop)."""
        pending = []
        for i in range(n):
            pending.append(_batch(i))
            if len(pending) >= k:
                tr.update_chunk(pending)
                pending = []
        if pending:
            tr.update_chunk(pending)

    # round 1: 9 batches at K=4 -> chunks 4+4+1 (short final chunk)
    round_of(4, 9)
    sizes = {"train_chunk_round1": _cache_size(tr._train_chunk)}
    checks.append(_check(
        "recompile", "chunk-cache==2 after 4+4+1 round",
        sizes["train_chunk_round1"] == 2,
        f"cache={sizes['train_chunk_round1']} (want 2: one K=4 "
        f"executable + one short-chunk K=1)"))
    # round 2, same shape mix: NO new executables
    round_of(4, 9)
    sizes["train_chunk_round2"] = _cache_size(tr._train_chunk)
    checks.append(_check(
        "recompile", "chunk-cache stable across rounds",
        sizes["train_chunk_round2"] == sizes["train_chunk_round1"],
        f"cache={sizes['train_chunk_round2']} after round 2"))

    # streamed path: full batch + SHORT batch (padded to static
    # shape) must share one train_step executable
    tr.update(_batch(50))
    tr.update(_batch(51, b=20))
    sizes["train_step"] = _cache_size(tr._train_step)
    checks.append(_check(
        "recompile", "step-cache==1 incl. padded short batch",
        sizes["train_step"] == 1,
        f"cache={sizes['train_step']} (padding must keep the "
        f"program shape static)"))

    # inference executable (the predict/extract/serve split): full +
    # short batch pad to ONE program shape
    tr.predict(_batch(60))
    tr.predict(_batch(61, b=20))
    nfin = tr.net_cfg.num_nodes - 1
    sizes["infer_step"] = _cache_size(tr._infer_fn(nfin))
    checks.append(_check(
        "recompile", "infer-cache==1 incl. padded short batch",
        sizes["infer_step"] == 1, f"cache={sizes['infer_step']}"))
    return sizes


# ---------------------------------------------------------------------------
# executable introspection plane (telemetry/flight.py)
# ---------------------------------------------------------------------------
def _executables_audit(checks: List[Dict[str, Any]]) -> None:
    """The sections above dispatched real train/infer/serve
    executables, so the process-wide executable registry
    (`/executables`, docs/OBSERVABILITY.md) must be NON-EMPTY with a
    stable entry schema and accumulated dispatch counts - the
    vacuity-guard stance of the other audits: an introspection plane
    that registers nothing would pass every per-entry check."""
    from cxxnet_tpu import telemetry
    execs = telemetry.executables().snapshot()
    checks.append(_check(
        "executables", "registry-non-empty", len(execs) > 0,
        f"{len(execs)} registered executables"))
    kinds = {e["kind"] for e in execs}
    checks.append(_check(
        "executables", "covers-train-infer-serve",
        {"train", "infer", "serve"} <= kinds,
        f"kinds registered: {sorted(kinds)}"))
    required = {"fingerprint", "name", "kind", "shape", "arg_bytes",
                "device", "donated", "compile_s", "flops",
                "cost_bytes", "out_bytes", "dispatches", "dispatch_s",
                "last_used_ts"}
    bad = [e.get("name", "?") for e in execs
           if not required <= set(e)]
    checks.append(_check(
        "executables", "entry-schema", not bad,
        f"entries missing schema fields: {bad[:5]}" if bad else
        f"all {len(execs)} entries carry the full schema"))
    dispatched = sum(1 for e in execs if e["dispatches"] > 0)
    checks.append(_check(
        "executables", "dispatch-counts-accumulate", dispatched > 0,
        f"{dispatched}/{len(execs)} entries saw dispatches"))
    donated = [e for e in execs if e["kind"] == "train"]
    checks.append(_check(
        "executables", "train-donation-footprint-recorded",
        bool(donated) and all(e["donated"] for e in donated),
        f"{len(donated)} train entries, donated="
        f"{[e['donated'] for e in donated]}"))


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------
def run_audit() -> Dict[str, Any]:
    """Trace + compile the representative executables and run every
    artifact check. Returns {platform, checks, cache_sizes}."""
    import jax
    from cxxnet_tpu.parallel import distributed

    checks: List[Dict[str, Any]] = []
    tr = _make_trainer()
    sb = tr.stage_batch(_batch(0))
    rng = jax.random.PRNGKey(0)

    checks += _audit_executable(
        "train_step", tr._train_step,
        (tr.state, sb.data, sb.extras, sb.labels, sb.mask, rng),
        donated=True)

    for k in (1, 4):
        chunk = tr.stage_chunk([_batch(i) for i in range(k)])
        step_idx = distributed.put_global(
            np.arange(k, dtype=np.int32), tr._replicated)
        checks += _audit_executable(
            f"train_chunk[K={k}]", tr._train_chunk,
            (tr.state, chunk.data, chunk.extras, chunk.labels,
             chunk.mask, step_idx, rng),
            donated=True)

    checks += _audit_executable(
        "eval_step", tr._eval_step,
        (tr.state["params"], sb.data, sb.extras), donated=False)
    if tr._eval_metric_step is not None:
        checks += _audit_executable(
            "eval_metric_step", tr._eval_metric_step,
            (tr.state["params"], sb.data, sb.extras, sb.labels,
             sb.mask, rng), donated=False)
    # the dedicated inference executable (predict/extract/serve all
    # share it - docs/SERVING.md); the serve audit below additionally
    # covers its bucket-shaped instantiations
    checks += _audit_executable(
        "infer_step", tr._infer_fn(tr.net_cfg.num_nodes - 1),
        (tr.state["params"], sb.data, sb.extras), donated=False)

    _zero_audit(checks)
    cache_sizes = _recompile_audit(checks)
    cache_sizes.update(_serve_audit(checks))
    cache_sizes.update(_pass_audit(checks))
    cache_sizes.update(_quant_audit(checks))
    _executables_audit(checks)
    return {
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "checks": checks,
        "cache_sizes": cache_sizes,
        "failed": sum(1 for c in checks if not c["ok"]),
    }
