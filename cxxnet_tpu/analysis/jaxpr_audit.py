"""graftlint tier 2: audit the LOWERED artifact, not the source.

Tier 1 trusts what the Python says; this tier inspects what we
actually dispatch (the TVM/Relay argument - PAPERS.md): trace the
real jitted executables of a representative trainer and assert on
the jaxpr + StableHLO + compiled HLO:

- **no-f64**: no float64 anywhere in the lowered module. An
  accidental x64 leak (np.float64 scalar, JAX_ENABLE_X64 drift)
  doubles bandwidth and silently changes trajectories.
- **no-host-callback**: no `custom_call` to a python/io callback and
  no infeed/outfeed - a host round-trip inside the step caps
  throughput at the host, invisibly.
- **donation-applied**: `donate_argnums` plumbed all the way through:
  donated params carry `tf.aliasing_output` in the lowered module AND
  the compiled HLO has a non-empty `input_output_alias` table. jax
  only *warns* when donation is dropped; this makes it a CI failure.
  (Non-donating executables are asserted alias-free, so the check
  cannot pass vacuously.)
- **no-captured-consts**: no weight-sized arrays baked into the
  executable as constants (params must arrive as ARGUMENTS - a
  captured weight re-embeds per compile and defeats donation).
- **recompile-audit**: the executable count stays bounded across a
  simulated round WITH a short final chunk - the PR 3 program-shape
  trap: `steps_per_dispatch=K` retraces once per distinct chunk
  length, so a round of 4+4+1 must cost exactly 2 `_train_chunk`
  lowering cache entries (K=4 and the K=1 flush), stable across
  rounds; padded short batches must NOT add `train_step`/eval
  entries.

Audited executables: `train_step`, `_train_chunk` (K=1 and K=4), and
the eval pair (`eval_step`, `eval_metric_step`), over the tiny-MLP
config the fused-dispatch smoke uses. Run under `JAX_PLATFORMS=cpu`
in CI; the checks are artifact-level, so they hold for any backend
that compiles the same programs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# weight-sized constant bound: the tiny net's legitimate lowering
# constants (iota tables, padding masks) stay well under this; its
# smallest weight (fc1: 36x16 f32) is 2.3 KiB and a captured one
# grows with the model - 4 KiB separates the two populations
_CONST_BYTES_MAX = 4096

_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
metric = error
eval_train = 1
silent = 1
seed = 7
"""


def _make_trainer():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    tr = NetTrainer()
    for k, v in parse_config_string(_CONF):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _batch(i: int, b: int = 32):
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(100 + i)
    return DataBatch(
        data=rng.rand(b, 1, 1, 36).astype(np.float32),
        label=(rng.randint(0, 3, size=(b, 1))
               .astype(np.float32)))


# ---------------------------------------------------------------------------
# artifact checks
# ---------------------------------------------------------------------------
def _check(target: str, check: str, ok: bool,
           detail: str = "") -> Dict[str, Any]:
    return {"target": target, "check": check, "ok": bool(ok),
            "detail": detail}


_F64_RE = re.compile(r"\bf64\b|xf64>|tensor<f64>")
_CALLBACK_RE = re.compile(
    r"custom_call[^\n]*(callback|py_func)|infeed|outfeed",
    re.IGNORECASE)


def _audit_executable(target: str, jitfn, args: Tuple,
                      donated: bool) -> List[Dict[str, Any]]:
    checks: List[Dict[str, Any]] = []
    lowered = jitfn.lower(*args)
    text = lowered.as_text()

    hits = _F64_RE.findall(text)
    checks.append(_check(
        target, "no-f64", not hits,
        f"{len(hits)} f64 type(s) in lowered module" if hits else ""))

    cb = _CALLBACK_RE.search(text)
    checks.append(_check(
        target, "no-host-callback", cb is None,
        f"host transfer in lowered module: {cb.group(0)[:60]}"
        if cb else ""))

    n_alias = text.count("tf.aliasing_output")
    ctext = lowered.compile().as_text()
    has_compiled_alias = ("input_output_alias={" in ctext
                          and "input_output_alias={}" not in ctext)
    if donated:
        checks.append(_check(
            target, "donation-applied",
            n_alias > 0 and has_compiled_alias,
            f"{n_alias} aliased params in lowered module; compiled "
            f"alias table {'present' if has_compiled_alias else 'MISSING'}"))
    else:
        checks.append(_check(
            target, "no-spurious-donation",
            n_alias == 0,
            f"{n_alias} aliased params on a non-donating executable"
            if n_alias else ""))

    consts: List = []
    try:
        consts = list(jitfn.trace(*args).jaxpr.consts)
    except AttributeError:
        # .trace needs jax >= 0.4.27; fall back to "unverifiable"
        checks.append(_check(
            target, "no-captured-consts", False,
            "jit .trace() unavailable on this jax - cannot audit "
            "captured constants"))
        return checks
    big = [c for c in consts
           if getattr(c, "nbytes", 0) > _CONST_BYTES_MAX]
    checks.append(_check(
        target, "no-captured-consts", not big,
        (f"{len(big)} constant(s) over {_CONST_BYTES_MAX} B captured "
         f"(largest {max(c.nbytes for c in big)} B) - weights must "
         "be arguments") if big else
        f"{len(consts)} small consts"))
    return checks


# ---------------------------------------------------------------------------
# recompile audit (the PR 3 program-shape trap)
# ---------------------------------------------------------------------------
def _cache_size(jitfn) -> Optional[int]:
    fn = getattr(jitfn, "_cache_size", None)
    return fn() if callable(fn) else None


def _recompile_audit(checks: List[Dict[str, Any]]) -> Dict[str, int]:
    tr = _make_trainer()
    if _cache_size(tr._train_step) is None:
        checks.append(_check(
            "recompile", "cache-size-api", False,
            "jit._cache_size unavailable on this jax version"))
        return {}

    def round_of(k: int, n: int) -> None:
        """One training pass: n batches dispatched in chunks of k
        with the round-boundary short-chunk flush (main.py's loop)."""
        pending = []
        for i in range(n):
            pending.append(_batch(i))
            if len(pending) >= k:
                tr.update_chunk(pending)
                pending = []
        if pending:
            tr.update_chunk(pending)

    # round 1: 9 batches at K=4 -> chunks 4+4+1 (short final chunk)
    round_of(4, 9)
    sizes = {"train_chunk_round1": _cache_size(tr._train_chunk)}
    checks.append(_check(
        "recompile", "chunk-cache==2 after 4+4+1 round",
        sizes["train_chunk_round1"] == 2,
        f"cache={sizes['train_chunk_round1']} (want 2: one K=4 "
        f"executable + one short-chunk K=1)"))
    # round 2, same shape mix: NO new executables
    round_of(4, 9)
    sizes["train_chunk_round2"] = _cache_size(tr._train_chunk)
    checks.append(_check(
        "recompile", "chunk-cache stable across rounds",
        sizes["train_chunk_round2"] == sizes["train_chunk_round1"],
        f"cache={sizes['train_chunk_round2']} after round 2"))

    # streamed path: full batch + SHORT batch (padded to static
    # shape) must share one train_step executable
    tr.update(_batch(50))
    tr.update(_batch(51, b=20))
    sizes["train_step"] = _cache_size(tr._train_step)
    checks.append(_check(
        "recompile", "step-cache==1 incl. padded short batch",
        sizes["train_step"] == 1,
        f"cache={sizes['train_step']} (padding must keep the "
        f"program shape static)"))

    # eval executable: full + short batch, one program
    tr.predict(_batch(60))
    tr.predict(_batch(61, b=20))
    sizes["eval_step"] = _cache_size(tr._eval_step)
    checks.append(_check(
        "recompile", "eval-cache==1 incl. padded short batch",
        sizes["eval_step"] == 1, f"cache={sizes['eval_step']}"))
    return sizes


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------
def run_audit() -> Dict[str, Any]:
    """Trace + compile the representative executables and run every
    artifact check. Returns {platform, checks, cache_sizes}."""
    import jax
    from cxxnet_tpu.parallel import distributed

    checks: List[Dict[str, Any]] = []
    tr = _make_trainer()
    sb = tr.stage_batch(_batch(0))
    rng = jax.random.PRNGKey(0)

    checks += _audit_executable(
        "train_step", tr._train_step,
        (tr.state, sb.data, sb.extras, sb.labels, sb.mask, rng),
        donated=True)

    for k in (1, 4):
        chunk = tr.stage_chunk([_batch(i) for i in range(k)])
        step_idx = distributed.put_global(
            np.arange(k, dtype=np.int32), tr._replicated)
        checks += _audit_executable(
            f"train_chunk[K={k}]", tr._train_chunk,
            (tr.state, chunk.data, chunk.extras, chunk.labels,
             chunk.mask, step_idx, rng),
            donated=True)

    checks += _audit_executable(
        "eval_step", tr._eval_step,
        (tr.state["params"], sb.data, sb.extras), donated=False)
    if tr._eval_metric_step is not None:
        checks += _audit_executable(
            "eval_metric_step", tr._eval_metric_step,
            (tr.state["params"], sb.data, sb.extras, sb.labels,
             sb.mask, rng), donated=False)

    cache_sizes = _recompile_audit(checks)
    return {
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "checks": checks,
        "cache_sizes": cache_sizes,
        "failed": sum(1 for c in checks if not c["ok"]),
    }
