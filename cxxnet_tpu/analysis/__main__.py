"""graftlint CLI - the blocking CI gate (docs/STATIC_ANALYSIS.md).

    python -m cxxnet_tpu.analysis [paths...] [options]

Modes (combinable; each contributes to the exit code and the JSON
report):

  paths...              tier-1 AST lint over .py trees (default mode;
                        with no paths, lints the cxxnet_tpu package)
  --check-configs DIR   config schema sweep: every *.conf under DIR
                        validated against the generated key registry
  --jaxpr-audit         tier-2: trace the real train/eval executables
                        and assert on the lowered artifact (imports
                        jax - run under JAX_PLATFORMS=cpu in CI)
  --lock-audit          concurrency tier-2: run the serve-storm /
                        prefetch-round / watchdog-stall scenarios
                        under the lock shim and assert an acyclic
                        lock-order graph, no lock held across a jax
                        dispatch boundary, and non-vacuous coverage
                        (docs/STATIC_ANALYSIS.md)

Options:

  --json FILE           write the combined machine-readable report
  --rules GL001,GL004   restrict tier-1 to a rule subset
  --show-waived         list waived findings in the text output
  --list-rules          print the rule catalog and exit
  --dump-keys           print the generated config-key registry
  --max-seconds S       fail if the tier-1 lint exceeded S seconds
                        (the CI perf budget for the analysis pass)
  --lock-audit-scenarios a,b
                        restrict the lock audit to a scenario subset
  --lock-audit-max-seconds S
                        fail if the lock audit exceeded S seconds
  --seed-inversion      inject the deliberate two-lock ABBA fixture
                        into the lock audit - the gate's self-test
                        (the audit MUST then exit non-zero; CI runs
                        this leg and asserts the failure)

Exit codes: 0 = clean (zero unwaived findings, all audit checks
pass), 1 = findings/audit failures, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from cxxnet_tpu.analysis import schema
from cxxnet_tpu.analysis.astlint import (
    RULES, lint_paths, render_text)

_PKG = os.path.dirname(os.path.abspath(__file__))
_DEFAULT_PATH = os.path.dirname(_PKG)


def _find_confs(root: str) -> List[str]:
    if os.path.isfile(root):
        return [root]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith(".")]
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".conf"))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cxxnet_tpu.analysis",
        description="graftlint: framework-aware static analysis")
    ap.add_argument("paths", nargs="*", help="python trees to lint")
    ap.add_argument("--check-configs", action="append", default=[],
                    metavar="DIR")
    ap.add_argument("--jaxpr-audit", action="store_true")
    ap.add_argument("--lock-audit", action="store_true")
    ap.add_argument("--lock-audit-scenarios", default="",
                    metavar="a,b")
    ap.add_argument("--lock-audit-max-seconds", type=float,
                    default=0.0)
    ap.add_argument("--seed-inversion", action="store_true")
    ap.add_argument("--json", dest="json_out", default="")
    ap.add_argument("--rules", default="")
    ap.add_argument("--show-waived", action="store_true")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--dump-keys", action="store_true")
    ap.add_argument("--max-seconds", type=float, default=0.0)
    args = ap.parse_args(argv)
    if args.seed_inversion and not args.lock_audit:
        print("--seed-inversion requires --lock-audit")
        return 2

    if args.list_rules:
        for rid, name in sorted(RULES.items()):
            print(f"{rid}  {name}")
        return 0
    if args.dump_keys:
        reg = schema.get_registry()
        for key in sorted(reg.exact):
            print(f"{key:28s} {reg.exact[key][0]}")
        for pfx, where in reg.prefixes:
            print(f"{pfx + '*':28s} {where}")
        for rx, where in reg.patterns:
            print(f"{rx.pattern:28s} {where}")
        return 0

    report = {}
    failed = False

    # -- tier 1: AST lint ---------------------------------------------------
    run_lint = bool(args.paths) or not (args.check_configs
                                        or args.jaxpr_audit
                                        or args.lock_audit)
    if run_lint:
        paths = args.paths or [_DEFAULT_PATH]
        # a missing path or an empty tree must FAIL, not vacuously
        # pass - a renamed package would otherwise turn the blocking
        # CI gate green-and-useless forever
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"graftlint: path(s) do not exist: {missing}")
            return 2
        rules = [r.strip() for r in args.rules.split(",")
                 if r.strip()] or None
        findings, n_files, elapsed = lint_paths(paths, rules)
        if n_files == 0:
            print(f"graftlint: no .py files under {paths} - "
                  "refusing to pass an empty scan")
            return 2
        print(render_text(findings, n_files, elapsed,
                          show_waived=args.show_waived))
        unwaived = [f for f in findings if not f.waived]
        report["lint"] = {
            "files": n_files, "elapsed_s": round(elapsed, 3),
            "findings": [f.to_dict() for f in findings],
            "unwaived": len(unwaived),
            "waived": sum(1 for f in findings if f.waived),
        }
        if unwaived:
            failed = True
        if args.max_seconds and elapsed > args.max_seconds:
            print(f"graftlint: FAIL - lint took {elapsed:.2f}s, "
                  f"budget is {args.max_seconds:.0f}s")
            report["lint"]["over_budget"] = True
            failed = True

    # -- config schema sweep ------------------------------------------------
    if args.check_configs:
        missing = [r for r in args.check_configs
                   if not os.path.exists(r)]
        if missing:
            print(f"config-schema: path(s) do not exist: {missing}")
            return 2
        confs = []
        for root in args.check_configs:
            confs.extend(_find_confs(root))
        if not confs:
            print(f"config-schema: no .conf files under "
                  f"{args.check_configs} - refusing to pass an "
                  "empty sweep")
            return 2
        results = []
        n_bad = 0
        for conf in confs:
            try:
                bad = schema.check_config_file(conf)
            except Exception as e:  # parse error is a finding too
                results.append({"conf": conf, "error": str(e)})
                n_bad += 1
                print(f"{conf}: parse error: {e}")
                continue
            results.append({"conf": conf, "unknown": [
                {"key": k, "suggestion": s} for k, s in bad]})
            for k, s in bad:
                n_bad += 1
                hint = f" (did you mean '{s}'?)" if s else ""
                print(f"{conf}: unknown config key '{k}'{hint}")
        print(f"config-schema: {len(confs)} conf file(s), "
              f"{n_bad} unknown key(s)")
        report["configs"] = {"files": len(confs), "unknown": n_bad,
                             "results": results}
        if n_bad:
            failed = True

    # -- tier 2: jaxpr/HLO audit --------------------------------------------
    if args.jaxpr_audit:
        from cxxnet_tpu.analysis.jaxpr_audit import run_audit
        audit = run_audit()
        for chk in audit["checks"]:
            mark = "ok" if chk["ok"] else "FAIL"
            print(f"  [{mark}] {chk['target']}: {chk['check']}"
                  + (f" - {chk['detail']}" if chk.get("detail")
                     else ""))
        n_fail = sum(1 for c in audit["checks"] if not c["ok"])
        print(f"jaxpr-audit: {len(audit['checks'])} checks, "
              f"{n_fail} failed")
        report["audit"] = audit
        if n_fail:
            failed = True

    # -- concurrency tier 2: runtime lock audit -----------------------------
    if args.lock_audit:
        from cxxnet_tpu.analysis.lock_audit import run_lock_audit
        scen = tuple(s.strip()
                     for s in args.lock_audit_scenarios.split(",")
                     if s.strip()) or None
        try:
            audit = run_lock_audit(scenarios=scen,
                                   seed_inversion=args.seed_inversion)
        except ValueError as e:  # unknown scenario name = usage error
            print(f"lock-audit: {e}")
            return 2
        for chk in audit["checks"]:
            mark = "ok" if chk["ok"] else "FAIL"
            print(f"  [{mark}] {chk['target']}: {chk['check']}"
                  + (f" - {chk['detail']}" if chk.get("detail")
                     else ""))
        for site in audit["contended"]:
            print(f"  contended: {site['site']} "
                  f"({site['kind']}, x{site['instances']}) "
                  f"acq={site['acquisitions']} "
                  f"wait={site['wait_total_ms']:.1f}ms "
                  f"held_max={site['held_max_ms']:.1f}ms")
        print(f"lock-audit: {len(audit['checks'])} checks, "
              f"{audit['failed']} failed; {audit['sites']} lock "
              f"sites, {len(audit['edges'])} order edges, "
              f"{audit['elapsed_s']:.1f}s")
        report["lock_audit"] = audit
        if audit["failed"]:
            failed = True
        budget = args.lock_audit_max_seconds
        if budget and audit["elapsed_s"] > budget:
            print(f"lock-audit: FAIL - audit took "
                  f"{audit['elapsed_s']:.1f}s, budget is "
                  f"{budget:.0f}s")
            audit["over_budget"] = True
            failed = True

    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
