"""Concurrency tier 2: runtime lock-order / contention audit.

The GL01x lint rules (astlint.py) check lock DISCIPLINE in the
source; this module audits lock BEHAVIOR in the running process - the
same lint+live-audit split as graftlint's jaxpr tier (trust the
source, then verify the artifact). A shim wraps
``threading.Lock``/``threading.RLock`` *construction* (which also
covers ``Condition``, ``Event`` and every ``queue.Queue``, since the
stdlib builds them from the module-level factories at call time), so
every lock created while the auditor is installed records:

- the **per-thread acquisition sequence**: acquiring B while holding
  A adds the edge A -> B to the cross-thread lock-order graph. Nodes
  are lock INSTANCES (labeled ``site:line#n`` - the classic
  lock-order-graph semantics; two locks born on one line are still
  two locks), while contention stats aggregate per construction
  site. A CYCLE in that graph is an inconsistent acquisition order -
  two threads interleaving it deadlock - and fails the audit;
- **contention**: wall time spent waiting in ``acquire`` and the
  held-duration of every hold (``Condition.wait`` releases the lock
  via ``_release_save``, so a consumer parked on an empty queue does
  NOT count as holding its mutex). The report ranks the top
  contended locks and feeds ``lock.audit.*`` registry gauges
  (docs/OBSERVABILITY.md);
- **dispatch-boundary hygiene**: ``jax.block_until_ready`` /
  ``jax.device_put`` are wrapped while the shim is installed; either
  reached with ANY audited lock held is flagged - a lock held across
  a device sync serializes every other thread behind the accelerator
  (the runtime twin of GL002/GL015).

The audited paths are the real exercised ones, reusing the smoke
harnesses' shapes (docs/STATIC_ANALYSIS.md "Concurrency analysis"):

- ``serve-storm``: a live continuous-batching ``Server`` (2 replicas,
  warmed buckets, HTTP ingress + queue-limit admission + checkpoint
  watcher armed) under a ragged multi-thread request storm with a
  mid-storm /predict POST and a live checkpoint hot-swap;
- ``prefetch-round``: a ``StagedPrefetcher`` pass (chunked, plus a
  mid-stream close) - the io producer/consumer queue discipline;
- ``watchdog-stall``: a fresh telemetry instance with heartbeat +
  hang watchdog through a beacon-silence episode (stall dump,
  recovery) - the observability plane's thread mesh.

``--seed-inversion`` (CLI) injects a deliberate two-lock ABBA fixture
- the gate's self-test: the audit MUST fail on it, proving the cycle
detector is alive (CI runs both legs; the seeded one must exit
non-zero).

``python -m cxxnet_tpu.analysis --lock-audit`` runs everything and
exits non-zero on a cycle, a dispatch-boundary violation, a scenario
failure, or an empty audit (zero locks observed = the shim did not
engage; the gate refuses to pass vacuously).
"""

from __future__ import annotations

import os
import sys
import sysconfig
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# acquire waits above this count as contended (below is scheduler
# noise on an uncontended fast path)
CONTENDED_WAIT_S = 1e-4
_STDLIB_DIR = sysconfig.get_paths()["stdlib"]


def _thread_name() -> str:
    """Current thread's name WITHOUT threading.current_thread():
    during thread bootstrap (before the thread registers) that call
    constructs a _DummyThread whose Event.set() would re-enter the
    audited lock path - unbounded recursion. A raw peek at the
    registry is allocation-free and safe from any bootstrap stage."""
    ident = threading.get_ident()
    t = getattr(threading, "_active", {}).get(ident)
    return t.name if t is not None else f"thread-{ident}"


def _check(target: str, check: str, ok: bool,
           detail: str = "") -> Dict[str, Any]:
    return {"target": target, "check": check, "ok": bool(ok),
            "detail": detail}


# ---------------------------------------------------------------------------
# the shim
# ---------------------------------------------------------------------------
class _Site:
    """Aggregate stats for one lock construction site (one 'lock
    class': every Queue mutex born on queue.py's behalf is keyed by
    the USER frame that built the Queue)."""

    __slots__ = ("key", "kind", "instances", "acquisitions",
                 "contended", "wait_total", "wait_max", "held_total",
                 "held_max")

    def __init__(self, key: str, kind: str) -> None:
        self.key = key
        self.kind = kind
        self.instances = 0
        self.acquisitions = 0
        self.contended = 0
        self.wait_total = 0.0
        self.wait_max = 0.0
        self.held_total = 0.0
        self.held_max = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "site": self.key, "kind": self.kind,
            "instances": self.instances,
            "acquisitions": self.acquisitions,
            "contended": self.contended,
            "wait_total_ms": round(self.wait_total * 1e3, 3),
            "wait_max_ms": round(self.wait_max * 1e3, 3),
            "held_total_ms": round(self.held_total * 1e3, 3),
            "held_max_ms": round(self.held_max * 1e3, 3),
        }


class _AuditedLockBase:
    """Wrapper recording acquire/release through the auditor. The
    plain-Lock variant deliberately does NOT define
    ``_release_save``/``_acquire_restore``/``_is_owned`` -
    ``threading.Condition`` probes for them with ``hasattr`` and must
    fall back to its Lock-protocol defaults (which route through
    ``acquire``/``release`` here)."""

    __slots__ = ("_inner", "_site", "_uid", "_aud")

    def __init__(self, inner, site: _Site, seq: int,
                 aud: "LockAuditor") -> None:
        self._inner = inner
        self._site = site
        # instance node id in the order graph; `seq` was allotted
        # under the auditor's meta lock (reading site.instances here
        # would race concurrent constructions at the same site and
        # alias two locks onto one node - a false cycle)
        self._uid = f"{site.key}#{seq}"
        self._aud = aud

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._aud._on_acquired(self, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        self._aud._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<audited {self._site.kind} {self._site.key}>"


class AuditedLock(_AuditedLockBase):
    __slots__ = ()


class AuditedRLock(_AuditedLockBase):
    """RLock wrapper: reentrant acquires are counted so only the
    outermost acquire/release record (a nested with on the same RLock
    is not a new hold, and never an order edge). The Condition
    protocol trio wraps our bookkeeping state around the inner
    lock's, so a ``cond.wait()`` fully releases the hold in the audit
    exactly as it does in the runtime."""

    __slots__ = ()

    def _release_save(self):
        saved = self._aud._on_release_save(self)
        return (saved, self._inner._release_save())

    def _acquire_restore(self, state) -> None:
        saved, inner_state = state
        t0 = time.perf_counter()
        self._inner._acquire_restore(inner_state)
        self._aud._on_acquire_restore(self, saved,
                                      time.perf_counter() - t0)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


class _HeldEntry:
    __slots__ = ("lock", "t0", "count")

    def __init__(self, lock: _AuditedLockBase, t0: float) -> None:
        self.lock = lock
        self.t0 = t0
        self.count = 1


class LockAuditor:
    """Installable construction shim + the recorded graph/stats.

    Usage::

        aud = LockAuditor()
        with aud.installed():
            ... exercise real code paths ...
        report = aud.report()

    Bookkeeping runs under a REAL lock captured before installation,
    and the per-thread held stack lives in a ``threading.local`` - the
    auditor never acquires an audited lock itself, so it cannot
    deadlock with (or add edges to) the code under audit."""

    def __init__(self) -> None:
        self._meta = threading.Lock()   # real: created pre-install
        self._local = threading.local()
        self._sites: Dict[str, _Site] = {}
        # (from_site, to_site) -> {"count": n, "threads": set}
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._boundaries: List[Dict[str, Any]] = []
        self._boundary_seen: set = set()
        self._installed = False
        self._saved: Dict[str, Any] = {}

    # -- construction site attribution --------------------------------------
    def _site_for(self, kind: str) -> Tuple[_Site, int]:
        # frame 2 is the caller of threading.Lock()/RLock() (the
        # factory wrapper frames are below); walk out of stdlib
        # internals (queue.py, threading.Condition, ...) to the frame
        # that actually OWNS the lock
        f = sys._getframe(2)
        chosen = None
        hops = 0
        while f is not None and hops < 16:
            path = f.f_code.co_filename
            if chosen is None:
                chosen = f  # innermost as the fallback
            if not path.startswith(_STDLIB_DIR):
                chosen = f
                break
            f = f.f_back
            hops += 1
        path = chosen.f_code.co_filename if chosen else "?"
        for marker in ("/cxxnet_tpu/", "/tests/"):
            i = path.find(marker)
            if i >= 0:
                path = path[i + 1:]
                break
        else:
            path = os.path.basename(path)
        key = f"{path}:{chosen.f_lineno if chosen else 0}"
        with self._meta:
            site = self._sites.get(key)
            if site is None:
                site = self._sites[key] = _Site(key, kind)
            site.instances += 1
            return site, site.instances

    # -- factories (what threading.Lock/RLock become) ------------------------
    def _make_lock(self):
        real = self._saved["Lock"]
        site, seq = self._site_for("Lock")
        return AuditedLock(real(), site, seq, self)

    def _make_rlock(self):
        real = self._saved["RLock"]
        site, seq = self._site_for("RLock")
        return AuditedRLock(real(), site, seq, self)

    # -- install / uninstall -------------------------------------------------
    def install(self) -> "LockAuditor":
        if self._installed:
            return self
        self._saved["Lock"] = threading.Lock
        self._saved["RLock"] = threading.RLock
        threading.Lock = self._make_lock  # type: ignore[assignment]
        threading.RLock = self._make_rlock  # type: ignore[assignment]
        jax = sys.modules.get("jax")
        if jax is not None:
            for name in ("block_until_ready", "device_put"):
                fn = getattr(jax, name, None)
                if callable(fn):
                    self._saved[f"jax.{name}"] = fn
                    setattr(jax, name,
                            self._wrap_boundary(fn, f"jax.{name}"))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = self._saved["Lock"]
        threading.RLock = self._saved["RLock"]
        jax = sys.modules.get("jax")
        if jax is not None:
            for name in ("block_until_ready", "device_put"):
                fn = self._saved.get(f"jax.{name}")
                if fn is not None:
                    setattr(jax, name, fn)
        self._installed = False

    class _Installed:
        def __init__(self, aud: "LockAuditor") -> None:
            self.aud = aud

        def __enter__(self) -> "LockAuditor":
            return self.aud.install()

        def __exit__(self, *exc) -> bool:
            self.aud.uninstall()
            return False

    def installed(self) -> "_Installed":
        return LockAuditor._Installed(self)

    # -- event recording ------------------------------------------------------
    def _stack(self) -> List[_HeldEntry]:
        stack = getattr(self._local, "held", None)
        if stack is None:
            stack = self._local.held = []
        return stack

    def _on_acquired(self, lock: _AuditedLockBase,
                     waited: float) -> None:
        stack = self._stack()
        for ent in stack:
            if ent.lock is lock:
                ent.count += 1  # reentrant RLock: not a new hold
                return
        now = time.perf_counter()
        tname = _thread_name()
        with self._meta:
            site = lock._site
            site.acquisitions += 1
            site.wait_total += waited
            if waited > site.wait_max:
                site.wait_max = waited
            if waited > CONTENDED_WAIT_S:
                site.contended += 1
            for ent in stack:
                a, b = ent.lock._uid, lock._uid
                edge = self._edges.get((a, b))
                if edge is None:
                    edge = self._edges[(a, b)] = {
                        "count": 0, "threads": set()}
                edge["count"] += 1
                edge["threads"].add(tname)
        stack.append(_HeldEntry(lock, now))

    def _on_release(self, lock: _AuditedLockBase) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            ent = stack[i]
            if ent.lock is lock:
                ent.count -= 1
                if ent.count > 0:
                    return
                del stack[i]
                held = time.perf_counter() - ent.t0
                with self._meta:
                    site = lock._site
                    site.held_total += held
                    if held > site.held_max:
                        site.held_max = held
                return
        # released a lock acquired before installation: not audited

    def _on_release_save(self, lock: _AuditedLockBase) -> int:
        """Condition.wait path: the FULL reentrant hold drops."""
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            ent = stack[i]
            if ent.lock is lock:
                saved = ent.count
                ent.count = 1
                del stack[i]
                held = time.perf_counter() - ent.t0
                with self._meta:
                    site = lock._site
                    site.held_total += held
                    if held > site.held_max:
                        site.held_max = held
                return saved
        return 1

    def _on_acquire_restore(self, lock: _AuditedLockBase, saved: int,
                            waited: float) -> None:
        self._on_acquired(lock, waited)
        stack = self._stack()
        for ent in stack:
            if ent.lock is lock:
                ent.count = max(saved, 1)
                return

    def _wrap_boundary(self, fn: Callable, name: str) -> Callable:
        def inner(*args, **kwargs):
            self.boundary(name)
            return fn(*args, **kwargs)
        inner.__name__ = getattr(fn, "__name__", name)
        return inner

    def boundary(self, name: str) -> None:
        """Mark a JAX dispatch/host-sync boundary on this thread; any
        audited lock held here is a violation."""
        stack = self._stack()
        if not stack:
            return
        sites = tuple(sorted(ent.lock._uid for ent in stack))
        key = (name, sites)
        with self._meta:
            if key in self._boundary_seen:
                return
            self._boundary_seen.add(key)
            self._boundaries.append({
                "boundary": name,
                "thread": _thread_name(),
                "locks": list(sites),
            })

    # -- analysis -------------------------------------------------------------
    def find_cycle(self) -> Optional[List[str]]:
        """First cycle in the instance-level lock-order graph (None =
        acyclic). Iterative coloring DFS; the returned path is the
        cycle's node sequence, closed (first == last)."""
        with self._meta:
            graph: Dict[str, List[str]] = {}
            for (a, b) in self._edges:
                graph.setdefault(a, []).append(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        for root in sorted(graph):
            if color.get(root, WHITE) != WHITE:
                continue
            stack: List[Tuple[str, int]] = [(root, 0)]
            path = [root]
            color[root] = GRAY
            while stack:
                node, idx = stack[-1]
                succs = graph.get(node, ())
                if idx < len(succs):
                    stack[-1] = (node, idx + 1)
                    nxt = succs[idx]
                    c = color.get(nxt, WHITE)
                    if c == GRAY:
                        return path[path.index(nxt):] + [nxt]
                    if c == WHITE:
                        color[nxt] = GRAY
                        stack.append((nxt, 0))
                        path.append(nxt)
                else:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()
        return None

    def report(self, top: int = 5) -> Dict[str, Any]:
        with self._meta:
            sites = sorted(self._sites.values(),
                           key=lambda s: -s.wait_total)
            edges = [{"from": a, "to": b, "count": e["count"],
                      "threads": sorted(e["threads"])}
                     for (a, b), e in sorted(self._edges.items())]
            boundaries = list(self._boundaries)
        cycle = self.find_cycle()
        acquired = [s for s in sites if s.acquisitions]
        return {
            "sites": len(self._sites),
            "instances": sum(s.instances for s in sites),
            "acquisitions": sum(s.acquisitions for s in sites),
            "edges": edges,
            "cycle": cycle,
            "contended": [s.to_dict() for s in acquired[:top]],
            "max_held_ms": round(
                max((s.held_max for s in sites), default=0.0) * 1e3, 3),
            "max_wait_ms": round(
                max((s.wait_max for s in sites), default=0.0) * 1e3, 3),
            "boundary_violations": boundaries,
        }


# ---------------------------------------------------------------------------
# scenarios (the real exercised paths)
# ---------------------------------------------------------------------------
def _scenario_prefetch_round(aud: LockAuditor) -> List[Dict[str, Any]]:
    """StagedPrefetcher pass: chunked staging (12 chunks of 4), a
    full drain, then a second pass abandoned mid-stream (close() -
    the drain-while-join shutdown discipline)."""
    import numpy as np

    from cxxnet_tpu.io.prefetch import StagedPrefetcher

    class _Src:
        def __init__(self, n: int) -> None:
            self.n = n
            self.i = 0

        def before_first(self) -> None:
            self.i = 0

        def next(self) -> bool:
            self.i += 1
            return self.i <= self.n

        def value(self):
            return np.full((8,), float(self.i), np.float32)

    def stage(batch):
        time.sleep(0.0005)  # a visible stage cost, so the queue works
        return batch * 2.0

    pf = StagedPrefetcher(stage, _Src(48), depth=2, chunk=4,
                          chunk_fn=list)
    batches = 0
    pf.before_first()
    while pf.next():
        batches += len(pf.value())
    pf.before_first()
    for _ in range(3):
        pf.next()
    pf.close()
    return [_check("prefetch-round", "all-batches-delivered",
                   batches == 48, f"{batches}/48 batches")]


def _scenario_watchdog_stall(aud: LockAuditor) -> List[Dict[str, Any]]:
    """A fresh telemetry plane (heartbeat sink + hang watchdog)
    through a beacon-silence episode: beacons tick, go silent until
    the watchdog dumps and flips unhealthy, then recover."""
    import tempfile

    from cxxnet_tpu import telemetry as tmod
    from cxxnet_tpu.telemetry.watchdog import Watchdog

    checks: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory() as td:
        tel = tmod.Telemetry()
        tel.configure(log_file=os.path.join(td, "events.jsonl"),
                      metrics_file=os.path.join(td, "metrics.jsonl"),
                      heartbeat_secs=0.05)
        wd = Watchdog(tel, stall_secs=0.25, poll_secs=0.05,
                      startup_secs=0.25)
        wd.start()
        try:
            for _ in range(4):
                tel.beacon("train.step")
                tel.observe("train.step_s", 0.01)
                with tel.span("round"):
                    time.sleep(0.04)
            # wait for BOTH the stall flag and the health flip: the
            # flag is set before _dump finishes writing the stacks,
            # so polling the flag alone races the health source
            deadline = time.monotonic() + 5.0
            while (not (wd.stalled and not tel.health.ok)
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            stalled_seen = wd.stalled
            unhealthy = not tel.health.ok
            tel.beacon("train.step")
            deadline = time.monotonic() + 5.0
            while wd.stalled and time.monotonic() < deadline:
                time.sleep(0.02)
            recovered = not wd.stalled and tel.health.ok
        finally:
            wd.close()
            tel.close()
        checks.append(_check("watchdog-stall", "stall-dumped",
                             stalled_seen, "watchdog fired"))
        checks.append(_check("watchdog-stall", "health-flipped",
                             unhealthy, "/healthz source set"))
        checks.append(_check("watchdog-stall", "recovered",
                             recovered, "beacon cleared the stall"))
    return checks


_STORM_SIZES = (1, 2, 3, 5, 8, 13, 4, 1, 6, 2, 7, 1)


def _scenario_serve_storm(aud: LockAuditor,
                          trainer) -> List[Dict[str, Any]]:
    """A live continuous-batching Server under a ragged request storm
    from 3 submitter threads (splits, coalescing, padding, replica
    fan-out all exercised); every future must resolve. The production
    front rides along: the HTTP ingress thread answers a /predict
    POST mid-storm, the admission check runs with a (non-binding)
    queue_limit armed, the connection accept gate is saturated and
    released (serve_max_conns armed - the gate's own lock joins the
    graph), and the checkpoint watcher thread picks up a published
    checkpoint which the canary judge thread scores and promotes
    live - so the ingress/shed/swap/canary lock interactions all land
    in the audited graph."""
    import json as _json
    import socket as _socket
    import tempfile
    import urllib.request

    import numpy as np

    from cxxnet_tpu.nnet import checkpoint as _ckpt
    from cxxnet_tpu.serve.server import Server

    tmpd = tempfile.mkdtemp(prefix="lock_audit_serve_")
    saved = os.path.join(tmpd, "0001.model")
    with open(saved, "wb") as f:
        trainer.save_model(f)
    watch = os.path.join(tmpd, "publish.model")
    srv = Server(trainer, max_batch=8, max_wait_ms=2.0, replicas=2,
                 http_port=0, queue_limit=100000,
                 swap_watch=watch, swap_poll_ms=20.0,
                 canary_frac=0.5, canary_window=0.8, max_conns=2)
    srv.shed_clear_ms = 100.0
    rows_sent = 0
    errors: List[str] = []
    results: List[int] = []
    http_status = 0
    gate_rejected = False
    res_lock = threading.Lock()
    srv.warmup()
    with srv:
        def submitter(seed: int) -> None:
            rng = np.random.RandomState(seed)
            futs = []
            for n in _STORM_SIZES:
                data = rng.rand(n, 1, 1, 36).astype(np.float32)
                futs.append((n, srv.submit(data)))
            for n, fut in futs:
                try:
                    out = fut.result(timeout=60.0)
                    with res_lock:
                        results.append(out.shape[0])
                        if out.shape[0] != n:
                            errors.append(
                                f"rows {out.shape[0]} != {n}")
                except Exception as e:  # noqa: BLE001 - reported below
                    with res_lock:
                        errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=submitter, args=(s,),
                                    name=f"storm-{s}", daemon=True)
                   for s in (11, 22, 33)]
        for t in threads:
            t.start()
        # mid-storm: saturate the accept gate (max_conns=2) with two
        # held raw connections - a third must get the gate's raw 503 -
        # then release; the gate lock's enter/leave/recover traffic
        # joins the audited graph under real load
        port = srv.metrics_server.port
        held = []
        try:
            for _ in range(2):
                h = _socket.create_connection(
                    ("127.0.0.1", port), timeout=10)
                h.sendall(b"GET /healthz HTTP/1.0\r\nX-Hold")
                held.append(h)
            time.sleep(0.2)
            probe = _socket.create_connection(
                ("127.0.0.1", port), timeout=10)
            probe.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
            probe.settimeout(10.0)
            buf = b""
            try:
                while True:
                    chunk = probe.recv(4096)
                    if not chunk:
                        break
                    buf += chunk
            except OSError:
                pass
            probe.close()
            gate_rejected = b"503" in buf.split(b"\r\n")[0]
        except Exception as e:  # noqa: BLE001 - reported below
            with res_lock:
                errors.append(f"gate: {type(e).__name__}: {e}")
        finally:
            for h in held:
                h.close()
        # one /predict POST through the ingress thread (retried: the
        # just-released gate slots may take a beat to free) and one
        # checkpoint published to the watched path (same weights -
        # the full validate/stage/canary/promote path is what the
        # audit wants)
        body = _json.dumps({"data": [[0.1] * 36]}).encode()
        for attempt in range(5):
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/predict",
                    data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    http_status = r.status
                break
            except Exception as e:  # noqa: BLE001 - reported below
                if attempt == 4:
                    with res_lock:
                        errors.append(
                            f"http: {type(e).__name__}: {e}")
                time.sleep(0.3)
        _ckpt.publish_model(saved, watch)
        for t in threads:
            t.join(timeout=120.0)
        # a trickle keeps canary traffic + shadow samples flowing
        # until the judge reaches its verdict at the window
        rng = np.random.RandomState(44)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if srv.stats()["canary_promoted"] >= 1:
                break
            data = rng.rand(4, 1, 1, 36).astype(np.float32)
            try:
                srv.submit(data).result(timeout=60.0)
            except Exception as e:  # noqa: BLE001 - reported below
                with res_lock:
                    errors.append(
                        f"trickle: {type(e).__name__}: {e}")
                break
            time.sleep(0.02)
        alive = [t.name for t in threads if t.is_alive()]
        rows_sent = 3 * sum(_STORM_SIZES)
        # ingress counters live on the HTTP plane: snapshot them
        # before stop() closes it
        conn_rejected = srv.stats().get("conn_rejected", 0)
    stats = srv.stats()
    checks = [
        _check("serve-storm", "all-submitters-done", not alive,
               f"stuck: {alive}" if alive else "3 threads joined"),
        _check("serve-storm", "all-rows-answered",
               not errors and sum(results) == rows_sent,
               errors[0] if errors
               else f"{sum(results)}/{rows_sent} rows"),
        _check("serve-storm", "dispatches-ran",
               stats["batches"] > 0 and stats["errors"] == 0,
               f"{stats['batches']} batches, "
               f"{stats['errors']} errors"),
        _check("serve-storm", "http-ingress-answered",
               http_status == 200, f"status {http_status}"),
        _check("serve-storm", "checkpoint-hot-swapped",
               stats["swaps"] == 1 and stats["swap_rejected"] == 0,
               f"{stats['swaps']} swaps, "
               f"{stats['swap_rejected']} rejected"),
        _check("serve-storm", "canary-judge-promoted",
               stats["canary_promoted"] == 1
               and stats["canary_rolled_back"] == 0,
               f"{stats['canary_promoted']} promoted, "
               f"{stats['canary_rolled_back']} rolled back"),
        _check("serve-storm", "conn-gate-exercised",
               gate_rejected and conn_rejected >= 1,
               f"raw 503 seen: {gate_rejected}, "
               f"{conn_rejected} rejected"),
    ]
    return checks


def _scenario_elastic_coordinator(aud: LockAuditor) -> List[Dict[str, Any]]:
    """The elastic coordinator's threads (parallel/coordinator.py)
    under audit: two members' lease-heartbeat threads plus concurrent
    barrier() calls from their training threads - a completed barrier
    with a single elected leader, a publish, then a conviction (one
    member stops arriving). The coordinator is brand-new cross-thread
    code; this scenario keeps its lock order in the audited graph
    from day one (the acceptance gate of the elastic PR)."""
    import tempfile

    from cxxnet_tpu.parallel.coordinator import (
        ControlPlane, Coordinator, PodReshapeRequired)

    checks: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory() as td:
        plane = ControlPlane(td)
        c0 = Coordinator(plane, 0, [0, 1], barrier_secs=5.0,
                         lease_secs=0.2, poll_secs=0.01)
        c1 = Coordinator(plane, 1, [0, 1], barrier_secs=5.0,
                         lease_secs=0.2, poll_secs=0.01)
        results: Dict[int, Any] = {}
        errors: List[str] = []
        res_lock = threading.Lock()

        def trainer(coord: Coordinator) -> None:
            try:
                for rnd in range(3):
                    r = coord.barrier(rnd)
                    with res_lock:
                        results[(coord.member, rnd)] = r
                    if r.is_leader:
                        path = os.path.join(td, f"{rnd:04d}.model")
                        with open(path, "wb") as f:
                            f.write(b"x" * 16)
                        coord.publish(r, rnd, path, "0" * 64, 16)
            except Exception as e:  # noqa: BLE001 - reported below
                with res_lock:
                    errors.append(f"{type(e).__name__}: {e}")

        with c0, c1:
            threads = [threading.Thread(target=trainer, args=(c,),
                                        name=f"elastic-m{c.member}",
                                        daemon=True)
                       for c in (c0, c1)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            # conviction path: member 0 barriers alone at round 3
            c0.barrier_secs = 0.3
            try:
                c0.barrier(3)
                convicted = False
            except PodReshapeRequired as e:
                convicted = e.missing == [1]
            # heartbeats must have renewed leases while the barriers
            # ran (sampled after the conviction wait - the barriers
            # themselves can complete inside one renewal period)
            renewed = c0.renewals > 0 and c1.renewals > 0
        leaders = {r.leader for r in results.values()}
        publishers = [r for r in results.values() if r.is_leader]
        manifest = plane.read_manifest()
        checks.append(_check(
            "elastic-coordinator", "barriers-completed",
            not errors and len(results) == 6,
            errors[0] if errors else f"{len(results)}/6 barriers"))
        checks.append(_check(
            "elastic-coordinator", "single-leader",
            leaders == {0} and len(publishers) == 3,
            f"leaders={sorted(leaders)}, "
            f"{len(publishers)} leader-side results"))
        checks.append(_check(
            "elastic-coordinator", "published",
            manifest is not None and manifest.get("epoch") == 3,
            f"manifest={manifest}"))
        checks.append(_check(
            "elastic-coordinator", "lease-renewed", renewed,
            f"renewals: m0={c0.renewals} m1={c1.renewals}"))
        checks.append(_check(
            "elastic-coordinator", "conviction-raised", convicted,
            "absent member convicted at the timed-out barrier"))
    return checks


def _scenario_seeded_inversion(
        aud: LockAuditor) -> List[Dict[str, Any]]:
    """The deliberate ABBA fixture: thread 1 takes A then B, thread 2
    takes B then A - run SEQUENTIALLY (no deadlock risk; the order
    graph does not care about timing, only per-thread sequences). The
    audit must report the cycle and fail."""
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def a_then_b() -> None:
        with lock_a:
            with lock_b:
                pass

    def b_then_a() -> None:
        with lock_b:
            with lock_a:
                pass

    for fn in (a_then_b, b_then_a):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join(timeout=10.0)
    return [_check("seeded-inversion", "fixture-ran", True,
                   "two-lock ABBA interleaving recorded")]


SCENARIOS = ("prefetch-round", "watchdog-stall", "serve-storm",
             "elastic-coordinator")


# ---------------------------------------------------------------------------
# the audit driver
# ---------------------------------------------------------------------------
def run_lock_audit(scenarios: Optional[Sequence[str]] = None,
                   seed_inversion: bool = False) -> Dict[str, Any]:
    """Run the selected scenarios (default: all) under one installed
    auditor and return the combined report: per-scenario checks plus
    the global graph checks (acyclic order, no lock across a dispatch
    boundary, non-vacuous coverage). ``seed_inversion`` additionally
    runs the ABBA fixture, which must make the acyclic check fail."""
    names = tuple(scenarios) if scenarios else SCENARIOS
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise ValueError(
            f"unknown lock-audit scenario(s) {unknown}; "
            f"known: {list(SCENARIOS)}")
    t0 = time.monotonic()
    checks: List[Dict[str, Any]] = []
    trainer = None
    if "serve-storm" in names:
        # built BEFORE the shim installs: the audit targets the serve
        # layer's locks, not jax's import-time internals
        from cxxnet_tpu.analysis.jaxpr_audit import _make_trainer
        trainer = _make_trainer()
    aud = LockAuditor()
    fns: Dict[str, Callable[[], List[Dict[str, Any]]]] = {
        "prefetch-round": lambda: _scenario_prefetch_round(aud),
        "watchdog-stall": lambda: _scenario_watchdog_stall(aud),
        "serve-storm": lambda: _scenario_serve_storm(aud, trainer),
        "elastic-coordinator":
            lambda: _scenario_elastic_coordinator(aud),
    }
    with aud.installed():
        for name in names:
            try:
                checks.extend(fns[name]())
            except Exception as e:  # noqa: BLE001 - a crash IS the finding
                checks.append(_check(
                    name, "scenario-completed", False,
                    f"{type(e).__name__}: {e}"))
        if seed_inversion:
            checks.extend(_scenario_seeded_inversion(aud))
    rep = aud.report()
    cycle = rep["cycle"]
    checks.append(_check(
        "lock-order", "acyclic", cycle is None,
        " -> ".join(cycle) if cycle
        else f"{len(rep['edges'])} edges, no cycle"))
    checks.append(_check(
        "dispatch-boundary", "no-lock-held-across-dispatch",
        not rep["boundary_violations"],
        "; ".join(f"{v['thread']} held {v['locks']} at "
                  f"{v['boundary']}"
                  for v in rep["boundary_violations"])
        or "no audited lock held at a jax boundary"))
    checks.append(_check(
        "coverage", "locks-observed", rep["acquisitions"] > 0,
        f"{rep['sites']} sites, {rep['instances']} instances, "
        f"{rep['acquisitions']} acquisitions"))
    rep["scenarios"] = list(names)
    rep["seed_inversion"] = bool(seed_inversion)
    rep["checks"] = checks
    rep["failed"] = sum(1 for c in checks if not c["ok"])
    rep["elapsed_s"] = round(time.monotonic() - t0, 3)
    # contention stats into the process registry, next to the other
    # observability series (docs/OBSERVABILITY.md)
    from cxxnet_tpu import telemetry
    telemetry.set_gauge("lock.audit.max_held_ms", rep["max_held_ms"])
    telemetry.set_gauge("lock.audit.max_wait_ms", rep["max_wait_ms"])
    telemetry.set_gauge("lock.audit.sites", rep["sites"])
    return rep
