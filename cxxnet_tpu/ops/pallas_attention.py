"""Fused flash-attention Pallas TPU kernels (forward + backward).

The attention core (ops/attention.py) is where a sequence model's FLOPs
and HBM traffic live; this is its Pallas fast path, same integration
pattern as the LRN kernel (ops/pallas_lrn.py): TPU-only `pallas_call`
with an XLA fallback and an interpret-mode test hook.

Design (the standard flash-attention schedule on TPU):

- forward: grid (B, H, nQ, nKV), innermost KV dim sequential
  ("arbitrary") so f32 VMEM scratch (acc, m, l) carries the
  online-softmax state across KV blocks of one Q block; the last KV
  step writes o = acc/l and the logsumexp row stats (lse = m + log l).
  Only (BQ, BK) score tiles ever materialize - O(S) memory instead of
  O(S^2), MXU-sized tiles instead of one giant softmax.
- backward: recompute p = exp(q.k*scale - lse) per tile from the saved
  lse (no S x S residuals). With delta = rowsum(do * o):
      ds = p * (do . v^T - delta)
      dq += ds . k * scale     (grid (B, H, nQ, nKV), KV innermost)
      dk += ds^T . q * scale   (grid (B, H, nKV, nQ), Q innermost)
      dv += p^T . do
  exposed as one jax.custom_vjp around the forward.
- causal masking is done in global coordinates from program ids;
  fully-future tiles are skipped with @pl.when (forward) so the causal
  schedule does ~half the work, matching the math of
  ops/attention.py exactly (differential tests, test_pallas_attention).

Softmax arithmetic is f32 regardless of input dtype (bf16 inputs feed
the MXU as bf16, accumulate f32 via preferred_element_type).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cxxnet_tpu.ops.attention import _scale

_NEG = -1e30

# default tile sizes, set by an on-chip sweep (tools/bench_attn, v5e,
# b4 h8 s4096 d128 fwd+grads): (1024, 1024) runs 56.7 TFLOP/s
# non-causal = 4.04x the XLA blockwise path, where the old MXU-exact
# (128, 128) managed only 0.93x - at 128 the (b, h, s/bq, s/bk) grid
# is 32k programs whose per-program overhead dominates; 1024-tiles
# amortize it 64x and Mosaic still sub-tiles the 1024x1024 f32 score
# block through the MXU. Shrunk automatically for short sequences
# (_blocks picks the largest divisor of s <= BLOCK).
BLOCK_Q = 1024
BLOCK_K = 1024

# Mosaic requires the last two dims of every block shape to be
# (sublane, lane)-tileable: divisible by (8, 128) or equal to the
# array dims. A per-row stat laid out as (b, h, s) with block
# (1, 1, bq) violates that (second-to-last block dim 1 vs array dim
# h), so lse/delta ride a trailing broadcast dim of 8 - block
# (1, 1, bq, 8) is (128, 8)-tiled, and 8 == the array dim satisfies
# the lane rule (same trick as jax's reference flash kernel, which
# uses a trailing MIN_BLOCK_SIZE=128; 8 costs 16x less HBM for the
# saved residual).
_STAT_LANES = 8


def _sublane(dtype) -> int:
    return 16 if dtype == jnp.bfloat16 else 8


def _blocks(s: int, block: int, sub: int = 1) -> int:
    """Largest divisor of s that is <= block and a multiple of the
    sublane tile (preferred); falls back to any divisor (interpret mode
    has no tiling constraint)."""
    for b in range(min(block, s), 0, -1):
        if s % b == 0 and b % sub == 0:
            return b
    b = min(block, s)
    while s % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l, *,
                scale, causal, bq, bk, nkv):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, _NEG)
        l[:] = jnp.zeros_like(l)

    qi = pl.program_id(2)
    q_off = qi * bq
    kv_off = ki * bk

    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kv_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        m_new = jnp.maximum(m[:], jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(s <= _NEG * 0.5, 0.0, p)
        corr = jnp.exp(m[:] - m_new)
        l[:] = l[:] * corr + jnp.sum(p, axis=1)
        acc[:] = acc[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m[:] = m_new

    if causal:
        pl.when(kv_off <= q_off + bq - 1)(_tile)
    else:
        _tile()

    @pl.when(ki == nkv - 1)
    def _out():
        safe = jnp.where(l[:] > 0, l[:], 1.0)
        o_ref[0, 0] = (acc[:] / safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            (m[:] + jnp.log(safe))[:, None], (bq, _STAT_LANES))


def _fwd(q, k, v, scale, causal, interpret) -> Tuple[jax.Array, jax.Array]:
    b, h, s, d = q.shape
    sub = _sublane(q.dtype)
    bq, bk = _blocks(s, BLOCK_Q, sub), _blocks(k.shape[2], BLOCK_K, sub)
    nq, nkv = s // bq, k.shape[2] // bk
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, nkv=nkv)
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b, h, qi, ki: (b, h, ki, 0))
    o, lse = pl.pallas_call(
        kern,
        grid=(b, h, nq, nkv),
        in_specs=[qspec, kspec, kspec],
        out_specs=[qspec,
                   pl.BlockSpec((1, 1, bq, _STAT_LANES),
                                lambda b, h, qi, ki: (b, h, qi, 0))],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, h, s, _STAT_LANES),
                                        jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq,), jnp.float32),
                        pltpu.VMEM((bq,), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc, *, scale, causal, bq, bk, nkv):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    qi = pl.program_id(2)
    q_off, kv_off = qi * bq, ki * bk
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kv_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])
        dov = jax.lax.dot_general(
            do_ref[0, 0], v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dov - delta_ref[0, 0][:, :1])
        acc[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(kv_off <= q_off + bq - 1)(_tile)
    else:
        _tile()

    @pl.when(ki == nkv - 1)
    def _out():
        dq_ref[0, 0] = acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, acck, accv, *, scale, causal, bq, bk, nq):
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        acck[:] = jnp.zeros_like(acck)
        accv[:] = jnp.zeros_like(accv)

    ki = pl.program_id(2)
    q_off, kv_off = qi * bq, ki * bk
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kv_off + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(kpos <= qpos, s, _NEG)
        p = jnp.exp(s - lse_ref[0, 0][:, :1])            # (bq, bk)
        do = do_ref[0, 0]
        accv[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dov = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dov - delta_ref[0, 0][:, :1])          # (bq, bk)
        acck[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)

    if causal:
        pl.when(kv_off <= q_off + bq - 1)(_tile)
    else:
        _tile()

    @pl.when(qi == nq - 1)
    def _out():
        dk_ref[0, 0] = acck[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = accv[:].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, o, lse, do, scale, causal, interpret):
    b, h, s, d = q.shape
    sk = k.shape[2]
    sub = _sublane(q.dtype)
    bq, bk = _blocks(s, BLOCK_Q, sub), _blocks(sk, BLOCK_K, sub)
    nq, nkv = s // bq, sk // bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # (b, h, s)
    delta = jnp.broadcast_to(delta[..., None],
                             (*delta.shape, _STAT_LANES))

    qspec = pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda b, h, qi, ki: (b, h, ki, 0))
    rspec = pl.BlockSpec((1, 1, bq, _STAT_LANES),
                         lambda b, h, qi, ki: (b, h, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nkv=nkv),
        grid=(b, h, nq, nkv),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # swapped grid: kv outer, q inner (sequential) so dk/dv accumulate
    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda b, h, ki, qi: (b, h, qi, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d), lambda b, h, ki, qi: (b, h, ki, 0))
    rspec2 = pl.BlockSpec((1, 1, bq, _STAT_LANES),
                          lambda b, h, ki, qi: (b, h, qi, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(b, h, nkv, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rspec2, rspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry: custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    interpret: bool = False):
    """Fused TPU attention; semantics == ops.attention.naive_attention.
    [B, H, S, D] in/out; O(S) memory; causal skips future tiles."""
    sc = _scale(q, scale)
    o, _ = _fwd(q, k, v, sc, causal, interpret)
    return o


def _vjp_fwd(q, k, v, causal, scale, interpret):
    sc = _scale(q, scale)
    o, lse = _fwd(q, k, v, sc, causal, interpret)
    return o, (q, k, v, o, lse)


def _vjp_bwd(causal, scale, interpret, res, do):
    q, k, v, o, lse = res
    sc = _scale(q, scale)
    return _bwd_impl(q, k, v, o, lse, do, sc, causal, interpret)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

# test hook, same convention as ops/pallas_lrn.py: force the kernel on
# non-TPU backends in interpret mode
_FORCE_INTERPRET = False


def _backend_ok() -> bool:
    return jax.default_backend() == "tpu" or _FORCE_INTERPRET


def _tile_ok(q, sk: int) -> bool:
    """Mosaic tileability: both score-tile dims must land on sublane
    multiples (the fallback-divisor path is for interpret mode only)
    and tiny dims would underfill the MXU for no win."""
    sub = _sublane(q.dtype)
    return (q.shape[2] >= sub and sk >= sub and q.shape[3] >= 8
            and _blocks(q.shape[2], BLOCK_Q, sub) % sub == 0
            and _blocks(sk, BLOCK_K, sub) % sub == 0)


def use_flash(q) -> bool:
    """Single-device eligibility: TPU backend + tileable shapes. On a
    multi-device mesh use the shard_map route below - pallas_call alone
    has no GSPMD partitioning rule (same split as ops/pallas_lrn.py)."""
    return (_backend_ok() and jax.device_count() == 1
            and _tile_ok(q, q.shape[2]))


def use_flash_sharded(q, mesh) -> bool:
    """shard_map-route eligibility: attention is independent per
    (batch, head), so sharding batch over 'data' (and heads over
    'model') needs no cross-device communication; each device runs the
    kernel on its local shard. The full sequence stays per-device - a
    'seq'-sharded input takes the ring route instead
    (layers/attention.py)."""
    from cxxnet_tpu.parallel.mesh import batch_shardable
    return (_backend_ok() and mesh is not None
            and batch_shardable(mesh, q.shape[0])
            and _tile_ok(q, q.shape[2]))


def flash_attention_sharded(q, k, v, mesh, causal: bool = False,
                            scale: Optional[float] = None):
    """flash_attention over a multi-device mesh: batch on 'data', heads
    on 'model' when divisible (replicated-head compute otherwise, same
    fallback as the LRN kernel's TP note)."""
    from jax.sharding import PartitionSpec as P
    names = mesh.axis_names
    model = ("model" if "model" in names
             and q.shape[1] % mesh.shape["model"] == 0 else None)
    spec = P("data" if "data" in names else None, model, None, None)
    fn = jax.shard_map(
        lambda qs, ks, vs: flash_attention(qs, ks, vs, causal, scale,
                                           _FORCE_INTERPRET),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # per-shard kernel, no collectives: nothing for vma to verify
        check_vma=False)
    return fn(q, k, v)
