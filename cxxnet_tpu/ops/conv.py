"""2-D convolution on NCHW tensors.

The reference lowers conv to im2col + per-group GEMM with chunking to bound
scratch memory (convolution_layer-inl.hpp:70-155, temp_col_max). On TPU the
whole dance is one lax.conv_general_dilated: XLA tiles it directly onto the
MXU, grouped conv maps to feature_group_count, and no scratch bound exists.

Output-size parity (convolution_layer-inl.hpp:174-177):
    out = (in + 2*pad - k) // stride + 1
which is exactly lax's explicit-padding convolution arithmetic.
"""

from __future__ import annotations

import jax
from jax import lax


def conv_out_dim(in_dim: int, ksize: int, stride: int, pad: int) -> int:
    """The reference convolution output-size formula."""
    return (in_dim + 2 * pad - ksize) // stride + 1


def conv2d(x: jax.Array, w: jax.Array, stride: int, pad_y: int, pad_x: int,
           num_group: int = 1, precision=None) -> jax.Array:
    """Grouped 2-D convolution.

    x: (batch, in_ch, h, w); w: (out_ch, in_ch // num_group, ky, kx).

    Precision: f32 operands default to HIGHEST so nominal-f32 training
    matches the reference's f32 GEMM (TPU's default would silently run
    bf16 MXU passes); bf16 training (dtype=bfloat16) keeps the fast
    path - that trade is the user's explicit choice there.
    """
    if precision is None and x.dtype == jax.numpy.float32:
        precision = lax.Precision.HIGHEST
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=((pad_y, pad_y), (pad_x, pad_x)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=num_group,
        precision=precision,
    )
