"""2-D convolution on NCHW tensors.

The reference lowers conv to im2col + per-group GEMM with chunking to bound
scratch memory (convolution_layer-inl.hpp:70-155, temp_col_max). On TPU the
whole dance is one lax.conv_general_dilated: XLA tiles it directly onto the
MXU, grouped conv maps to feature_group_count, and no scratch bound exists.

Output-size parity (convolution_layer-inl.hpp:174-177):
    out = (in + 2*pad - k) // stride + 1
which is exactly lax's explicit-padding convolution arithmetic.

Space-to-depth: an input-layer conv (3 channels, large kernel, stride
s > 1 - AlexNet's 11x11/s4) is MXU-hostile in both its forward (the
contraction dim is in_ch*ky*kx but spatially strided) and especially
its weight gradient (an rhs-dilated conv contracting over batch and
output positions with only 3 channels). Rewriting it as a stride-1
conv over in_ch*s*s channels (the MLPerf-era TPU trick) makes both
directions dense MXU contractions. With dy = q*s + r:

    out[o, y, x] = sum_{i, dy, dx} xpad[i, y*s+dy, x*s+dx] * w[o, i, dy, dx]
                 = sum_{(i,r,rx), q, qx} X[(i,r,rx), y+q, x+qx] * W'[(i,r,rx), q, qx]

where X is xpad with each s*s spatial block moved into channels and W'
is w zero-padded to ceil(k/s)*s then block-moved the same way - an
EXACT reshuffle of the same multiply-adds (same products, same
channel-major summation groups), not an approximation. The transform
is applied inside conv2d (weights keep their reference OIHW layout /
checkpoint format); autodiff then derives the dense-shape wgrad
automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv_out_dim(in_dim: int, ksize: int, stride: int, pad: int) -> int:
    """The reference convolution output-size formula."""
    return (in_dim + 2 * pad - ksize) // stride + 1


# auto heuristic bound: s2d pays when the contraction channel count is
# tiny (the input layer); 3 RGB planes always qualify, a mid-net conv
# never does
_S2D_MAX_IN_CH = 4


def s2d_auto(in_ch: int, stride: int, ky: int, kx: int,
             num_group: int = 1) -> bool:
    """The ONE definition of the space-to-depth auto heuristic:
    ungrouped, strided, kernel covers the stride, and a tiny input
    channel count. Evaluated in-op by conv2d (s2d=None) and at the
    graph level by the `space_to_depth` pattern-rewrite pass
    (nnet/passes.py), which stamps the decision onto the DAG - a
    single predicate, so the two can never disagree."""
    return (num_group == 1 and stride > 1
            and min(ky, kx) >= stride and in_ch <= _S2D_MAX_IN_CH)


def conv2d(x: jax.Array, w: jax.Array, stride: int, pad_y: int, pad_x: int,
           num_group: int = 1, precision=None, s2d=None) -> jax.Array:
    """Grouped 2-D convolution.

    x: (batch, in_ch, h, w); w: (out_ch, in_ch // num_group, ky, kx).

    Precision: f32 operands default to HIGHEST so nominal-f32 training
    matches the reference's f32 GEMM (TPU's default would silently run
    bf16 MXU passes); bf16 training (dtype=bfloat16) keeps the fast
    path - that trade is the user's explicit choice there.

    s2d: None = auto (space-to-depth when ungrouped, strided, and
    in_ch <= 4 - see module docstring); True/False force it. The
    rewrite computes identical sums regrouped, so values match the
    direct lowering to float rounding.
    """
    if precision is None and x.dtype == jax.numpy.float32:
        precision = lax.Precision.HIGHEST
    if s2d is None:
        s2d = s2d_auto(x.shape[1], stride, w.shape[2], w.shape[3],
                       num_group)
    elif s2d and (num_group != 1 or stride <= 1):
        # an explicit force that cannot apply must not be silently
        # dropped - the user would benchmark the unrewritten conv
        # believing s2d is active
        raise ValueError(
            "space_to_depth=1 requires an ungrouped conv with "
            f"stride > 1 (got num_group={num_group}, stride={stride})")
    if s2d:
        return _conv2d_s2d(x, w, stride, pad_y, pad_x, precision)
    return lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=((pad_y, pad_y), (pad_x, pad_x)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=num_group,
        precision=precision,
    )


def _blocks_to_channels(a: jax.Array, s: int) -> jax.Array:
    """(n, c, H, W) -> (n, c*s*s, H/s, W/s): each s*s spatial block
    becomes s*s channels, channel index (c*s + r)*s + rx."""
    n, c, h, w = a.shape
    a = a.reshape(n, c, h // s, s, w // s, s)
    a = a.transpose(0, 1, 3, 5, 2, 4)
    return a.reshape(n, c * s * s, h // s, w // s)


def _conv2d_s2d(x, w, s, pad_y, pad_x, precision):
    """Space-to-depth rewrite of an ungrouped strided conv (module
    docstring). Padded-length bookkeeping: the rewrite needs the
    (zero-)padded input length to be exactly ((out-1) + ceil(k/s)) * s;
    positions past the reference's own pad are read only by the
    zero-padded kernel taps (dy >= k), and trimmed positions are read
    by no kept output window - so padding/trimming to that length
    changes nothing."""
    b, c, h, wd = x.shape
    oc, ic, ky, kx = w.shape
    oy = conv_out_dim(h, ky, s, pad_y)
    ox = conv_out_dim(wd, kx, s, pad_x)
    kpy, kpx = -(-ky // s), -(-kx // s)
    zero = jnp.zeros((), x.dtype)
    xp = lax.pad(x, zero, (
        (0, 0, 0), (0, 0, 0),
        (pad_y, (oy - 1 + kpy) * s - h - pad_y, 0),
        (pad_x, (ox - 1 + kpx) * s - wd - pad_x, 0)))
    X = _blocks_to_channels(xp, s)
    wp = lax.pad(w, jnp.zeros((), w.dtype), (
        (0, 0, 0), (0, 0, 0),
        (0, kpy * s - ky, 0), (0, kpx * s - kx, 0)))
    # the SAME block->channel shuffle as the input (one definition of
    # the channel-index contract, so X and W' cannot disagree)
    wp = _blocks_to_channels(wp, s)
    return lax.conv_general_dilated(
        X, wp, window_strides=(1, 1), padding=((0, 0), (0, 0)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=precision)
