"""Fused Pallas TPU kernel for cross-channel LRN (fwd + analytic bwd).

The XLA lowering of LRN (ops/nn.py: reduce_window over channels + power)
materializes the squared tensor and the window sum in HBM; on AlexNet the
two LRN layers cost ~9% of the train step, all bandwidth + transcendental
VPU work. This kernel fuses square -> channel-window sum -> pow(-beta)
-> scale into one VMEM pass (the role cudnn fast paths play in the
reference - cudnn_convolution_layer-inl.hpp:13-171), with the analytic
backward of lrn_layer-inl.hpp:59-77 as a second kernel under custom_vjp:

    norm_c  = knorm + alpha/n * sum_{j in win(c)} x_j^2
    out_c   = x_c * norm_c^-beta
    gin_c   = g_c * norm_c^-beta
              - (2 alpha beta / n) * x_c * rsum_c
    rsum_c  = sum_{j : c in win(j)} g_j * x_j * norm_j^(-beta-1)

win(c) = [c-lo, c+hi] with lo = n//2, hi = n-lo-1 (the reference chpool
convention); the backward sum runs over the reversed window [c-hi, c+lo].

Kernels tile (B, C, H*W) as (1, C, T) VMEM blocks over a (B, ceil(HW/T))
grid; channel shifts are static concat+slice, unrolled over the window
(local_size is a config constant). Falls back to the XLA path off-TPU or
when C violates the sublane tiling constraint.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

_LANE_TILE = 512


def _shift_down(a: jax.Array, d: int) -> jax.Array:
    """result[c] = a[c-d] (zeros shifted in at the top)."""
    z = jnp.zeros((d, a.shape[1]), a.dtype)
    return jnp.concatenate([z, a[:-d]], axis=0)


def _shift_up(a: jax.Array, d: int) -> jax.Array:
    """result[c] = a[c+d] (zeros shifted in at the bottom)."""
    z = jnp.zeros((d, a.shape[1]), a.dtype)
    return jnp.concatenate([a[d:], z], axis=0)


def _window_sum(a: jax.Array, up: int, down: int) -> jax.Array:
    """sum_{j = c-down}^{c+up} a[j] along axis 0, zero padded."""
    s = a
    for d in range(1, up + 1):
        s = s + _shift_up(a, d)
    for d in range(1, down + 1):
        s = s + _shift_down(a, d)
    return s


def _fwd_kernel(x_ref, o_ref, *, n, alpha, beta, knorm):
    x = x_ref[0].astype(jnp.float32)
    lo, hi = n // 2, n - n // 2 - 1
    # norm_c sums x_j^2 over the window j in [c-lo, c+hi]
    s = _window_sum(x * x, hi, lo)
    norm = knorm + (alpha / n) * s
    o_ref[0] = (x * jnp.power(norm, -beta)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, g_ref, o_ref, *, n, alpha, beta, knorm):
    x = x_ref[0].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    lo, hi = n // 2, n - n // 2 - 1
    norm = knorm + (alpha / n) * _window_sum(x * x, hi, lo)
    u = g * x * jnp.power(norm, -beta - 1.0)
    # reversed window [c-hi, c+lo]
    rsum = _window_sum(u, lo, hi)
    gin = g * jnp.power(norm, -beta) - (2.0 * alpha * beta / n) * x * rsum
    o_ref[0] = gin.astype(o_ref.dtype)


def _tile_ok(x: jax.Array) -> bool:
    c = x.shape[1]
    sub = 16 if x.dtype == jnp.bfloat16 else 8
    return c % sub == 0 and c * _LANE_TILE * 4 * 3 < 12 * 2 ** 20


def _call(kernel, args, x, interpret):
    b, c, h, w = x.shape
    hw = h * w
    t = min(_LANE_TILE, hw)
    grid = (b, pl.cdiv(hw, t))
    spec = pl.BlockSpec((1, c, t), lambda i, j: (i, 0, j))
    flat = [a.reshape(b, c, hw) for a in args]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, c, hw), x.dtype),
        grid=grid,
        in_specs=[spec] * len(flat),
        out_specs=spec,
        interpret=interpret,
    )(*flat)
    return out.reshape(b, c, h, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_pallas(x, local_size, alpha, beta, knorm, interpret=False):
    """Fused LRN; numerically identical to ops.nn.lrn (tested to 1e-5)."""
    kern = functools.partial(_fwd_kernel, n=local_size, alpha=alpha,
                             beta=beta, knorm=knorm)
    return _call(kern, [x], x, interpret)


def _vjp_fwd(x, local_size, alpha, beta, knorm, interpret=False):
    return lrn_pallas(x, local_size, alpha, beta, knorm, interpret), x


def _vjp_bwd(local_size, alpha, beta, knorm, interpret, x, g):
    kern = functools.partial(_bwd_kernel, n=local_size, alpha=alpha,
                             beta=beta, knorm=knorm)
    return (_call(kern, [x, g], x, interpret),)


lrn_pallas.defvjp(_vjp_fwd, _vjp_bwd)


def use_pallas_lrn(x: jax.Array) -> bool:
    """Single-device eligibility: TPU backend + channel dim tiles
    cleanly. On a multi-device mesh use the shard_map route below -
    pallas_call alone has no GSPMD partitioning rule."""
    return (_backend_ok() and jax.device_count() == 1 and _tile_ok(x))


# test hook: force the kernel on non-TPU backends in interpret mode so
# the shard_map route is exercised on the virtual CPU mesh
_FORCE_INTERPRET = False


def _backend_ok() -> bool:
    return jax.default_backend() == "tpu" or _FORCE_INTERPRET


def use_pallas_lrn_sharded(x: jax.Array, mesh) -> bool:
    """shard_map-route eligibility over `mesh`: LRN is per-sample, so
    sharding the batch over the 'data' axis needs no cross-device
    communication; each device runs the kernel on its local shard.
    Requires the per-shard batch to be whole and the channel tiling
    constraint on the (unchanged) per-shard channel dim."""
    from cxxnet_tpu.parallel.mesh import batch_shardable
    return (_backend_ok() and batch_shardable(mesh, x.shape[0])
            and _tile_ok(x))


def lrn_pallas_sharded(x, mesh, local_size, alpha, beta, knorm):
    """lrn_pallas over a multi-device mesh: batch dim sharded on 'data',
    channels/spatial replicated within each shard. If the operand arrives
    channel-sharded (tensor parallelism), GSPMD gathers channels first -
    the same all-gather the XLA reduce_window path would need for its
    cross-channel window.
    """
    from jax.sharding import PartitionSpec as P
    spec = P("data", *(None,) * (x.ndim - 1))
    fn = jax.shard_map(
        lambda xs: lrn_pallas(xs, local_size, alpha, beta, knorm,
                              _FORCE_INTERPRET),
        mesh=mesh, in_specs=spec, out_specs=spec,
        # pallas_call's out_shape carries no varying-mesh-axes info;
        # the per-shard computation touches no collectives, so the
        # vma check has nothing to verify anyway
        check_vma=False)
    return fn(x)
