"""Pooling ops on NCHW tensors via lax.reduce_window.

Output-size parity: the reference uses a ceil-flavored formula
(pooling_layer-inl.hpp:103-106, mirrored by mshadow pool):

    out = min(in - k + stride - 1, in - 1) // stride + 1

i.e. the last window may be truncated at the boundary. We reproduce this
with explicit high padding and neutral init values (-inf for max, 0 for
sum/avg); avg pooling divides by the FULL window size k*k even for
truncated windows, matching mshadow pool<sum> scaled by 1/(ky*kx).

Max-pool backward parity: the reference's unpool (pooling layer
backprop via mshadow unpool<maximum>) assigns the window's gradient to
EVERY source position equal to the window max - on ties (ubiquitous
after relu, where windows are full of equal zeros) ALL tied positions
receive the full gradient. XLA's native reduce_window-max gradient
(select_and_scatter) picks a single winner instead, so max_pool2d
carries a custom_vjp implementing the reference rule exactly.

The tie rule separates exactly into two 1-D unpools: with
r = rowmax(x) and m = colmax(r), x <= r <= m gives
[x==r]*[r==m] == [x==m], so distributing g through the column max
(onto r) and then through the row max (onto x) duplicates gradient to
exactly the positions the 2-D rule would. Each 1-D unpool only
enumerates the ceil(k/stride) windows that can cover a position
(window o covers p iff o = p//s - d with p%s + d*s < k), so the
backward costs ~2*ceil(k/s) half-size elementwise passes instead of
the ky*kx full-tensor passes of the naive formulation - for the
AlexNet/GoogLeNet 3x3 stride-2 pools that is 4 small passes vs 9 big
ones, and it is what makes `pool_grad=ties` (exact mshadow parity)
affordable on TPU. Still no select_and_scatter anywhere (historically
a slow lowering on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pool_out_dim(in_dim: int, ksize: int, stride: int, pad: int = 0) -> int:
    """The reference pooling output-size formula (pad is an extension over
    the reference, which has no pooling padding; pad=0 is exact parity)."""
    in_dim = in_dim + 2 * pad
    return min(in_dim - ksize + stride - 1, in_dim - 1) // stride + 1


def _pool_padding(in_dim: int, ksize: int, stride: int, pad: int) -> int:
    """High padding needed so reduce_window emits pool_out_dim outputs."""
    out = pool_out_dim(in_dim, ksize, stride, pad)
    return max(0, (out - 1) * stride + ksize - (in_dim + pad))


def pool2d(x: jax.Array, mode: str, ksize_y: int, ksize_x: int,
           stride: int, pad_y: int = 0, pad_x: int = 0,
           grad_mode: str = "ties") -> jax.Array:
    """Pool an NCHW tensor. mode in {'max', 'sum', 'avg'}.

    pad_y/pad_x symmetrically pad before pooling (inception-style
    same-size pooling); padding is neutral for the reducer (-inf for
    max, 0 for sum/avg) and avg still divides by the full window size.

    grad_mode (max pooling only): 'ties' (default) is the reference's
    unpool rule - every source equal to the window max receives the
    full gradient, via the separable ~2*ceil(k/s)-pass backward (see
    module docstring). 'winner' opts into XLA's native
    reduce_window-max gradient (select_and_scatter: one winner per
    window, the cuDNN-style rule) - a DOCUMENTED semantics change on
    tied windows, exposed as `pool_grad = winner` for workloads where
    even the separable tie backward shows up in the profile and exact
    mshadow tie parity is not required.
    """
    if grad_mode not in ("ties", "winner"):
        raise ValueError(f"unknown grad_mode {grad_mode!r}")
    if grad_mode == "winner" and mode != "max":
        # the layer guard rejects this too; enforce at the op so a
        # direct caller can never believe it switched a backward rule
        # that does not exist for sum/avg
        raise ValueError("grad_mode='winner' only exists for max "
                         "pooling")
    hi_y = _pool_padding(x.shape[2], ksize_y, stride, pad_y)
    hi_x = _pool_padding(x.shape[3], ksize_x, stride, pad_x)
    if mode == "max":
        if grad_mode == "winner":
            out = _reduce_max(x, ksize_y, ksize_x, stride,
                              pad_y, pad_x, hi_y, hi_x)
        else:
            out = max_pool2d(x, ksize_y, ksize_x, stride, pad_y, pad_x,
                             hi_y, hi_x)
    elif mode in ("sum", "avg"):
        out = lax.reduce_window(
            x, 0.0, lax.add, (1, 1, ksize_y, ksize_x),
            (1, 1, stride, stride),
            ((0, 0), (0, 0), (pad_y, hi_y), (pad_x, hi_x)))
        if mode == "avg":
            out = out * (1.0 / (ksize_y * ksize_x))
    else:
        raise ValueError(f"unknown pooling mode {mode!r}")
    return out


def _reduce_max(x, ky, kx, stride, pad_y, pad_x, hi_y, hi_x):
    """The ONE primal max reduce_window both backward modes share -
    'winner' differentiates straight through it (select_and_scatter),
    'ties' wraps it in the custom_vjp below; a padding-layout change
    here changes both forwards together."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, ky, kx), (1, 1, stride, stride),
        ((0, 0), (0, 0), (pad_y, hi_y), (pad_x, hi_x)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6, 7))
def max_pool2d(x, ky, kx, stride, pad_y, pad_x, hi_y, hi_x):
    """Max pooling with the reference's unpool backward (see module
    docstring). Padding args are precomputed by pool2d."""
    return _reduce_max(x, ky, kx, stride, pad_y, pad_x, hi_y, hi_x)


def _max_pool_fwd(x, ky, kx, stride, pad_y, pad_x, hi_y, hi_x):
    # separable forward: identical values to the 2-D reduce_window
    # (max is associative), but the row-max intermediate r is exactly
    # the residual the separable ties backward needs (module docstring)
    r = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, 1, kx), (1, 1, 1, stride),
        ((0, 0), (0, 0), (0, 0), (pad_x, hi_x)))
    out = lax.reduce_window(
        r, -jnp.inf, lax.max, (1, 1, ky, 1), (1, 1, stride, 1),
        ((0, 0), (0, 0), (pad_y, hi_y), (0, 0)))
    return out, (x, r, out)


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _cover_lookup(a, s, d, length, axis, fill):
    """Array whose index p along `axis` holds a[p//s - d] (`fill` where
    that index is outside a). With q = p - d*s, q//s == p//s - d
    exactly, so the strided window lookup is a repeat(s) shifted right
    by d*s - pure layout ops (broadcast-reshape + pad), no gather."""
    r = jnp.repeat(a, s, axis=axis) if s > 1 else a
    cfg = [(0, 0, 0)] * a.ndim
    cfg[axis] = (d * s, length - r.shape[axis] - d * s, 0)
    return lax.pad(r, jnp.asarray(fill, a.dtype), cfg)


def _unpool_1d(vals, pooled, g, k, s, axis):
    """One-axis mshadow ties unpool: gin[p] = sum over windows o
    covering p of g[o] * (vals[p] == pooled[o]), where `vals` is
    already neutrally padded along `axis`. Only o = p//s - d with
    d in [0, ceil(k/s)) can cover p, and does iff p%s + d*s < k (a
    static per-position mask) - so ceil(k/s) passes, not k."""
    length = vals.shape[axis]
    shape = [1] * vals.ndim
    shape[axis] = length
    phase = (jnp.arange(length) % s).reshape(shape)
    gin = jnp.zeros(vals.shape, g.dtype)
    for d in range(_ceil_div(k, s)):
        m = _cover_lookup(pooled, s, d, length, axis, -jnp.inf)
        gd = _cover_lookup(g, s, d, length, axis, 0.0)
        covers = phase + d * s < k
        gin = gin + jnp.where(covers & (vals == m), gd, 0.0)
    return gin


def _max_pool_bwd(ky, kx, stride, pad_y, pad_x, hi_y, hi_x, res, g):
    x, r, out = res
    # step 1: distribute g through the column max, out -> r (rows are
    # the pooled axis; r spans padded rows only inside the unpool)
    rp = jnp.pad(r, ((0, 0), (0, 0), (pad_y, hi_y), (0, 0)),
                 constant_values=-jnp.inf)
    gr = _unpool_1d(rp, out, g, ky, stride, axis=2)
    gr = lax.slice_in_dim(gr, pad_y, pad_y + x.shape[2], axis=2)
    # step 2: distribute gr through the row max, r -> x
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (pad_x, hi_x)),
                 constant_values=-jnp.inf)
    gin = _unpool_1d(xp, r, gr, kx, stride, axis=3)
    gin = lax.slice_in_dim(gin, pad_x, pad_x + x.shape[3], axis=3)
    return (gin.astype(x.dtype),)


max_pool2d.defvjp(_max_pool_fwd, _max_pool_bwd)


def insanity_pool2d(x: jax.Array, rng: jax.Array, ksize_y: int, ksize_x: int,
                    stride: int, p_keep: float) -> jax.Array:
    """Stochastic displaced max pooling (insanity_max_pooling).

    Parity with InsanityPoolingExp (insanity_pooling_layer-inl.hpp:13-101):
    every source pixel draws a uniform flag; with probability p_keep it is
    read in place, otherwise it is read from a neighbour one pixel
    up/down/left/right (each with probability (1-p_keep)/4, clamped at the
    border). Max pooling then runs over the displaced reads - which equals
    max-pooling the "jittered" image.
    """
    b, c, h, w = x.shape
    flag = jax.random.uniform(rng, (b, c, h, w), dtype=jnp.float32)
    delta = (1.0 - p_keep) / 4.0

    ys = jnp.broadcast_to(jnp.arange(h)[None, None, :, None], (b, c, h, w))
    xs = jnp.broadcast_to(jnp.arange(w)[None, None, None, :], (b, c, h, w))

    yd = jnp.where((flag >= p_keep) & (flag < p_keep + delta), -1,
                   jnp.where((flag >= p_keep + delta) &
                             (flag < p_keep + 2 * delta), 1, 0))
    xd = jnp.where((flag >= p_keep + 2 * delta) &
                   (flag < p_keep + 3 * delta), -1,
                   jnp.where(flag >= p_keep + 3 * delta, 1, 0))
    y_src = jnp.clip(ys + yd, 0, h - 1)
    x_src = jnp.clip(xs + xd, 0, w - 1)

    flat_idx = (y_src * w + x_src).reshape(b, c, h * w)
    jittered = jnp.take_along_axis(
        x.reshape(b, c, h * w), flat_idx, axis=2).reshape(b, c, h, w)
    # backward parity (insanity_pooling_layer-inl.hpp
    # InsanityUnPoolingExp): the gradient credits the window SLOT whose
    # displaced read won the max - NOT the displaced source pixel.
    # Straight-through the displacement (identity gradient from the
    # jittered view back to the same coordinates) so the max-pool
    # unpool rule below lands the gradient at slot positions, ties
    # duplicated, exactly like the reference. The zero term is
    # (x - stop_grad(x)) so the VALUE is bit-exactly `jittered` -
    # an x + (jit - x) form drifts by 1 ulp and breaks the unpool
    # rule's exact tie comparisons.
    jittered = jax.lax.stop_gradient(jittered) \
        + (x - jax.lax.stop_gradient(x))
    return pool2d(jittered, "max", ksize_y, ksize_x, stride)
