"""Pooling ops on NCHW tensors via lax.reduce_window.

Output-size parity: the reference uses a ceil-flavored formula
(pooling_layer-inl.hpp:103-106, mirrored by mshadow pool):

    out = min(in - k + stride - 1, in - 1) // stride + 1

i.e. the last window may be truncated at the boundary. We reproduce this
with explicit high padding and neutral init values (-inf for max, 0 for
sum/avg); avg pooling divides by the FULL window size k*k even for
truncated windows, matching mshadow pool<sum> scaled by 1/(ky*kx).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def pool_out_dim(in_dim: int, ksize: int, stride: int, pad: int = 0) -> int:
    """The reference pooling output-size formula (pad is an extension over
    the reference, which has no pooling padding; pad=0 is exact parity)."""
    in_dim = in_dim + 2 * pad
    return min(in_dim - ksize + stride - 1, in_dim - 1) // stride + 1


def _pool_padding(in_dim: int, ksize: int, stride: int, pad: int) -> int:
    """High padding needed so reduce_window emits pool_out_dim outputs."""
    out = pool_out_dim(in_dim, ksize, stride, pad)
    return max(0, (out - 1) * stride + ksize - (in_dim + pad))


def pool2d(x: jax.Array, mode: str, ksize_y: int, ksize_x: int,
           stride: int, pad_y: int = 0, pad_x: int = 0) -> jax.Array:
    """Pool an NCHW tensor. mode in {'max', 'sum', 'avg'}.

    pad_y/pad_x symmetrically pad before pooling (inception-style
    same-size pooling); padding is neutral for the reducer (-inf for
    max, 0 for sum/avg) and avg still divides by the full window size.
    """
    hi_y = _pool_padding(x.shape[2], ksize_y, stride, pad_y)
    hi_x = _pool_padding(x.shape[3], ksize_x, stride, pad_x)
    padding = ((0, 0), (0, 0), (pad_y, hi_y), (pad_x, hi_x))
    window = (1, 1, ksize_y, ksize_x)
    strides = (1, 1, stride, stride)
    if mode == "max":
        init = -jnp.inf
        out = lax.reduce_window(x, init, lax.max, window, strides, padding)
    elif mode in ("sum", "avg"):
        out = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if mode == "avg":
            out = out * (1.0 / (ksize_y * ksize_x))
    else:
        raise ValueError(f"unknown pooling mode {mode!r}")
    return out


def insanity_pool2d(x: jax.Array, rng: jax.Array, ksize_y: int, ksize_x: int,
                    stride: int, p_keep: float) -> jax.Array:
    """Stochastic displaced max pooling (insanity_max_pooling).

    Parity with InsanityPoolingExp (insanity_pooling_layer-inl.hpp:13-101):
    every source pixel draws a uniform flag; with probability p_keep it is
    read in place, otherwise it is read from a neighbour one pixel
    up/down/left/right (each with probability (1-p_keep)/4, clamped at the
    border). Max pooling then runs over the displaced reads - which equals
    max-pooling the "jittered" image.
    """
    b, c, h, w = x.shape
    flag = jax.random.uniform(rng, (b, c, h, w), dtype=jnp.float32)
    delta = (1.0 - p_keep) / 4.0

    ys = jnp.broadcast_to(jnp.arange(h)[None, None, :, None], (b, c, h, w))
    xs = jnp.broadcast_to(jnp.arange(w)[None, None, None, :], (b, c, h, w))

    yd = jnp.where((flag >= p_keep) & (flag < p_keep + delta), -1,
                   jnp.where((flag >= p_keep + delta) &
                             (flag < p_keep + 2 * delta), 1, 0))
    xd = jnp.where((flag >= p_keep + 2 * delta) &
                   (flag < p_keep + 3 * delta), -1,
                   jnp.where(flag >= p_keep + 3 * delta, 1, 0))
    y_src = jnp.clip(ys + yd, 0, h - 1)
    x_src = jnp.clip(xs + xd, 0, w - 1)

    flat_idx = (y_src * w + x_src).reshape(b, c, h * w)
    jittered = jnp.take_along_axis(
        x.reshape(b, c, h * w), flat_idx, axis=2).reshape(b, c, h, w)
    return pool2d(jittered, "max", ksize_y, ksize_x, stride)
