"""Elementwise activations, softmax and LRN.

Parity with the reference op functors (src/layer/op.h:15-101) and the LRN
layer (src/layer/lrn_layer-inl.hpp:12-93). Backward passes come from
autodiff; note jax's grads of these match the reference's
"grad-from-output" formulations (sigmoid_grad a*(1-a) etc.) analytically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def relu(x):
    return jnp.maximum(x, 0.0)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softplus(x):
    return jax.nn.softplus(x)


def gelu(x):
    return jax.nn.gelu(x, approximate=True)


def xelu(x, b):
    """Leaky relu variant: x > 0 ? x : x / b (op.h:50-55)."""
    return jnp.where(x > 0, x, x / b)


def mxelu(x, b):
    """Multiplicative leaky relu: x > 0 ? x : x * b (prelu_layer-inl.hpp:11-15)."""
    return jnp.where(x > 0, x, x * b)


def softmax(x):
    """Row softmax over the last dim (mshadow::Softmax equivalent)."""
    return jax.nn.softmax(x, axis=-1)


def lrn(x, local_size: int, alpha: float, beta: float, knorm: float):
    """Cross-channel local response normalization on NCHW.

    out = x * (knorm + alpha/n * sum_{window n}(x^2)) ^ (-beta)
    (lrn_layer-inl.hpp:36-56: tmp_norm = chpool<sum>(x^2) * (alpha/n) + knorm,
    out = x * tmp_norm^(-beta)).
    """
    from cxxnet_tpu.ops import pallas_lrn as pk
    if pk.use_pallas_lrn(x):
        return pk.lrn_pallas(x, local_size, alpha, beta, knorm,
                             pk._FORCE_INTERPRET)
    from cxxnet_tpu.parallel.mesh import get_active_mesh
    mesh = get_active_mesh()
    if mesh is not None and mesh.devices.size > 1 \
            and pk.use_pallas_lrn_sharded(x, mesh):
        return pk.lrn_pallas_sharded(x, mesh, local_size, alpha, beta,
                                     knorm)
    sq = x * x
    pad_lo = local_size // 2
    pad_hi = local_size - pad_lo - 1
    window_sum = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, local_size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (pad_lo, pad_hi), (0, 0), (0, 0)))
    norm = knorm + (alpha / local_size) * window_sum
    return x * jnp.power(norm, -beta)
