"""TPU-first op vocabulary.

The reference builds every layer from mshadow expression templates (dot,
pool, chpool, unpack_patch2col, ...). Here the same vocabulary is provided
as jax.numpy/lax functions that lower to XLA HLO: convolution goes straight
to ConvGeneralDilated (no im2col, no temp_col_max chunking - the compiler
tiles onto the MXU), pooling to reduce_window, LRN to a channel-window
reduce, and backward passes everywhere come from jax.grad instead of the
hand-written Backprop methods.
"""

from cxxnet_tpu.ops.pooling import pool2d, pool_out_dim, insanity_pool2d
from cxxnet_tpu.ops.conv import conv2d, conv_out_dim
from cxxnet_tpu.ops.nn import (
    relu, sigmoid, tanh, softplus, gelu, xelu, mxelu, softmax, lrn)

__all__ = [
    "pool2d", "pool_out_dim", "insanity_pool2d",
    "conv2d", "conv_out_dim",
    "relu", "sigmoid", "tanh", "softplus", "gelu", "xelu", "mxelu",
    "softmax", "lrn",
]
