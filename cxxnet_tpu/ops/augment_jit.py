"""Device-side image augmentation: crop / mirror / mean / scale inside
the jitted train step.

The reference augments every image on the HOST (image_augmenter-inl.hpp
+ the crop/mirror/mean pipeline of iter_img_proc). That is the right
call for GPUs with idle host cores; on a TPU host where a single b256
AlexNet batch costs tens of ms of numpy arithmetic per step, the host
becomes the bottleneck while the MXU idles (bench.py's
host_prep/device split measures exactly this). `device_augment = 1`
moves the per-pixel work onto the device, TPU-style:

- the iterator stages RAW decoded images (io/augment.py passthrough
  mode; uint8 batches ride H2D at 1/4 the f32 bytes);
- the jitted step crops FIRST (per-sample jax.random offsets via
  vmapped dynamic_slice - O(crop) arithmetic, not O(raw)), subtracts
  the mean, applies contrast/illumination draws, mirrors by a
  per-sample flag, scales, and casts to the compute dtype - all fused
  by XLA into the step's leading ops;
- eval/predict use the deterministic variant (center crop, no mirror,
  no jitter), matching AugmentIterator's non-random path.

Semantics parity with io/augment.py `_set_data` (the host pipeline):
(x - mean) * contrast + illumination, crop, mirror, * scale - with the
crop commuted ahead of the (elementwise) subtraction, and the mirror
applied to the difference, exactly as the host path does. The mean
image may be crop-sized (what `_create_mean_img` produces - it
accumulates processed, i.e. cropped, instances) or raw-sized (a
user-provided full-frame mean): crop-sized subtracts directly,
raw-sized is cropped per-sample with the same offsets.

Randomness comes from the step PRNG instead of the iterator's numpy
RandomState - a documented deviation: same distributions, different
stream (the reference seeds per-iterator, we fold per-step).

Affine warps (rotation/shear/aspect/random-scale) are NOT deferrable -
they run scipy on the host - so passthrough mode rejects them
(io/augment.py validates ImageAugmenter.need_process() == False).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Shape3 = Tuple[int, int, int]


def make_device_augment(out_shape: Shape3,
                        mean_loader: Optional[Callable] = None,
                        mean_values: Optional[Tuple[float, float, float]]
                        = None,
                        scale: float = 1.0,
                        rand_crop: int = 0, rand_mirror: int = 0,
                        mirror: int = 0,
                        crop_y_start: int = -1, crop_x_start: int = -1,
                        max_random_contrast: float = 0.0,
                        max_random_illumination: float = 0.0,
                        ) -> Callable:
    """Build `apply(data, rng, train) -> (b, c, ty, tx) float32`.

    out_shape: the net's (c, ty, tx) input_shape. The RAW staged shape
    is read from the traced batch at trace time (no config key needed).
    mean_loader: nullary callable returning the (c, ry, rx)- or
    (c, ty, tx)-shaped f32 mean array (or None) - called lazily at
    trace time, AFTER the iterator had its chance to create the mean
    file on first use. When both are configured, mean_values wins and
    the mean image is never loaded - the host pipeline's precedence
    (io/augment.py:313 checks the per-channel values first).
    """
    c, ty, tx = out_shape
    if mean_values is not None and not any(mean_values):
        # all-zero mean_value is OFF on the host path (the branch tests
        # mean_r/g/b > 0), which also disables contrast/illumination
        mean_values = None
    if mean_values is not None:
        mean_loader = None

    def apply(data, rng, train: bool):
        b, dc, ry, rx = data.shape
        if dc != c or ty > ry or tx > rx:
            raise ValueError(
                f"device_augment: raw batch {data.shape[1:]} cannot "
                f"produce net input {out_shape}")
        meanimg = mean_loader() if mean_loader is not None else None
        if meanimg is not None and meanimg.shape not in (
                (c, ry, rx), (c, ty, tx)):
            raise ValueError(
                f"device_augment: mean image {meanimg.shape} matches "
                f"neither the raw shape {(c, ry, rx)} nor the crop "
                f"shape {(c, ty, tx)}")
        yy_max, xx_max = ry - ty, rx - tx

        k_y, k_x, k_m, k_c, k_i = jax.random.split(rng, 5)
        if train and rand_crop and (yy_max or xx_max):
            yy = jax.random.randint(k_y, (b,), 0, yy_max + 1)
            xx = jax.random.randint(k_x, (b,), 0, xx_max + 1)
        else:
            yy = jnp.full((b,), yy_max // 2, jnp.int32)
            xx = jnp.full((b,), xx_max // 2, jnp.int32)
        # fixed crop offsets (crop_y/x_start) override BOTH the center
        # default and a random draw, exactly like the host path
        # (augment.py applies them after the rand_crop branch). Range-
        # check here: dynamic_slice CLAMPS out-of-range offsets, which
        # would silently train on shifted windows where the host path
        # fails on the resulting shape mismatch
        if yy_max and crop_y_start != -1:
            if not 0 <= crop_y_start <= yy_max:
                raise ValueError(
                    f"device_augment: crop_y_start={crop_y_start} out "
                    f"of range [0, {yy_max}] for raw {ry} crop {ty}")
            yy = jnp.full((b,), crop_y_start, jnp.int32)
        if xx_max and crop_x_start != -1:
            if not 0 <= crop_x_start <= xx_max:
                raise ValueError(
                    f"device_augment: crop_x_start={crop_x_start} out "
                    f"of range [0, {xx_max}] for raw {rx} crop {tx}")
            xx = jnp.full((b,), crop_x_start, jnp.int32)
        if train and rand_mirror:
            # mirror=1 still forces EVERY sample - the host path ORs
            # the flags (io/augment.py:309-310), it does not let the
            # random draw override the unconditional mirror
            mir = jax.random.bernoulli(k_m, 0.5, (b,))
            if mirror:
                mir = jnp.ones((b,), bool)
        else:
            mir = jnp.full((b,), bool(mirror))
        # host-pipeline parity quirk: contrast/illumination only apply
        # on the mean-subtracting branches (augment.py's no-mean branch
        # crops without them) - match it, never "fix" it silently
        has_mean = mean_loader is not None or mean_values is not None
        if train and max_random_contrast > 0 and has_mean:
            contrast = 1.0 + jax.random.uniform(
                k_c, (b,), minval=-max_random_contrast,
                maxval=max_random_contrast)
        else:
            contrast = jnp.ones((b,), jnp.float32)
        if train and max_random_illumination > 0 and has_mean:
            illum = jax.random.uniform(
                k_i, (b,), minval=-max_random_illumination,
                maxval=max_random_illumination)
        else:
            illum = jnp.zeros((b,), jnp.float32)

        mean_c = (jnp.asarray(meanimg, jnp.float32)
                  if meanimg is not None else None)
        raw_mean = mean_c is not None and mean_c.shape == (c, ry, rx)

        def one(img, yy, xx, mir, contrast, illum):
            x = jax.lax.dynamic_slice(
                img, (0, yy, xx), (c, ty, tx)).astype(jnp.float32)
            if mean_values is not None:
                # host precedence: per-channel values beat the mean
                # image (augment.py:313; subtraction only at c == 3,
                # but contrast/illumination apply regardless)
                if c == 3:
                    mb, mg, mr = mean_values
                    x = x - jnp.asarray([mr, mg, mb],
                                        jnp.float32)[:, None, None]
            elif mean_c is not None:
                # crop-then-subtract == subtract-then-crop (elementwise)
                m = (jax.lax.dynamic_slice(mean_c, (0, yy, xx),
                                           (c, ty, tx))
                     if raw_mean else mean_c)
                x = x - m
            x = x * contrast + illum
            # mirror AFTER the subtraction (the host path mirrors the
            # mean-subtracted crop, not the raw pixels)
            x = jnp.where(mir, x[:, :, ::-1], x)
            return x * scale

        return jax.vmap(one)(data, yy, xx, mir, contrast, illum)

    return apply
