"""Scaled-dot-product attention ops: naive, blockwise (flash-style), and
the partial/merge primitives ring attention is built from.

The reference has no attention (cxxnet predates it - SURVEY.md notes
sequence models are absent), so this module is pure TPU-native extension
surface: it exists so the framework's long-context story (ring /
all-to-all sequence parallelism, parallel/ring.py) has a single-device
ground truth and a memory-efficient local kernel.

Layout convention: [batch, heads, seq, head_dim] (BHSD). All softmax
arithmetic runs in float32 regardless of input dtype (bf16 scores lose
the softmax's dynamic range on TPU); the output is cast back to the
query dtype.

The blockwise form is the standard online-softmax recurrence: partial
results are (acc, m, l) - unnormalized weighted values, running row max,
running denominator - merged associatively, which is exactly what lets
the ring variant accumulate across K/V blocks that arrive one ppermute
step at a time.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# Finite stand-in for -inf in masked score entries: exp(x - m) with both
# at -1e30 is exp(0)=1 only when ALL entries of a row are masked, and
# such rows carry l=0 and are resolved by the caller (or cannot occur -
# causal rows always see their own position). -inf itself would produce
# inf-inf=nan in the max-subtraction.
_NEG = -1e30


def _scale(q, scale: Optional[float]) -> float:
    return (1.0 / (q.shape[-1] ** 0.5)) if scale is None else scale


def _causal_bias(sq: int, sk: int, q_offset, kv_offset) -> jax.Array:
    """(sq, sk) additive bias: 0 where key position <= query position in
    GLOBAL coordinates, _NEG elsewhere. Offsets may be traced values
    (ring attention passes lax.axis_index-derived block offsets)."""
    qpos = q_offset + jnp.arange(sq)[:, None]
    kpos = kv_offset + jnp.arange(sk)[None, :]
    return jnp.where(kpos <= qpos, 0.0, _NEG)


def naive_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None):
    """Reference semantics: softmax(q.k^T * scale [+ causal mask]).v with
    the full (sq, sk) score matrix materialized. Ground truth for the
    blockwise/ring variants' differential tests."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s.astype(jnp.float32) * _scale(q, scale)
    if causal:
        s = s + _causal_bias(q.shape[2], k.shape[2], 0, 0)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


def attention_partial(q, k, v, *, scale: Optional[float] = None,
                      causal: bool = False, q_offset=0, kv_offset=0,
                      kv_valid: Optional[int] = None,
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One K/V block's contribution as an online-softmax partial.

    Returns (acc [B,H,Sq,D] f32 unnormalized, m [B,H,Sq] f32 row max,
    l [B,H,Sq] f32 denominator). Offsets place the blocks on the global
    sequence for causal masking (traced values allowed). `kv_valid`
    masks key GLOBAL positions >= kv_valid - the tail-padding mask for
    callers that pad K/V up to a block-size multiple."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s.astype(jnp.float32) * _scale(q, scale)
    if causal:
        s = s + _causal_bias(q.shape[2], k.shape[2],
                             q_offset, kv_offset)[None, None]
    if kv_valid is not None:
        kpos = kv_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where((kpos < kv_valid)[None, None], s, _NEG)
    m = jnp.max(s, axis=-1)
    # keep fully-masked rows finite: their p rows are exp(_NEG - _NEG)=1
    # scaled below by where(), so force p=0 via the mask itself
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= _NEG * 0.5, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def merge_partials(a: Tuple[jax.Array, jax.Array, jax.Array],
                   b: Tuple[jax.Array, jax.Array, jax.Array],
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Associative merge of two online-softmax partials."""
    acc_a, m_a, l_a = a
    acc_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    ca = jnp.exp(m_a - m)
    cb = jnp.exp(m_b - m)
    acc = acc_a * ca[..., None] + acc_b * cb[..., None]
    l = l_a * ca + l_b * cb
    return acc, m, l


def finalize_partial(acc, l, dtype) -> jax.Array:
    """acc/l with fully-masked rows (l=0) resolved to 0."""
    safe = jnp.where(l > 0, l, 1.0)
    return (acc / safe[..., None]).astype(dtype)


def empty_partial(q) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, h, sq, d = q.shape
    return (jnp.zeros((b, h, sq, d), jnp.float32),
            jnp.full((b, h, sq), _NEG, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32))


def blockwise_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None,
                        kv_block: int = 512):
    """Flash-style memory-efficient attention: lax.scan over K/V blocks
    with the online-softmax recurrence; peak score memory is
    (Sq, kv_block) instead of (Sq, Sk). Semantics == naive_attention.

    The scan carries f32 (acc, m, l); XLA keeps the whole loop on-chip.
    Wrap in jax.checkpoint (remat=1) for the O(S) memory backward."""
    sk = k.shape[2]
    kv_block = min(kv_block, sk)
    if nblk_pad := (-sk) % kv_block:
        # static shapes: pad K/V up to the next block multiple and mask
        # the tail (kv_valid). A divisor fallback would degrade to
        # kv_block=1 - an S-iteration serial scan - on prime/odd
        # lengths, exactly the long sequences this exists for.
        pad = ((0, 0), (0, 0), (0, nblk_pad), (0, 0))
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    kv_valid = sk if nblk_pad else None
    nblk = k.shape[2] // kv_block
    if nblk == 1:
        acc, m, l = attention_partial(q, k, v, scale=scale, causal=causal,
                                      kv_valid=kv_valid)
        return finalize_partial(acc, l, q.dtype)

    kb = k.reshape(k.shape[0], k.shape[1], nblk, kv_block, k.shape[3])
    vb = v.reshape(v.shape[0], v.shape[1], nblk, kv_block, v.shape[3])
    kb = jnp.moveaxis(kb, 2, 0)   # [nblk, B, H, kv_block, D]
    vb = jnp.moveaxis(vb, 2, 0)

    def step(carry, xs):
        kv_i, k_i, v_i = xs
        part = attention_partial(q, k_i, v_i, scale=scale, causal=causal,
                                 q_offset=0, kv_offset=kv_i * kv_block,
                                 kv_valid=kv_valid)
        return merge_partials(carry, part), None

    init = empty_partial(q)
    (acc, _, l), _ = lax.scan(step, init, (jnp.arange(nblk), kb, vb))
    return finalize_partial(acc, l, q.dtype)
