"""Int8 post-training-quantized inference kernels (docs/GRAPH_PASSES.md
"quantize_int8").

The quantize_int8 graph pass (nnet/passes.py) stamps eligible
conv/fullc layers with a per-channel symmetric weight scale and a
per-tensor activation scale, both FROZEN at calibration time exactly
like fold_conv_bn's (mean, rstd) - so the steady-state executable
carries no max-reductions over weights or activations, only one fused
round/clip/convert pass per quantized tensor. This module is the
execution vocabulary of that pass:

- ``per_channel_scale`` / ``quantize_weight``: symmetric per-output-
  channel weight quantization. The scale is computed HOST-side from
  the transformed float weights at calibration (trainer
  `_fill_quant_scales`); the int8 values are computed IN-JIT from the
  live params, so a checkpoint load or set_weight is picked up
  (the frozen scale goes stale instead and the epoch-bump eviction
  recalibrates, the fold-stats invalidation rule).
- ``quantize_act``: per-tensor symmetric activation quantization
  against the frozen calibration scale (absmax / 127).
- ``int8_matmul``: `(m, k) x (n, k) -> (m, n)` int8 x int8 -> int32
  contraction - a Pallas TPU kernel tiling onto the MXU (int8 native
  rate, int32 accumulators) when the shape tiles cleanly, else
  `lax.dot_general` with ``preferred_element_type=int32`` (the CPU
  fallback the jaxpr quant-audit traces: int8 operands, int32
  accumulation, no f32 data-path dot either way).
- ``int8_conv2d``: NCHW int8 convolution with int32 accumulation via
  `lax.conv_general_dilated` (XLA lowers it onto the TPU MXU
  directly; no space-to-depth rewrite on the int8 path).

Cost model (docs/PERFORMANCE.md): the int8 win is weight-bandwidth +
MXU rate. Measured on XLA:CPU (bench.py `int8_over_fold`), the
small-batch weight-bound serving regime wins ~1.4x on the bench's
2048-wide fullc MLP at batch 16, while large batches (>= 64 rows)
and CPU convolutions LOSE - which is exactly what the per-layer
``layer_quant`` tuning axis exists to pin per platform
(docs/GRAPH_PASSES.md "when int8 loses").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# contraction over the last dim of both operands: x (m, k) . w (n, k)
_DN = (((1,), (1,)), ((), ()))

# smallest representable scale guard: an all-zero channel/tensor must
# quantize to zeros, not divide by zero
_SCALE_FLOOR = 1e-8

# int8 MXU tiling units (pallas_guide.md): sublane 32, lane 128
_SUBLANE, _LANE = 32, 128
# per-operand VMEM block budget (bytes); conservative vs the ~16 MB
# per-core VMEM so x/w/out blocks + double buffering fit
_VMEM_BLOCK_BYTES = 4 * 2 ** 20

# test hook: force the Pallas kernel on non-TPU backends in interpret
# mode (the pallas_lrn _FORCE_INTERPRET idiom) so CI exercises the
# kernel path without a TPU
_FORCE_INTERPRET = False


def per_channel_scale(w: np.ndarray) -> np.ndarray:
    """Symmetric per-output-channel (dim 0) scale of a weight:
    absmax / 127 per channel, floored so an all-zero channel gets a
    representable scale. HOST-side numpy - called once at calibration
    (the frozen constant the in-jit quantize divides by)."""
    w = np.asarray(w, np.float32)
    amax = np.abs(w.reshape(w.shape[0], -1)).max(axis=1)
    return (np.maximum(amax, _SCALE_FLOOR) / 127.0).astype(np.float32)


def quantize_weight(w: jax.Array, scale) -> jax.Array:
    """In-jit weight quantization against a FROZEN per-channel scale:
    one fused multiply/round/clip/convert pass over the live weight
    (no max-reduction - that happened at calibration). `scale` is
    (out_channels,); broadcasts over the remaining dims."""
    scale = jnp.asarray(scale, jnp.float32)
    inv = (1.0 / scale).reshape((-1,) + (1,) * (w.ndim - 1))
    q = jnp.clip(jnp.round(w.astype(jnp.float32) * inv), -127, 127)
    return q.astype(jnp.int8)


def quantize_act(x: jax.Array, scale) -> jax.Array:
    """Per-tensor activation quantization against the frozen
    calibration scale (a scalar): clip(round(x / s)) to [-127, 127]."""
    s = jnp.asarray(scale, jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8)


def dequantize(acc: jax.Array, act_scale, w_scale) -> jax.Array:
    """int32 accumulator -> f32: acc * (act_scale * w_scale) with the
    per-channel weight scale broadcast over the trailing dims for
    conv (n, c, h, w) or the feature dim for matmul (m, n)."""
    s = (jnp.asarray(act_scale, jnp.float32)
         * jnp.asarray(w_scale, jnp.float32))
    if acc.ndim == 4:
        return acc.astype(jnp.float32) * s[None, :, None, None]
    return acc.astype(jnp.float32) * s[None, :]


# ---------------------------------------------------------------------------
# the int8 dot: Pallas TPU kernel + lax fallback
# ---------------------------------------------------------------------------
def _mm_kernel(x_ref, w_ref, o_ref):
    # one (bm, k) x (bn, k) -> (bm, bn) MXU contraction per grid cell;
    # int32 accumulation is the kernel's whole point - never let the
    # dot default to a narrower accumulator
    o_ref[:, :] = lax.dot_general(
        x_ref[:, :], w_ref[:, :], _DN,
        preferred_element_type=jnp.int32)


def _block(dim: int, unit: int, cap: int = 512) -> int:
    """Largest divisor of `dim` that is a multiple of `unit` and at
    most `cap`; 0 when none exists (the shape does not tile)."""
    best = 0
    b = unit
    while b <= min(dim, cap):
        if dim % b == 0:
            best = b
        b += unit
    return best


def _pallas_blocks(m: int, k: int, n: int):
    """(bm, bn) Pallas block sizes, or None when the shape violates
    the int8 tiling constraints / VMEM budget and the lax fallback
    must run."""
    if k % _LANE:
        return None
    bm, bn = _block(m, _SUBLANE), _block(n, _LANE)
    if not bm or not bn:
        return None
    if max(bm, bn) * k > _VMEM_BLOCK_BYTES:
        return None
    return bm, bn


def use_pallas_int8(m: int, k: int, n: int) -> bool:
    """Kernel-route eligibility: TPU backend (or the interpret-mode
    test hook), a single device (pallas_call has no GSPMD
    partitioning rule - multi-device meshes take the lax path, which
    GSPMD partitions), and clean int8 tiling."""
    if not (jax.default_backend() == "tpu" or _FORCE_INTERPRET):
        return False
    if jax.device_count() != 1:
        return False
    return _pallas_blocks(m, k, n) is not None


def _matmul_pallas(xq: jax.Array, wq: jax.Array) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    m, k = xq.shape
    n = wq.shape[0]
    bm, bn = _pallas_blocks(m, k, n)
    return pl.pallas_call(
        _mm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.int32),
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((bn, k), lambda i, j: (j, 0))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=_FORCE_INTERPRET,
    )(xq, wq)


def int8_matmul(xq: jax.Array, wq: jax.Array) -> jax.Array:
    """`xq (m, k) . wq (n, k)^T -> (m, n)` with int8 operands and
    int32 accumulation: the Pallas MXU kernel when eligible, else the
    lax.dot_general preferred-element-type fallback (same jaxpr-level
    contract either way - the quant-audit asserts it)."""
    m, k = xq.shape
    if use_pallas_int8(m, k, wq.shape[0]):
        return _matmul_pallas(xq, wq)
    return lax.dot_general(xq, wq, _DN,
                           preferred_element_type=jnp.int32)


def int8_conv2d(xq: jax.Array, wq: jax.Array, stride: int, pad_y: int,
                pad_x: int, num_group: int = 1) -> jax.Array:
    """Grouped NCHW int8 convolution with int32 accumulation. The
    space-to-depth rewrite does not apply on the int8 path (the
    direct lowering is value-identical; s2d exists for f32/bf16 MXU
    density, which int8 gets from its native rate)."""
    return lax.conv_general_dilated(
        xq, wq,
        window_strides=(stride, stride),
        padding=((pad_y, pad_y), (pad_x, pad_x)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=num_group,
        preferred_element_type=jnp.int32,
    )
