#!/usr/bin/env python3
"""launch_dist: spawn an N-worker multi-controller job on this host.

The localhost analog of the reference's ps-lite launcher + mpi.conf
(example/MNIST/mpi.conf: num_servers/num_workers on one machine) - except
there are no server processes to launch: every worker runs the same SPMD
program and gradients ride XLA collectives (parallel/distributed.py).

Usage:
  launch_dist.py -n 4 [--coordinator 127.0.0.1:29500] -- \\
      python -m cxxnet_tpu.main train.conf param_server=dist

Each worker gets CXN_COORDINATOR / CXN_NUM_WORKER / CXN_WORKER_RANK in
its environment; config keys dist_num_worker/dist_worker_rank on the
iterators pick up the worker's data shard.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List


def launch(cmd: List[str], num_workers: int,
           coordinator: str = "127.0.0.1:29500",
           extra_env: dict | None = None) -> int:
    import time
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env["CXN_COORDINATOR"] = coordinator
        env["CXN_NUM_WORKER"] = str(num_workers)
        env["CXN_WORKER_RANK"] = str(rank)
        if extra_env:
            env.update(extra_env)
        procs.append(subprocess.Popen(cmd, env=env))
    # poll all workers: one crashing must tear the job down, or the
    # survivors hang forever inside collectives waiting for the peer
    rc = 0
    live = list(procs)
    while live and rc == 0:
        time.sleep(0.2)
        for p in list(live):
            code = p.poll()
            if code is not None:
                live.remove(p)
                rc = rc or code
    if rc:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def cli_main() -> None:
    args = sys.argv[1:]
    num_workers = 2
    coordinator = "127.0.0.1:29500"
    cmd: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a in ("-n", "--num-workers"):
            num_workers = int(args[i + 1])
            i += 2
        elif a == "--coordinator":
            coordinator = args[i + 1]
            i += 2
        elif a == "--":
            cmd = args[i + 1:]
            break
        else:
            print(__doc__)
            sys.exit(1)
    if not cmd:
        print(__doc__)
        sys.exit(1)
    sys.exit(launch(cmd, num_workers, coordinator))


if __name__ == "__main__":
    cli_main()
