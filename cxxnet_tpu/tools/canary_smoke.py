"""Canaried-rollout smoke: both judge verdicts under live load.

    python -m cxxnet_tpu.tools.canary_smoke [--out DIR] [--keep]

Trains the tiny synthetic-MNIST MLP through the real CLI (two rounds,
two consecutive checkpoints - bitwise-different weights that agree on
nearly every argmax, the realistic canary shape), then drives a live
HTTP server with `canary_frac`/`canary_window` armed through both
verdicts of docs/SERVING.md "Canary runbook":

- service time is pinned with the `serve_dispatch_delay` fault
  injector (as in serve_http_smoke: makes "2x the sustainable rate"
  deterministic across CI machines), and an OPEN-LOOP Poisson storm
  at ~2x sustainable runs long enough to straddle the whole canary
  window;
- PROMOTE leg: the round-2 checkpoint atomically published MID-STORM
  starts a canary (a deterministic request fraction served by the
  candidate through the SAME warmed bucket executables - the
  executable cache must stay flat), the judge auto-promotes at the
  window, zero requests drop (every response a 200, `errors == 0`),
  and post-promote answers match a cold Server restarted on the new
  checkpoint bit for bit;
- ROLLBACK leg: the same checkpoint republished with the
  `canary_divergence` fault armed ("corrupt" NaN-poisons the shadow
  outputs) must be auto-rolled-back (`swap.rolled_back`), with the
  incumbent still serving bitwise-identical answers afterwards;
- every /metrics scrape along the way must be exposition-valid.

Exit 0 iff all checks pass; CI uploads the tallies as artifacts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 2
max_round = 2
eta = 0.3
metric = error
silent = 1
"""

# the same net, sans data/training keys: the in-process servers load
# the CLI-trained checkpoints into this config
NET_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
silent = 1
"""


def _run_cli(out_dir: str, *overrides: str) -> subprocess.CompletedProcess:
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    return subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, "canary_smoke.conf"), *overrides],
        env=env, capture_output=True, text=True, timeout=540)


def _post(port: int, payload: dict, timeout: float = 120.0):
    """POST /predict; returns (status, headers, parsed body)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        return r.read().decode()


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu import telemetry
    from cxxnet_tpu.nnet import checkpoint
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve import Server
    from cxxnet_tpu.telemetry.http import validate_exposition
    from cxxnet_tpu.utils import fault

    write_synth_mnist(out_dir, 192, 0, "train")
    conf = os.path.join(out_dir, "canary_smoke.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(d=out_dir))
    mdir = os.path.join(out_dir, "models")
    ck_old = os.path.join(mdir, "0001.model")
    ck_new = os.path.join(mdir, "0002.model")
    publish = os.path.join(out_dir, "publish.model")

    train = _run_cli(out_dir, f"model_dir={mdir}")
    trained = (train.returncode == 0 and os.path.exists(ck_old)
               and os.path.exists(ck_new))

    checks = [("train run produced two checkpoints", trained)]
    tally = {"200": 0, "other": 0}
    bad_scrapes = []
    stats = {}
    canary_routed = 0
    promoted = cache_flat = post_matches_cold = False
    rolled_back = incumbent_intact = False

    if trained:
        tr = NetTrainer(dev="cpu", cfg=NET_CFG)
        with open(ck_old, "rb") as f:
            tr.load_model(f)
        srv = Server(tr, max_batch=4, max_wait_ms=2.0, replicas=1,
                     http_port=0, swap_watch=publish,
                     swap_poll_ms=25.0, canary_frac=0.5,
                     canary_window=1.5)
        srv.warmup()
        n_warm = srv.executable_cache_size()
        # pin the service time (50ms/dispatch): sustainable capacity
        # is then deterministic on every CI machine
        fault.clear()
        for k in range(4000):
            fault.inject("serve_dispatch_delay", "delay", "0.05",
                         at=k + 1)
        srv.start()
        port = srv.metrics_server.port
        rng = np.random.RandomState(31)
        probe = rng.randn(4, 36).astype(np.float32).tolist()
        payload = {"data": probe, "raw": True}
        lock = threading.Lock()
        pre_swap = _post(port, payload)[2].get("outputs")
        bad_scrapes.extend(validate_exposition(_scrape(port)))

        # --- promote leg: 2x-sustainable Poisson storm straddling the
        # whole canary window, checkpoint published mid-storm --------
        sustainable_rps = (1 * 4 / 0.05) / 4.0  # 4-row requests
        n_req = 120
        gaps = rng.exponential(1.0 / (2.0 * sustainable_rps), n_req)
        arrivals = np.cumsum(gaps)

        def fire(i):
            code, _, _ = _post(port, payload)
            with lock:
                tally["200" if code == 200 else "other"] += 1

        threads = []
        t_start = time.perf_counter()
        for i in range(n_req):
            pause = t_start + float(arrivals[i]) - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            if i == n_req // 4:
                # mid-storm: atomically publish the round-2 weights -
                # the watcher starts a canary while the storm runs
                checkpoint.publish_model(ck_new, publish)
            t = threading.Thread(target=fire, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=300)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if srv.stats()["canary_promoted"] >= 1:
                break
            time.sleep(0.05)
        mid = srv.stats()
        promoted = (mid["canary_promoted"] == 1 and mid["swaps"] == 1
                    and mid["canary_rolled_back"] == 0)
        canary_routed = mid["canary_requests"]
        cache_flat = srv.executable_cache_size() == n_warm
        post_swap = _post(port, payload)[2].get("outputs")
        bad_scrapes.extend(validate_exposition(_scrape(port)))

        # --- rollback leg: republish with poisoned shadow outputs ---
        fault.clear()
        for k in range(50):
            fault.inject("canary_divergence", "corrupt", at=k + 1)
        checkpoint.publish_model(ck_new, publish)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if srv.stats()["canary_rolled_back"] >= 1:
                break
            # a light trickle keeps shadow samples flowing
            _post(port, payload)
            time.sleep(0.05)
        fault.clear()
        end = srv.stats()
        rolled_back = (end["canary_rolled_back"] == 1
                       and end["swaps"] == 1)
        post_rollback = _post(port, payload)[2].get("outputs")
        incumbent_intact = post_rollback == post_swap
        bad_scrapes.extend(validate_exposition(_scrape(port)))
        stats = srv.stop()

        # cold reference: a fresh server over the promoted checkpoint
        tr_new = NetTrainer(dev="cpu", cfg=NET_CFG)
        with open(ck_new, "rb") as f:
            tr_new.load_model(f)
        srv2 = Server(tr_new, max_batch=4, max_wait_ms=2.0,
                      replicas=1, http_port=0)
        srv2.warmup()
        srv2.start()
        cold = _post(srv2.metrics_server.port, payload)[2].get(
            "outputs")
        srv2.stop()
        post_matches_cold = (post_swap == cold
                             and post_swap != pre_swap)
        telemetry.reset_for_tests()

        checks += [
            ("mid-storm publish canaried + auto-promoted at window "
             "(swaps == 1)", promoted),
            ("canary traffic routed to the candidate side",
             canary_routed > 0),
            ("zero drops across storm + both verdicts (all 200s, "
             "errors == 0)",
             tally["other"] == 0 and stats.get("errors") == 0),
            ("executable cache flat (both sides share warmed "
             "executables)", cache_flat),
            ("post-promote answers == cold restart on the new "
             "checkpoint", post_matches_cold),
            ("poisoned republish auto-rolled-back (swaps stays 1)",
             rolled_back),
            ("incumbent bitwise-unchanged after rollback",
             incumbent_intact),
            ("every /metrics scrape exposition-valid",
             not bad_scrapes),
        ]

    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not trained:
        print("--- train stderr tail ---")
        print(train.stderr[-2000:])
    for line in bad_scrapes[:5]:
        print(f"  bad exposition line: {line}")
    with open(os.path.join(out_dir, "canary_summary.json"), "w") as f:
        json.dump({"codes": tally, "canary_requests": canary_routed,
                   "server_stats": stats}, f, indent=1, default=str)
    print(f"canary_smoke: {'PASS' if ok else 'FAIL'} "
          f"(codes {tally}, canary_requests {canary_routed})")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: canary_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="canary_smoke_")
        rc = run_smoke(d)
        print(f"canary_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
