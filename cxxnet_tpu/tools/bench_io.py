"""Decode-pipeline benchmark: native C++ two-stage reader vs Python
fallback (docs/io.md).

The reference overlaps page IO with a JPEG decode pool
(iter_thread_imbin-inl.hpp); `native/cxxnet_io.cc` plays that role here
and this tool measures what the margin actually is, so the io budget
for pod-scale feeding is a number, not an assumption (SURVEY.md §7
hard-part #4).

Generates a synthetic imgbin (JPEG blobs of a given size), then streams
it through `ImageBinIterator` with `use_native=1` (C++ page reader +
libjpeg decode pool + reorder buffer) and `use_native=0` (Python page
prefetch thread + PIL decode on the caller), reporting decoded
images/sec for each.

Usage: python -m cxxnet_tpu.tools.bench_io [n_images] [size] [threads]
"""

from __future__ import annotations

import io
import os
import sys
import tempfile
import time

import numpy as np


def make_dataset(tmp: str, n: int, size: int) -> tuple:
    """Write n JPEGs of (size x size) into an imgbin + list file."""
    from PIL import Image
    from cxxnet_tpu.utils.binary_page import BinaryPageWriter

    rng = np.random.RandomState(0)
    # a handful of distinct images cycled, so dataset build stays fast
    # but blobs are real JPEG work to decode
    blobs = []
    for _ in range(min(n, 16)):
        arr = rng.randint(0, 255, (size, size, 3), np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        blobs.append(buf.getvalue())
    bin_path = os.path.join(tmp, "bench.bin")
    lst_path = os.path.join(tmp, "bench.lst")
    with open(bin_path, "wb") as fo:
        w = BinaryPageWriter(fo)
        for i in range(n):
            w.push(blobs[i % len(blobs)])
        w.close()
    with open(lst_path, "w") as fo:
        for i in range(n):
            fo.write(f"{i}\t0\timg{i}.jpg\n")
    return lst_path, bin_path


def run_mode(lst: str, bin_path: str, use_native: int,
             threads: int) -> float:
    from cxxnet_tpu.io.iter_img import ImageBinIterator
    it = ImageBinIterator()
    it.set_param("image_list", lst)
    it.set_param("image_bin", bin_path)
    it.set_param("use_native", str(use_native))
    it.set_param("decode_threads", str(threads))
    it.set_param("silent", "1")
    it.init()
    n = 0
    t0 = time.perf_counter()
    it.before_first()
    while it.next():
        n += 1
    dt = time.perf_counter() - t0
    return n / dt


def main(argv) -> int:
    n = int(argv[0]) if len(argv) > 0 else 2000
    size = int(argv[1]) if len(argv) > 1 else 256
    threads = int(argv[2]) if len(argv) > 2 else 4
    from cxxnet_tpu.io.native import native_available
    with tempfile.TemporaryDirectory() as tmp:
        lst, bin_path = make_dataset(tmp, n, size)
        py_ips = run_mode(lst, bin_path, 0, threads)
        print(f"python decode: {py_ips:.1f} images/sec "
              f"({n} x {size}x{size} JPEG)")
        if native_available():
            nat_ips = run_mode(lst, bin_path, 1, threads)
            print(f"native decode ({threads} threads): {nat_ips:.1f} "
                  f"images/sec ({nat_ips / py_ips:.2f}x python)")
        else:
            print("native decode: libcxxnet_io.so not built "
                  "(make -C native)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
