"""Write the sklearn handwritten-digits dataset as MNIST idx files.

The reference's acceptance bar is "run example/MNIST/MNIST.conf
unmodified -> ~98% accuracy" (example/MNIST/README.md:104-109). This
sandbox has no network egress, so the real MNIST files cannot be
fetched; the nearest REAL handwriting data available offline is
sklearn.datasets.load_digits (1797 scanned 8x8 digits from the UCI
optical-recognition corpus). This tool upsamples them to 28x28 and
writes gzip idx files with the exact MNIST magic/layout, so MNIST.conf
runs byte-for-byte unmodified against real handwritten data.

Usage: python -m cxxnet_tpu.tools.digits_to_idx <outdir> [test_fraction]
"""

from __future__ import annotations

import gzip
import os
import struct
import sys

import numpy as np


def write_idx(out_dir: str, prefix: str, images: np.ndarray,
              labels: np.ndarray) -> None:
    """gzip idx files: magic 2051 (images) / 2049 (labels), big-endian
    dims, uint8 payload - the layout iter_mnist expects."""
    n, rows, cols = images.shape
    with gzip.open(os.path.join(
            out_dir, f"{prefix}-images-idx3-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, rows, cols))
        f.write(np.ascontiguousarray(images, np.uint8).tobytes())
    with gzip.open(os.path.join(
            out_dir, f"{prefix}-labels-idx1-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(np.ascontiguousarray(labels, np.uint8).tobytes())


def build(out_dir: str, test_fraction: float = 0.2,
          seed: int = 0) -> tuple:
    from scipy import ndimage
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = d.images  # (1797, 8, 8) float in [0, 16]
    up = np.stack([
        ndimage.zoom(im, 28.0 / 8.0, order=1) for im in imgs])
    up = np.clip(up * (255.0 / 16.0), 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)

    rng = np.random.RandomState(seed)
    order = rng.permutation(len(up))
    n_test = int(len(up) * test_fraction)
    test_idx, train_idx = order[:n_test], order[n_test:]

    os.makedirs(out_dir, exist_ok=True)
    write_idx(out_dir, "train", up[train_idx], labels[train_idx])
    write_idx(out_dir, "t10k", up[test_idx], labels[test_idx])
    return len(train_idx), n_test


def main(argv) -> int:
    out_dir = argv[0] if argv else "./data"
    frac = float(argv[1]) if len(argv) > 1 else 0.2
    ntr, nte = build(out_dir, frac)
    print(f"wrote {ntr} train / {nte} test real handwritten digits "
          f"to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
