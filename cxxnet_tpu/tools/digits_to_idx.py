"""Write the sklearn handwritten-digits dataset as MNIST idx files.

The reference's acceptance bar is "run example/MNIST/MNIST.conf
unmodified -> ~98% accuracy" (example/MNIST/README.md:104-109). This
sandbox has no network egress, so the real MNIST files cannot be
fetched; the nearest REAL handwriting data available offline is
sklearn.datasets.load_digits (1797 scanned 8x8 digits from the UCI
optical-recognition corpus). This tool upsamples them to 28x28 and
writes gzip idx files with the exact MNIST magic/layout, so MNIST.conf
runs byte-for-byte unmodified against real handwritten data.

Usage: python -m cxxnet_tpu.tools.digits_to_idx <outdir> [test_fraction]
"""

from __future__ import annotations

import gzip
import os
import struct
import sys

import numpy as np


def write_idx(out_dir: str, prefix: str, images: np.ndarray,
              labels: np.ndarray) -> None:
    """gzip idx files: magic 2051 (images) / 2049 (labels), big-endian
    dims, uint8 payload - the layout iter_mnist expects."""
    n, rows, cols = images.shape
    with gzip.open(os.path.join(
            out_dir, f"{prefix}-images-idx3-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, rows, cols))
        f.write(np.ascontiguousarray(images, np.uint8).tobytes())
    with gzip.open(os.path.join(
            out_dir, f"{prefix}-labels-idx1-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(np.ascontiguousarray(labels, np.uint8).tobytes())


def load_split(test_fraction: float = 0.2, seed: int = 0) -> tuple:
    """The canonical acceptance split: (train_x, train_y, test_x,
    test_y), images uint8 (n, 28, 28). ONE function owns the
    upsampling + shuffle so the framework acceptance runs and the
    same-split external baselines (docs/acceptance/baseline_mlp.py)
    provably train on identical data."""
    from scipy import ndimage
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = d.images  # (1797, 8, 8) float in [0, 16]
    up = np.stack([
        ndimage.zoom(im, 28.0 / 8.0, order=1) for im in imgs])
    up = np.clip(up * (255.0 / 16.0), 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)

    rng = np.random.RandomState(seed)
    order = rng.permutation(len(up))
    n_test = int(len(up) * test_fraction)
    test_idx, train_idx = order[:n_test], order[n_test:]
    return (up[train_idx], labels[train_idx],
            up[test_idx], labels[test_idx])


def build(out_dir: str, test_fraction: float = 0.2,
          seed: int = 0) -> tuple:
    train_x, train_y, test_x, test_y = load_split(test_fraction, seed)
    os.makedirs(out_dir, exist_ok=True)
    write_idx(out_dir, "train", train_x, train_y)
    write_idx(out_dir, "t10k", test_x, test_y)
    return len(train_x), len(test_x)


def main(argv) -> int:
    out_dir = argv[0] if argv else "./data"
    frac = float(argv[1]) if len(argv) > 1 else 0.2
    ntr, nte = build(out_dir, frac)
    print(f"wrote {ntr} train / {nte} test real handwritten digits "
          f"to {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
