"""Live cross-host telemetry aggregation (docs/OBSERVABILITY.md).

    python -m cxxnet_tpu.tools.agg host0.metrics.jsonl host1.metrics.jsonl
    python -m cxxnet_tpu.tools.agg http://tpu-a:9100 http://tpu-b:9100 --follow
    python -m cxxnet_tpu.tools.agg run*.jsonl --json

Before this tool, a multi-host run's telemetry story was OFFLINE:
per-host JSONL streams merged by ``sort -k ts`` after the fact (the
ROADMAP pod item's open end). This tool is the live view: each source
is either a per-host metrics JSONL (tailed incrementally - ``--follow``
keeps reading as the run appends) or a live process's ``/varz``
endpoint (scraped per poll; same record schema by construction), and
every poll renders ONE merged cluster table:

- one row per process (host/pid): record age, round, steps, step
  p50/p99 ms, images/sec, loss, NaN rollbacks, serve queue depth;
- a **step-time spread** line: max/min of per-host step p50 and the
  ratio between them - the straggler signal (arXiv:2004.13336
  multi-controller training runs at the speed of its slowest host);
- hosts whose p50 exceeds ``--straggler-factor`` x the cluster median
  are flagged ``STRAGGLER``; hosts silent past ``--stale-secs`` are
  flagged ``STALE`` (preempted / wedged / partitioned).

``--follow`` re-polls every ``--interval`` seconds and reprints;
``--json`` emits the merged state as one JSON object for scripting.

``--verdict-json`` is the detection-to-DECISION surface (the elastic
supervisor's conviction input, parallel/elastic.py): one JSON object
whose ``restart`` list names every process the aggregation convicts -
``stale`` (silent past ``--stale-secs``: preempted / wedged /
partitioned) or ``straggler`` (step p50 past ``--straggler-factor`` x
the cluster median). Exit status 3 when a restart is recommended, 0
when the pod is healthy - scriptable both ways.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

STALE_SECS = 60.0
STRAGGLER_FACTOR = 1.5


class _JsonlSource:
    """Incremental tail of one per-host metrics JSONL: every poll
    parses only the bytes appended since the last one, and a torn
    last line (writer mid-record) stays unconsumed until its newline
    arrives."""

    def __init__(self, path: str) -> None:
        self.name = path
        self.path = path
        self.errors = 0
        self._pos = 0
        self._buf = ""

    def poll(self) -> List[Dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            self.errors += 1
            return []
        self._buf += chunk
        out: List[Dict] = []
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # corrupt line: skip, like read_jsonl
            if isinstance(rec, dict):
                out.append(rec)
        return out


class _VarzSource:
    """One live process's /varz endpoint; each poll yields one
    metrics-stream-schema record (http.py builds it that way, so file
    tails and live scrapes feed the same ingest)."""

    def __init__(self, url: str) -> None:
        base = url if "://" in url else f"http://{url}"
        if not base.rstrip("/").endswith("/varz"):
            base = base.rstrip("/") + "/varz"
        self.name = base
        self.url = base
        self.errors = 0

    def poll(self) -> List[Dict]:
        try:
            with urllib.request.urlopen(self.url, timeout=2.0) as r:
                rec = json.load(r)
        except (OSError, ValueError, urllib.error.URLError):
            self.errors += 1
            return []
        return [rec] if isinstance(rec, dict) else []


def make_source(spec: str):
    """`http://...` / `host:port` scrape /varz; anything else tails a
    JSONL file."""
    if "://" in spec:
        return _VarzSource(spec)
    head, _, tail = spec.rpartition(":")
    if head and tail.isdigit():
        return _VarzSource(spec)
    return _JsonlSource(spec)


def _hist(metrics: Dict, name: str, stat: str) -> Optional[float]:
    h = metrics.get(name)
    if isinstance(h, dict):
        v = h.get(stat)
        return float(v) if v is not None else None
    return None


def _num(metrics: Dict, name: str) -> Optional[float]:
    v = metrics.get(name)
    return float(v) if isinstance(v, (int, float)) else None


class HostState:
    """Latest view of one process, merged from its records on
    ts+proc tags (key = host/pid, the stream's process identity)."""

    def __init__(self, key: str) -> None:
        self.key = key
        self.proc: object = "?"
        self.last_ts = 0.0
        self.round: Optional[int] = None
        self.steps: Optional[int] = None
        self.step_p50_ms: Optional[float] = None
        self.step_p99_ms: Optional[float] = None
        self.images_per_sec: Optional[float] = None
        self.loss: Optional[float] = None
        self.nan_rollbacks: Optional[int] = None
        self.queue_depth: Optional[float] = None
        # counter-delta rate fallback for varz scrapes (no per-round
        # images_per_sec field on a bare registry snapshot)
        self._prev_images: Optional[float] = None
        self._prev_ts: Optional[float] = None

    def ingest(self, rec: Dict) -> None:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)) or ts < self.last_ts:
            # merge discipline: records apply in ts order; a late
            # cross-source replay of older state must not regress the
            # live row
            return
        self.last_ts = float(ts)
        if "proc" in rec:
            self.proc = rec.get("proc")
        if rec.get("kind") == "round":
            if rec.get("round") is not None:
                self.round = rec.get("round")
            if rec.get("images_per_sec") is not None:
                self.images_per_sec = rec.get("images_per_sec")
        metrics = rec.get("metrics")
        if not isinstance(metrics, dict):
            return
        h = metrics.get("train.step_s")
        if isinstance(h, dict):
            if h.get("count") is not None:
                self.steps = int(h["count"])
            p50 = _hist(metrics, "train.step_s", "p50")
            p99 = _hist(metrics, "train.step_s", "p99")
            self.step_p50_ms = p50 * 1e3 if p50 is not None else None
            self.step_p99_ms = p99 * 1e3 if p99 is not None else None
        if _num(metrics, "train.loss") is not None:
            self.loss = _num(metrics, "train.loss")
        if _num(metrics, "fault.nan_rollback") is not None:
            self.nan_rollbacks = int(_num(metrics, "fault.nan_rollback"))
        if _num(metrics, "serve.queue_depth") is not None:
            self.queue_depth = _num(metrics, "serve.queue_depth")
        images = _num(metrics, "train.images")
        if images is not None:
            if (self._prev_images is not None
                    and self._prev_ts is not None
                    and self.last_ts > self._prev_ts
                    and images > self._prev_images):
                self.images_per_sec = round(
                    (images - self._prev_images)
                    / (self.last_ts - self._prev_ts), 1)
            self._prev_images, self._prev_ts = images, self.last_ts


class Aggregator:
    def __init__(self, sources, stale_secs: float = STALE_SECS,
                 straggler_factor: float = STRAGGLER_FACTOR) -> None:
        self.sources = sources
        self.hosts: Dict[str, HostState] = {}
        self.stale_secs = stale_secs
        self.straggler_factor = straggler_factor

    def poll(self) -> int:
        n = 0
        for src in self.sources:
            for rec in src.poll():
                self.ingest(rec)
                n += 1
        return n

    def ingest(self, rec: Dict) -> None:
        key = f"{rec.get('host')}/{rec.get('pid')}"
        st = self.hosts.get(key)
        if st is None:
            st = self.hosts[key] = HostState(key)
        st.ingest(rec)

    # -- analysis ----------------------------------------------------------
    def spread(self) -> Optional[Dict[str, float]]:
        """Per-host step-p50 spread: {min, max, median, ratio}."""
        vals = sorted(h.step_p50_ms for h in self.hosts.values()
                      if h.step_p50_ms is not None)
        if not vals:
            return None
        mid = vals[len(vals) // 2] if len(vals) % 2 else \
            0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
        return {"min_ms": vals[0], "max_ms": vals[-1], "median_ms": mid,
                "ratio": vals[-1] / vals[0] if vals[0] > 0
                else float("inf")}

    def flags(self, host: HostState, now: float) -> List[str]:
        out = []
        if host.last_ts and now - host.last_ts > self.stale_secs:
            out.append("STALE")
        sp = self.spread()
        if (sp is not None and host.step_p50_ms is not None
                and len(self.hosts) > 1
                and host.step_p50_ms
                > self.straggler_factor * sp["median_ms"]):
            out.append("STRAGGLER")
        return out

    def to_dict(self, now: Optional[float] = None) -> Dict:
        # graftlint: disable=GL004 record ages compare against the streams' wall-clock ts tags
        now = time.time() if now is None else now
        hosts = {}
        for key in sorted(self.hosts):
            h = self.hosts[key]
            hosts[key] = {
                "proc": h.proc,
                "age_s": round(now - h.last_ts, 1) if h.last_ts else None,
                "round": h.round, "steps": h.steps,
                "step_p50_ms": h.step_p50_ms,
                "step_p99_ms": h.step_p99_ms,
                "images_per_sec": h.images_per_sec,
                "loss": h.loss, "nan_rollbacks": h.nan_rollbacks,
                "queue_depth": h.queue_depth,
                "flags": self.flags(h, now),
            }
        return {"hosts": hosts, "spread": self.spread(),
                "source_errors": {s.name: s.errors
                                  for s in self.sources if s.errors}}

    def verdict(self, now: Optional[float] = None) -> Dict:
        """Machine-readable restart recommendation: the cluster state
        (to_dict) plus a ``restart`` list - one entry per process the
        flags convict, with the evidence (record age for STALE, p50
        ratio vs the cluster median for STRAGGLER). Deterministic in
        ``now`` so tests pin it with a fake clock."""
        d = self.to_dict(now)
        sp = d["spread"]
        restart = []
        for key, h in d["hosts"].items():
            if "STALE" in h["flags"]:
                restart.append({
                    "host": key, "reason": "stale",
                    "age_s": h["age_s"],
                    "stale_secs": self.stale_secs})
            elif "STRAGGLER" in h["flags"]:
                ratio = (h["step_p50_ms"] / sp["median_ms"]
                         if sp and sp["median_ms"] else None)
                restart.append({
                    "host": key, "reason": "straggler",
                    "step_p50_ms": h["step_p50_ms"],
                    "median_ms": sp["median_ms"] if sp else None,
                    "ratio": round(ratio, 2) if ratio else None,
                    "straggler_factor": self.straggler_factor})
        d["restart"] = restart
        return d

    # -- rendering ---------------------------------------------------------
    def render(self, now: Optional[float] = None) -> str:
        d = self.to_dict(now)
        if not d["hosts"]:
            return "no records yet"
        cols = [("host/pid", 22), ("proc", 4), ("age_s", 6),
                ("round", 5), ("steps", 7), ("p50ms", 8), ("p99ms", 8),
                ("img/s", 8), ("loss", 8), ("nan_rb", 6), ("queue", 6)]
        lines = ["  " + " ".join(n.rjust(w) for n, w in cols)]

        def fmt(v, w, prec=1):
            if v is None:
                return "-".rjust(w)
            if isinstance(v, float):
                return f"{v:.{prec}f}".rjust(w)
            return str(v).rjust(w)

        for key, h in d["hosts"].items():
            flags = (" " + ",".join(h["flags"])) if h["flags"] else ""
            lines.append("  " + " ".join([
                key[-22:].rjust(22), fmt(h["proc"], 4),
                fmt(h["age_s"], 6), fmt(h["round"], 5),
                fmt(h["steps"], 7), fmt(h["step_p50_ms"], 8, 2),
                fmt(h["step_p99_ms"], 8, 2),
                fmt(h["images_per_sec"], 8),
                fmt(h["loss"], 8, 4), fmt(h["nan_rollbacks"], 6),
                fmt(h["queue_depth"], 6, 0)]) + flags)
        sp = d["spread"]
        if sp is not None and len(d["hosts"]) > 1:
            lines.append(
                f"  step p50 spread: {sp['min_ms']:.2f}-"
                f"{sp['max_ms']:.2f} ms (median {sp['median_ms']:.2f},"
                f" max/min {sp['ratio']:.2f}x)")
        for name, n in d["source_errors"].items():
            lines.append(f"  source {name}: {n} poll error(s)")
        return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    follow = "--follow" in argv
    as_json = "--json" in argv
    as_verdict = "--verdict-json" in argv
    interval = 2.0
    stale = STALE_SECS
    factor = STRAGGLER_FACTOR
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--interval":
            interval = float(argv[i + 1])
            i += 2
        elif a == "--stale-secs":
            stale = float(argv[i + 1])
            i += 2
        elif a == "--straggler-factor":
            factor = float(argv[i + 1])
            i += 2
        elif a in ("--follow", "--json", "--verdict-json"):
            i += 1
        elif a.startswith("--"):
            print(f"agg: unknown flag {a}")
            print(__doc__)
            return 2
        else:
            paths.append(a)
            i += 1
    if not paths:
        print(__doc__)
        return 1
    agg = Aggregator([make_source(p) for p in paths],
                     stale_secs=stale, straggler_factor=factor)
    if as_verdict:
        agg.poll()
        v = agg.verdict()
        print(json.dumps(v, indent=2, default=str))
        return 3 if v["restart"] else 0
    try:
        while True:
            agg.poll()
            if as_json:
                print(json.dumps(agg.to_dict(), indent=2, default=str))
            else:
                if follow:
                    # graftlint: disable=GL004 header shows the wall-clock poll time next to record ages
                    now_ts = time.time()
                    stamp = time.strftime("%H:%M:%S",
                                          time.localtime(now_ts))
                    print(f"=== {stamp} "
                          f"({len(agg.hosts)} processes) ===")
                print(agg.render())
            if not follow:
                return 0
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
