#!/usr/bin/env python3
"""bench_pool: max-pool backward micro-bench — reference tie rule vs
XLA's native single-winner rule, on AlexNet's three pooling shapes.

The tie-duplicating unpool backward (ops/pooling.py, the reference's
mshadow semantics) costs ky*kx shifted compares over input-sized
tensors; XLA's native select_and_scatter picks one winner. Whether
that traffic matters on a real chip decides the default guidance for
`pool_grad = winner` (docs/layer.md). Prints one JSON line per shape.

No device->host readbacks (block_until_ready only — docs/perf.md).

Usage: python -m cxxnet_tpu.tools.bench_pool [--steps N]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main(argv) -> int:
    steps = 30
    batch = 256
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    if "--batch" in argv:
        # CPU smoke: bf16 pooling is emulated (pathologically slow) on
        # the host backend; shrink the batch there
        batch = int(argv[argv.index("--batch") + 1])

    # honor an explicit JAX_PLATFORMS before the first device touch (a
    # bare jax init probes every plugin incl. a possibly-dead tunnel)
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()

    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.ops.pooling import pool2d
    from cxxnet_tpu.utils.platform import setup_scoped_cache
    setup_scoped_cache(jax.default_backend())

    # (name, input shape, k, stride) — AlexNet's pools, default b256
    shapes = [("pool1", (batch, 96, 55, 55), 3, 2),
              ("pool2", (batch, 256, 27, 27), 3, 2),
              ("pool3", (batch, 256, 13, 13), 3, 2)]
    rng = np.random.RandomState(0)
    for name, shp, k, st in shapes:
        x = jnp.asarray(rng.randn(*shp), jnp.bfloat16)
        row = {"shape": name}
        for gm in ("ties", "winner"):
            f = jax.jit(jax.grad(
                lambda x, gm=gm: pool2d(
                    x, "max", k, k, st, grad_mode=gm)
                .astype(jnp.float32).sum()))
            g = f(x)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(steps):
                g = f(x)
            jax.block_until_ready(g)
            row[gm + "_ms"] = round(
                (time.perf_counter() - t0) / steps * 1e3, 3)
        row["winner_speedup"] = round(
            row["ties_ms"] / max(row["winner_ms"], 1e-9), 3)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
