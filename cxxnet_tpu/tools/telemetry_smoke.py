"""Telemetry end-to-end smoke: train 2 rounds on synthetic digits with
log_file/metrics_file armed, inject one transient io fault, then
validate the streams and render the metrics report.

    python -m cxxnet_tpu.tools.telemetry_smoke [--out DIR] [--keep]

Exit 0 iff: both streams are valid JSONL; the event stream contains
step AND data span timings, a checkpoint save with a duration, and a
fault retry event; the metrics stream yields per-round rows with a
nonzero fault.retry counter; and metrics_report renders them. This is
the acceptance proof for docs/OBSERVABILITY.md and runs in CI, which
uploads the produced JSONL as workflow artifacts.
"""

from __future__ import annotations

import gzip
import os
import struct
import sys
import tempfile

import numpy as np


def write_synth_mnist(dirname: str, n: int, seed: int,
                      prefix: str) -> None:
    """Separable 3-class idx-format set: class = f(mean intensity)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 3, size=n).astype(np.uint8)
    images = np.zeros((n, 6, 6), dtype=np.uint8)
    for i, y in enumerate(labels):
        base = 40 + 80 * int(y)
        images[i] = np.clip(rng.randn(6, 6) * 10 + base, 0, 255)
    with gzip.open(os.path.join(dirname, f"{prefix}-img.gz"), "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, 6, 6))
        f.write(images.tobytes())
    with gzip.open(os.path.join(dirname, f"{prefix}-lbl.gz"), "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())


CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 2
max_round = 2
eta = 0.3
metric = error
eval_train = 1
silent = 1
model_dir = {d}/models
log_file = {d}/events.jsonl
metrics_file = {d}/metrics.jsonl
"""


def run_smoke(out_dir: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.telemetry.sink import read_jsonl
    from cxxnet_tpu.tools import metrics_report
    from cxxnet_tpu.utils import fault

    write_synth_mnist(out_dir, 256, 0, "train")
    write_synth_mnist(out_dir, 64, 1, "test")
    conf = os.path.join(out_dir, "smoke.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(d=out_dir))

    # one transient io error on the third next(): exercises the retry
    # path so the streams carry a real fault counter/event
    fault.clear()
    fault.inject("io.next", "ioerror", at=3)
    try:
        rc = LearnTask().run([conf, "io_retry_backoff=0.0"])
    finally:
        fault.clear()
    if rc != 0:
        print(f"telemetry_smoke: training failed rc={rc}")
        return 1

    events = list(read_jsonl(os.path.join(out_dir, "events.jsonl")))
    metrics = list(read_jsonl(os.path.join(out_dir, "metrics.jsonl")))
    span_names = {e.get("name") for e in events if e.get("kind") == "span"}
    checks = [
        ("train.step span events", "train.step" in span_names),
        ("train.data span events", "train.data" in span_names),
        ("checkpoint save event with duration",
         any(e.get("kind") == "checkpoint" and e.get("op") == "save"
             and e.get("secs", 0) > 0 for e in events)),
        ("fault retry event",
         any(e.get("kind") == "fault" and e.get("type") == "retry"
             for e in events)),
        ("eval events with parsed values",
         any(e.get("kind") == "eval" and e.get("values")
             for e in events)),
        ("per-round metrics records",
         sum(1 for m in metrics if m.get("kind") == "round") >= 2),
        ("nonzero fault.retry counter in final snapshot",
         any(m.get("kind") == "final"
             and (m.get("metrics") or {}).get("fault.retry", 0) >= 1
             for m in metrics)),
        ("host/pid tags on every record",
         all("host" in r and "pid" in r for r in events + metrics)),
    ]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and passed

    agg = metrics_report.aggregate(os.path.join(out_dir, "metrics.jsonl"))
    report = metrics_report.render(agg)
    print(report)
    if not agg["rounds"]:
        print("telemetry_smoke: metrics_report found no rounds")
        ok = False
    print(f"telemetry_smoke: {'PASS' if ok else 'FAIL'} "
          f"({len(events)} events, {len(metrics)} metric records)")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: telemetry_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="telemetry_smoke_")
        rc = run_smoke(d)
        print(f"telemetry_smoke: streams kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
