"""Graph-pass smoke: folding/pruning must change no product answer.

    python -m cxxnet_tpu.tools.pass_smoke [--out DIR] [--keep]

Trains a tiny fullc+batch_norm MLP once through the real CLI, then
proves the infer-stage graph passes (docs/GRAPH_PASSES.md) at the
product surface:

- **fold parity**: `task = pred` with `graph_passes =
  fold_conv_bn,dead_layer_elim` vs passes off, at `batch_size = 96`
  so the whole pred set is ONE batch - the fold's calibration batch
  IS the inference batch, making the fold a pure contraction-order
  rewrite: identical argmax on every row (line-identical prediction
  files) and tight-allclose `task = pred_raw` logits;
- **fold engagement**: the fold leg's event stream carries the
  `graph_passes calibrate` event, and an in-process trace shows the
  folded infer jaxpr contains ZERO rsqrt (the BN moment pipeline is
  gone) while the unfolded one contains it - the parity checks
  cannot pass vacuously with the passes silently off;
- **dead-layer elimination**: `task = extract` of the EARLY node
  fc1 produces byte-identical features with passes on vs off, and
  the pruned extract executable traces a strictly smaller program
  (fewer jaxpr equations, fewer matmuls). Finding recorded here:
  jax's jit already dead-code-eliminates the LOWERED module (the
  compiled HLO of an early-node infer matches with or without the
  dead tail), so the pass's artifact-level win is the traced
  program + trace/lowering latency; the smoke asserts the traced
  sizes and reports the lowered bytes.

PR-11 legs (docs/GRAPH_PASSES.md "Pass catalog"):

- **activation-fusion parity**: a second trained MLP whose head is
  fullc -> bias -> relu, `task = pred` with
  `graph_passes = dead_layer_elim,fuse_activation` vs passes off -
  identical argmax on every row + tight-allclose raw logits (the
  bias absorption is a pure add-reassociation);
- **1x1-merge parity**: an in-process child (same pinned runtime)
  trains a conv -> 1x1-conv -> relu net and compares fused
  (`merge_conv_1x1,fuse_activation`) vs unfolded predict_dist rows,
  plus the one-conv-fewer traced-program claim;
- **per-layer-plan autotune**: tools/autotune.py on a tiny budget
  writes a schema-v2 cache (the plan JSON stays in --out as a CI
  artifact), then the SAME pred task replays it twice via
  `tuning_cache =` - identical output files (plans are
  deterministic pickups, not per-run noise).

PR-12 leg (docs/GRAPH_PASSES.md "Quantization"):

- **int8 quant leg**: the SAME trained fullc+bn MLP, `task = pred`
  with `graph_passes = fold_conv_bn,dead_layer_elim,quantize_int8`
  vs passes off - argmax agreement >= 95/96 rows (int8 is an
  approximation, so the pinned threshold prices its accuracy cost
  instead of demanding identity), a calibrate event carrying
  `quant_sites` on the quant leg's stream, and an in-process
  int8-engagement proof at the traced-jaxpr level (the
  GRAPH_PASSES.md key finding - wins are measured on the traced
  program): every data-path matmul of the quantized infer trace is
  int8 x int8 -> int32 with ZERO f32 data-path dots, while the float
  trace keeps f32 dots (vacuity guard). The verdict is written to
  `quant_report.json`, uploaded with the CI artifacts.

All inference legs run under `--xla_cpu_use_thunk_runtime=false`
(the fused/zero/serve smokes' scoped pin): folded and unfolded are
different program shapes, and the thunk runtime's per-shape codegen
drifts ~1 ULP - backend noise the argmax labels must not inherit.
Exit 0 iff all checks pass.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

import numpy as np

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
pred = {d}/out.txt
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:bn1] = batch_norm:bn1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 2
max_round = 2
eta = 0.3
metric = error
silent = 1
"""

_PASSES = "graph_passes=fold_conv_bn,dead_layer_elim"

# int8 quant leg: the fold pipeline + quantize_int8 on top
_QUANT_PASSES = "graph_passes=fold_conv_bn,dead_layer_elim," \
                "quantize_int8"
# pinned argmax-agreement floor: 95 of the 96 pred rows. int8 is an
# approximation - the threshold prices its accuracy cost instead of
# demanding identity (docs/GRAPH_PASSES.md "Quantization")
_QUANT_AGREE_MIN = 95

# activation-fusion leg: same data blocks, fullc -> bias -> relu head
CONF_ACT = CONF.replace(
    "layer[+1:bn1] = batch_norm:bn1\nlayer[+1:sg1] = tanh",
    "layer[+0] = bias:bs1\n  init_bias = 0.05\nlayer[+1:sg1] = relu")

_ACT_PASSES = "graph_passes=dead_layer_elim,fuse_activation"

# 1x1-merge leg (in-process child): conv -> 1x1 conv -> relu head
_MERGE_CONF = """
netconfig=start
layer[+1:c1] = conv:c1
  nchannel = 4
  kernel_size = 3
  pad = 1
layer[+1:c2] = conv:c2
  nchannel = 6
  kernel_size = 1
layer[+1:r1] = relu
layer[+1:fl] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 16
dev = cpu
eta = 0.1
silent = 1
seed = 5
"""


def _pinned_env() -> dict:
    return dict(
        os.environ, JAX_PLATFORMS="cpu",
        # append, don't replace: inherited flags must keep applying
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())


def _run_cli(out_dir: str, *overrides: str,
             conf: str = "pass_smoke.conf"
             ) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, conf), *overrides],
        env=_pinned_env(), capture_output=True, text=True, timeout=540)


def _run_merge_leg() -> dict:
    """Spawn the --merge-leg child under the pinned runtime and parse
    its JSON verdict."""
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.tools.pass_smoke",
         "--merge-leg"],
        env=_pinned_env(), capture_output=True, text=True, timeout=540)
    for line in r.stdout.splitlines():
        if line.startswith("MERGELEG="):
            return json.loads(line[len("MERGELEG="):])
    return {"error": f"rc={r.returncode}: {r.stderr[-300:]}"}


def _lines(path):
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return f.read().splitlines()


def _floats(lines):
    return np.asarray([[float(t) for t in ln.split()]
                       for ln in lines], np.float64)


def _program_sizes() -> dict:
    """In-process introspection: traced-jaxpr sizes of the extract
    and final-node infer executables with passes on vs off (fresh
    weights - program SIZE is weight-independent)."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    net_conf = CONF.split("netconfig=start")[1].split("netconfig=end")[0]
    base = ("netconfig=start" + net_conf + "netconfig=end\n"
            "input_shape = 1,1,36\nbatch_size = 32\ndev = cpu\n"
            "eta = 0.3\nsilent = 1\nseed = 3\n")

    def build(extra=""):
        tr = NetTrainer()
        for k, v in parse_config_string(base + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    def sizes(tr, node):
        data = np.zeros((32, 1, 1, 36), np.float32)
        gdata, gextras = tr.stage_infer_rows(data)
        fn = tr._infer_fn(node)
        traced = fn.trace(tr.state["params"], gdata, gextras)
        eqns = traced.jaxpr.jaxpr.eqns
        return {
            "eqns": len(eqns),
            "dots": sum(1 for e in eqns
                        if e.primitive.name == "dot_general"),
            "rsqrt": str(traced.jaxpr).count("rsqrt"),
            "lowered_bytes": len(fn.lower(
                tr.state["params"], gdata, gextras).as_text()),
        }

    off, on = build(), build(_PASSES.replace("=", " = ", 1))
    early = off.net.node_index("fc1")
    final = off.net_cfg.num_nodes - 1
    # fold the final-node executable: calibrate on a fixed batch
    from cxxnet_tpu.io.data import DataBatch
    rng = np.random.RandomState(5)
    on.calibrate_graph_passes(DataBatch(
        data=rng.rand(32, 1, 1, 36).astype(np.float32),
        label=rng.randint(0, 3, (32, 1)).astype(np.float32)))
    return {
        "extract_off": sizes(off, early),
        "extract_on": sizes(on, early),
        "final_off": sizes(off, final),
        "final_on": sizes(on, final),
    }


def _quant_engagement() -> dict:
    """In-process int8-engagement proof at the traced-jaxpr level
    (the GRAPH_PASSES.md key finding - wins are measured on the
    traced program, and the parity check alone could pass vacuously
    with quantize_int8 silently off): data-path dot dtypes of the
    quantized vs float infer executables, classified by the audit's
    own `_data_path_dots` (one definition)."""
    from cxxnet_tpu.analysis.jaxpr_audit import _data_path_dots
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    net_conf = CONF.split("netconfig=start")[1].split("netconfig=end")[0]
    base = ("netconfig=start" + net_conf + "netconfig=end\n"
            "input_shape = 1,1,36\nbatch_size = 32\ndev = cpu\n"
            "eta = 0.3\nsilent = 1\nseed = 3\n")

    def build(extra=""):
        tr = NetTrainer()
        for k, v in parse_config_string(base + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    off = build()
    on = build(_QUANT_PASSES.replace("=", " = ", 1))
    rng = np.random.RandomState(9)
    on.calibrate_graph_passes(DataBatch(
        data=rng.rand(32, 1, 1, 36).astype(np.float32),
        label=rng.randint(0, 3, (32, 1)).astype(np.float32)))
    node = off.net_cfg.num_nodes - 1

    def dots(tr):
        g, ge = tr.stage_infer_rows(np.zeros((32, 1, 1, 36),
                                             np.float32))
        return _data_path_dots(tr._infer_fn(node),
                               (tr.state["params"], g, ge), 32)

    i8_on, fp_on = dots(on)
    i8_off, fp_off = dots(off)
    return {"int8_dots_quant": i8_on, "float_dots_quant": fp_on,
            "int8_dots_float": i8_off, "float_dots_float": fp_off}


def merge_leg() -> dict:
    """--merge-leg child (runs under the parent's pinned runtime):
    train the conv -> 1x1-conv net a few steps, compare predict_dist
    fused (merge_conv_1x1 + fuse_activation) vs passes off, and
    count the traced data-path convs."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    def build(extra=""):
        tr = NetTrainer()
        for k, v in parse_config_string(_MERGE_CONF + extra):
            tr.set_param(k, v)
        tr.init_model()
        return tr

    def batch(i):
        r = np.random.RandomState(300 + i)
        return DataBatch(
            data=r.rand(16, 3, 8, 8).astype(np.float32),
            label=r.randint(0, 3, (16, 1)).astype(np.float32))

    off = build()
    on = build("graph_passes = dead_layer_elim,merge_conv_1x1,"
               "fuse_activation\n")
    for i in range(3):
        off.update(batch(i))
        on.update(batch(i))
    b = batch(90)
    po, pn = off.predict_dist(b), on.predict_dist(b)

    def convs(tr):
        node = tr.net_cfg.num_nodes - 1
        g, ge = tr.stage_infer_rows(np.zeros((16, 3, 8, 8),
                                             np.float32))
        eqns = tr._infer_fn(node).trace(
            tr.state["params"], g, ge).jaxpr.jaxpr.eqns
        return sum(1 for e in eqns
                   if e.primitive.name == "conv_general_dilated")

    return {
        "max_diff": float(np.abs(po - pn).max()),
        "allclose": bool(np.allclose(po, pn, rtol=5e-4, atol=1e-6)),
        "argmax_equal": bool((po.argmax(1) == pn.argmax(1)).all()),
        "convs_off": convs(off),
        "convs_on": convs(on),
    }


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu.telemetry.sink import read_jsonl
    write_synth_mnist(out_dir, 192, 0, "train")
    # 96 test instances + batch_size=96 on the inference legs = the
    # whole pred set is ONE batch (the fold calibration batch)
    write_synth_mnist(out_dir, 96, 1, "test")
    with open(os.path.join(out_dir, "pass_smoke.conf"), "w") as f:
        f.write(CONF.format(d=out_dir))
    mdir = os.path.join(out_dir, "models")
    model = os.path.join(mdir, "0002.model")
    p_off = os.path.join(out_dir, "pred_off.txt")
    p_on = os.path.join(out_dir, "pred_fold.txt")
    r_off = os.path.join(out_dir, "raw_off.txt")
    r_on = os.path.join(out_dir, "raw_fold.txt")
    x_off = os.path.join(out_dir, "extract_off.txt")
    x_on = os.path.join(out_dir, "extract_on.txt")
    log = os.path.join(out_dir, "pass_events.jsonl")

    train = _run_cli(out_dir, f"model_dir={mdir}")
    common = (f"model_in={model}", "batch_size=96")
    legs = {
        "pred_off": _run_cli(out_dir, "task=pred", *common,
                             f"pred={p_off}"),
        "pred_on": _run_cli(out_dir, "task=pred", *common,
                            f"pred={p_on}", _PASSES,
                            f"log_file={log}"),
        "raw_off": _run_cli(out_dir, "task=pred_raw", *common,
                            f"pred={r_off}"),
        "raw_on": _run_cli(out_dir, "task=pred_raw", *common,
                           f"pred={r_on}", _PASSES),
        "x_off": _run_cli(out_dir, "task=extract", *common,
                          "extract_node_name=fc1", f"pred={x_off}"),
        "x_on": _run_cli(out_dir, "task=extract", *common,
                         "extract_node_name=fc1", f"pred={x_on}",
                         _PASSES),
    }
    po, pn = _lines(p_off), _lines(p_on)
    ro, rn = _lines(r_off), _lines(r_on)
    xo, xn = _lines(x_off), _lines(x_on)
    raw_diff = float("nan")
    raw_close = False
    if ro and rn and len(ro) == len(rn):
        a, b = _floats(ro), _floats(rn)
        raw_diff = float(np.abs(a - b).max())
        # ~ULP contraction change through a %g-printed file: the
        # SERVING.md "Numerics fine print" tolerance class
        raw_close = bool(np.allclose(a, b, rtol=5e-4, atol=1e-6))
    events = ([e for e in read_jsonl(log)
               if e.get("kind") == "graph_passes"]
              if os.path.exists(log) else [])
    calibrated = any(e.get("op") == "calibrate" for e in events)
    sizes = _program_sizes()
    ex_off, ex_on = sizes["extract_off"], sizes["extract_on"]
    fin_off, fin_on = sizes["final_off"], sizes["final_on"]

    # --- activation-fusion parity leg (CLI, second trained MLP) ----
    with open(os.path.join(out_dir, "pass_smoke_act.conf"), "w") as f:
        f.write(CONF_ACT.format(d=out_dir))
    mdir_a = os.path.join(out_dir, "models_act")
    model_a = os.path.join(mdir_a, "0002.model")
    a_off, a_on = (os.path.join(out_dir, n)
                   for n in ("act_off.txt", "act_on.txt"))
    ar_off, ar_on = (os.path.join(out_dir, n)
                     for n in ("act_raw_off.txt", "act_raw_on.txt"))
    train_a = _run_cli(out_dir, f"model_dir={mdir_a}",
                       conf="pass_smoke_act.conf")
    common_a = (f"model_in={model_a}", "batch_size=96")
    act_legs = {
        "a_off": _run_cli(out_dir, "task=pred", *common_a,
                          f"pred={a_off}",
                          conf="pass_smoke_act.conf"),
        "a_on": _run_cli(out_dir, "task=pred", *common_a,
                         f"pred={a_on}", _ACT_PASSES,
                         conf="pass_smoke_act.conf"),
        "ar_off": _run_cli(out_dir, "task=pred_raw", *common_a,
                           f"pred={ar_off}",
                           conf="pass_smoke_act.conf"),
        "ar_on": _run_cli(out_dir, "task=pred_raw", *common_a,
                          f"pred={ar_on}", _ACT_PASSES,
                          conf="pass_smoke_act.conf"),
    }
    ao, an = _lines(a_off), _lines(a_on)
    aro, arn = _lines(ar_off), _lines(ar_on)
    act_diff, act_close = float("nan"), False
    if aro and arn and len(aro) == len(arn):
        fa, fb = _floats(aro), _floats(arn)
        act_diff = float(np.abs(fa - fb).max())
        act_close = bool(np.allclose(fa, fb, rtol=5e-4, atol=1e-6))

    # --- 1x1-merge parity leg (pinned in-process child) ------------
    merge = _run_merge_leg()

    # --- int8 quant leg: quantized pred vs float, same trained MLP -
    q_pred = os.path.join(out_dir, "pred_quant.txt")
    q_log = os.path.join(out_dir, "quant_events.jsonl")
    quant_leg = _run_cli(out_dir, "task=pred", *common,
                         f"pred={q_pred}", _QUANT_PASSES,
                         f"log_file={q_log}")
    qn = _lines(q_pred)
    q_agree = (sum(a == b for a, b in zip(po, qn))
               if po and qn and len(po) == len(qn) else 0)
    q_events = ([e for e in read_jsonl(q_log)
                 if e.get("kind") == "graph_passes"]
                if os.path.exists(q_log) else [])
    q_calibrated = any(e.get("op") == "calibrate"
                       and e.get("quant_sites") for e in q_events)
    quant = _quant_engagement()

    # --- per-layer-plan autotune leg: tiny grid, cache written then
    # replayed - the plan JSON stays in out_dir as the CI artifact
    plan_json = os.path.join(out_dir, "tuning_plan.json")
    at = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.tools.autotune",
         "--out", plan_json, "--budget-secs", "5", "--serve", "1",
         "--per-layer", "1"],
        env=_pinned_env(), capture_output=True, text=True,
        timeout=540)
    plan_blob = {}
    if os.path.exists(plan_json):
        with open(plan_json) as f:
            plan_blob = json.load(f)
    t1, t2 = (os.path.join(out_dir, n)
              for n in ("tuned_pred_1.txt", "tuned_pred_2.txt"))
    tuned_legs = [
        _run_cli(out_dir, "task=pred", *common, f"pred={t1}",
                 f"tuning_cache={plan_json}"),
        _run_cli(out_dir, "task=pred", *common, f"pred={t2}",
                 f"tuning_cache={plan_json}"),
    ]
    to1, to2 = _lines(t1), _lines(t2)

    checks = [
        ("train run completed",
         train.returncode == 0 and os.path.exists(model)),
        ("all inference legs completed",
         all(r.returncode == 0 for r in legs.values())),
        ("fold parity: identical argmax predictions (96 lines)",
         po is not None and po == pn and len(po) == 96),
        ("fold parity: tight-allclose pred_raw logits "
         f"(max diff {raw_diff:.2e})", raw_close),
        ("fold engaged: calibrate event on the fold leg's stream",
         calibrated),
        ("fold engaged: folded infer jaxpr has no rsqrt "
         f"({fin_on['rsqrt']} vs unfolded {fin_off['rsqrt']})",
         fin_on["rsqrt"] == 0 and fin_off["rsqrt"] > 0),
        ("fold: strictly smaller traced program "
         f"({fin_on['eqns']} vs {fin_off['eqns']} eqns)",
         fin_on["eqns"] < fin_off["eqns"]),
        ("dle: byte-identical extract of early node fc1",
         xo is not None and xo == xn and len(xo) == 96),
        ("dle: extract traces a strictly smaller program "
         f"({ex_on['eqns']} vs {ex_off['eqns']} eqns, "
         f"{ex_on['dots']} vs {ex_off['dots']} matmuls)",
         ex_on["eqns"] < ex_off["eqns"]
         and ex_on["dots"] < ex_off["dots"]),
        ("dle: lowered module no larger "
         f"({ex_on['lowered_bytes']} vs {ex_off['lowered_bytes']} B;"
         " equal = jax's own DCE, the documented finding)",
         ex_on["lowered_bytes"] <= ex_off["lowered_bytes"]),
        ("act-fusion legs completed",
         train_a.returncode == 0
         and all(r.returncode == 0 for r in act_legs.values())),
        ("act-fusion parity: identical argmax predictions (96 lines)",
         ao is not None and ao == an and len(ao) == 96),
        ("act-fusion parity: tight-allclose pred_raw logits "
         f"(max diff {act_diff:.2e})", act_close),
        ("1x1-merge parity: allclose rows + identical argmax "
         f"(max diff {merge.get('max_diff', float('nan')):.2e})",
         merge.get("allclose", False)
         and merge.get("argmax_equal", False)),
        ("1x1-merge: exactly one conv fewer in the traced program "
         f"({merge.get('convs_on')} vs {merge.get('convs_off')})",
         merge.get("convs_off", 0) >= 2
         and merge.get("convs_on") == merge.get("convs_off", 0) - 1),
        ("int8 leg completed", quant_leg.returncode == 0),
        (f"int8 argmax agreement >= {_QUANT_AGREE_MIN}/96 "
         f"(got {q_agree}/96)",
         qn is not None and len(qn) == 96
         and q_agree >= _QUANT_AGREE_MIN),
        ("int8 leg: calibrate event carries quant_sites",
         q_calibrated),
        ("int8 engaged: quantized trace is all-int8/int32 data-path "
         f"dots ({quant.get('int8_dots_quant')} int8, "
         f"{quant.get('float_dots_quant')} float)",
         quant.get("int8_dots_quant", 0) > 0
         and quant.get("float_dots_quant", 1) == 0),
        ("int8 vacuity guard: float trace keeps float data-path dots "
         f"({quant.get('float_dots_float')} float, "
         f"{quant.get('int8_dots_float')} int8)",
         quant.get("float_dots_float", 0) > 0
         and quant.get("int8_dots_float", 1) == 0),
        ("autotune leg: schema-v2 cache with a per-layer plan field",
         at.returncode == 0 and plan_blob.get("version") == 2
         and "layers" in plan_blob.get("platforms", {}).get("cpu", {})),
        ("autotune leg: cache replay is deterministic "
         "(two identical tuned pred files, 96 lines)",
         all(r.returncode == 0 for r in tuned_legs)
         and to1 is not None and to1 == to2 and len(to1) == 96),
    ]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not ok:
        for tag, r in ([("train", train), ("train_act", train_a),
                        ("autotune", at), ("quant", quant_leg)]
                       + list(legs.items()) + list(act_legs.items())):
            if r.returncode != 0:
                print(f"--- {tag} stderr tail ---")
                print(r.stderr[-2000:])
        if "error" in merge:
            print(f"--- merge leg ---\n{merge['error']}")
    with open(os.path.join(out_dir, "pass_sizes.json"), "w") as f:
        json.dump(sizes, f, indent=1, sort_keys=True)
    # the quant-leg verdict rides the pass-smoke artifact upload
    with open(os.path.join(out_dir, "quant_report.json"), "w") as f:
        json.dump({"argmax_agree": q_agree, "rows": 96,
                   "agree_min": _QUANT_AGREE_MIN,
                   "calibrate_event": q_calibrated, **quant},
                  f, indent=1, sort_keys=True)
    print(f"pass_smoke: {'PASS' if ok else 'FAIL'} "
          f"(raw max diff {raw_diff:.2e}; extract traced "
          f"{ex_off['eqns']}->{ex_on['eqns']} eqns)")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--merge-leg" in args:
        print("MERGELEG=" + json.dumps(merge_leg()))
        return 0
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: pass_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="pass_smoke_")
        rc = run_smoke(d)
        print(f"pass_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
