#!/usr/bin/env python3
"""imgbin_partition: shard a big .lst into N .lst/.bin partitions.

Tool parity with tools/imgbin-partition-maker.py (which emits a Makefile
whose targets im2bin each shard so `make -j` packs them in parallel).
Partitioned bins feed the imgbinx iterator's multi-bin mode
(`image_conf_prefix`/`image_conf_ids`) and per-worker sharding in
distributed runs (iter_thread_imbin-inl.hpp:189-220).

Usage:
  imgbin_partition.py <image.lst> <image_root> <out_prefix> <nparts>
      [--mode=contiguous|roundrobin] [--pack | --makefile]

Writes <out_prefix>.<i>.lst for i in [0, nparts); with --pack also packs
each shard into <out_prefix>.<i>.bin in-process, with --makefile emits
<out_prefix>.mk whose targets call im2bin per shard (the reference's
parallel-make workflow).
"""

from __future__ import annotations

import sys
from typing import List, Tuple

from cxxnet_tpu.io.iter_img import parse_list_file


def partition_list(entries: List[Tuple[int, List[float], str]],
                   nparts: int, mode: str = "contiguous",
                   ) -> List[List[Tuple[int, List[float], str]]]:
    if nparts <= 0:
        raise ValueError("nparts must be positive")
    if mode == "contiguous":
        # same arithmetic as the distributed reader shard split:
        # part i gets [i*ceil(n/k), min((i+1)*ceil(n/k), n))
        step = (len(entries) + nparts - 1) // nparts
        return [entries[i * step: (i + 1) * step] for i in range(nparts)]
    if mode == "roundrobin":
        return [entries[i::nparts] for i in range(nparts)]
    raise ValueError(f"unknown partition mode {mode}")


def _write_lst(path: str,
               entries: List[Tuple[int, List[float], str]]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for idx, labels, fname in entries:
            lab = "\t".join(repr(v) for v in labels)  # exact round-trip
            f.write(f"{idx}\t{lab}\t{fname}\n")


def make_partitions(list_path: str, image_root: str, out_prefix: str,
                    nparts: int, mode: str = "contiguous",
                    pack: bool = False, makefile: bool = False,
                    ) -> List[str]:
    entries = parse_list_file(list_path)
    parts = partition_list(entries, nparts, mode)
    lst_paths = []
    for i, part in enumerate(parts):
        lst = f"{out_prefix}.{i}.lst"
        _write_lst(lst, part)
        lst_paths.append(lst)
    if pack:
        from cxxnet_tpu.tools.im2bin import im2bin
        for i, lst in enumerate(lst_paths):
            im2bin(lst, image_root, f"{out_prefix}.{i}.bin")
    if makefile:
        mk = f"{out_prefix}.mk"
        with open(mk, "w", encoding="utf-8") as f:
            bins = " ".join(f"{out_prefix}.{i}.bin"
                            for i in range(nparts))
            f.write(f"all: {bins}\n\n")
            for i in range(nparts):
                f.write(f"{out_prefix}.{i}.bin: {out_prefix}.{i}.lst\n")
                f.write(f"\tpython -m cxxnet_tpu.tools.im2bin "
                        f"{out_prefix}.{i}.lst {image_root} $@\n\n")
            f.write(".PHONY: all\n")
    return lst_paths


def cli_main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    opts = [a for a in sys.argv[1:] if a.startswith("--")]
    if len(args) != 4:
        print(__doc__)
        sys.exit(1)
    mode = "contiguous"
    pack = makefile = False
    for o in opts:
        if o.startswith("--mode="):
            mode = o.split("=", 1)[1]
        elif o == "--pack":
            pack = True
        elif o == "--makefile":
            makefile = True
        else:
            print(f"unknown option {o}")
            sys.exit(1)
    make_partitions(args[0], args[1], args[2], int(args[3]), mode,
                    pack, makefile)


if __name__ == "__main__":
    cli_main()
