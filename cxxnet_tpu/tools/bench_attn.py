#!/usr/bin/env python3
"""bench_attn: flash-attention kernel block-size sweep on the chip.

The Pallas kernel's (BLOCK_Q, BLOCK_K) default is (128, 128) — exact
MXU-shaped score tiles, but a (b, h, s/bq, s/bk) grid of tiny programs
whose per-program overhead caps throughput (round-4 on-silicon: 13.4
TFLOP/s non-causal = 0.91x the XLA blockwise path; causal 1.21x).
Larger tiles amortize the grid at more VMEM per program. This sweeps
the candidates and prints one JSON line per config so the winner can
be promoted to the module defaults with data.

Usage:  python -m cxxnet_tpu.tools.bench_attn [--quick]
          [--shape b,h,s,d] [--steps N]

Each config is measured fwd+all-grads (the training cost), bf16.
A config that fails to lower prints an error row instead of aborting
the sweep. Sync is a SCALAR READBACK, not block_until_ready: on some
tunnel boots block_until_ready is a silent no-op (docs/perf.md) and
every blocked timing measures dispatch; the one-element readback is
correct in every observed window. Its sticky H2D poisoning cannot
touch the sweep because the ONLY H2D in this process is the single
q/k/v staging in main(), shared by every config and performed before
the first measurement (and hence before the first readback); later
configs re-jit but never re-stage.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _rsync(tree):
    """Readback-sync via the harness's shared primitive
    (bench._readback_sync): block_until_ready is not trustworthy on
    the tunnel, and a readback is correct in every observed window -
    and no H2D (timed or untimed) happens after the first one, so its
    sticky poisoning has nothing to slow (see module docstring)."""
    try:
        import bench
    except ImportError as e:
        raise RuntimeError(
            "bench_attn reuses the repo-root bench.py sync primitive; "
            "run it from a source checkout root (bench.py is not "
            "packaged)") from e
    return bench._readback_sync(tree)


def measure(core, q, k, v, flops, steps):
    import jax
    f = jax.jit(jax.grad(
        lambda q, k, v: core(q, k, v).astype("float32").sum(),
        argnums=(0, 1, 2)))
    t0 = time.perf_counter()
    g = f(q, k, v)
    _rsync(g)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        g = f(q, k, v)
    _rsync(g)
    return steps * flops / (time.perf_counter() - t0) / 1e12, compile_s


def main(argv) -> int:
    shape = (4, 8, 4096, 128)
    steps = 10
    if "--shape" in argv:
        shape = tuple(
            int(t) for t in argv[argv.index("--shape") + 1].split(","))
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    configs = [(128, 128), (256, 256), (512, 512), (256, 1024),
               (512, 1024), (1024, 1024)]
    if "--quick" in argv:
        configs = [(128, 128), (512, 512)]

    # honor an explicit JAX_PLATFORMS before the first device touch (a
    # bare jax init probes every plugin incl. a possibly-dead tunnel)
    from cxxnet_tpu.utils.platform import ensure_env_platform
    ensure_env_platform()

    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.ops import pallas_attention as PA
    from cxxnet_tpu.ops.attention import blockwise_attention
    from cxxnet_tpu.utils.platform import setup_scoped_cache
    setup_scoped_cache(jax.default_backend())

    b, h, s, d = shape
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
               for _ in range(3))
    flops = 14.0 * b * h * s * s * d
    # causal rows count REALIZED flops (~half: future tiles skipped)
    # and compare against a causal XLA baseline - full-count causal
    # numbers would overstate throughput ~2x and make vs_xla
    # apples-to-oranges
    flops_c = flops / 2.0

    baselines = {}
    for causal in (False, True):
        tf, _ = measure(
            lambda q, k, v, c=causal: blockwise_attention(
                q, k, v, kv_block=512, causal=c),
            q, k, v, flops_c if causal else flops, steps)
        baselines[causal] = tf
        print(json.dumps({
            "config": "xla_blockwise" + ("_causal" if causal else ""),
            "tflops": round(tf, 2)}), flush=True)

    saved = PA.BLOCK_Q, PA.BLOCK_K
    try:
        for bq, bk in configs:
            PA.BLOCK_Q, PA.BLOCK_K = bq, bk
            for causal in (False, True):
                try:
                    tf, comp = measure(
                        lambda q, k, v: PA.flash_attention(
                            q, k, v, causal, None, False),
                        q, k, v, flops_c if causal else flops, steps)
                    print(json.dumps({
                        "config": f"bq{bq}_bk{bk}" +
                                  ("_causal" if causal else ""),
                        "tflops": round(tf, 2),
                        "vs_xla": round(tf / baselines[causal], 3),
                        "compile_s": round(comp, 1)}), flush=True)
                except Exception as e:  # noqa: BLE001 - sweep survives
                    print(json.dumps({
                        "config": f"bq{bq}_bk{bk}" +
                                  ("_causal" if causal else ""),
                        "error": f"{type(e).__name__}: {e}"[:200]}),
                        flush=True)
    finally:
        PA.BLOCK_Q, PA.BLOCK_K = saved
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
