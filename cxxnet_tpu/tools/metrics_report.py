"""Summarize telemetry metrics JSONL stream(s) into per-round tables.

    python -m cxxnet_tpu.tools.metrics_report metrics.jsonl
    python -m cxxnet_tpu.tools.metrics_report host0.jsonl host1.jsonl
    python -m cxxnet_tpu.tools.metrics_report metrics.jsonl --json

Input is the ``metrics_file=`` stream a training run emits
(docs/OBSERVABILITY.md): per-round ``round`` records carrying step/data
timing stats plus a full registry snapshot, and a terminal ``final``
snapshot. Several files - a pod run's per-host streams - merge on
their ``ts`` + process tags (every record carries host/pid/proc), so
no manual ``cat | sort`` is needed and the per-process counter deltas
stay correct across the interleave. Output is a per-round
throughput/latency table (with a proc column once more than one
process appears), per-round deltas of the interesting counters
(checkpoint saves, retries, NaN rollbacks), and a final-counter
summary per process. ``--json`` renders the same aggregation as one
JSON object for scripting.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Sequence, Union

from cxxnet_tpu.telemetry.sink import read_jsonl

# counters reported as per-round deltas in the table footer columns
DELTA_COUNTERS = [
    ("checkpoint.saves", "saves"),
    ("fault.retry", "retries"),
    ("fault.nan_rollback", "nan_rb"),
    ("io.prefetch.stalls", "stalls"),
]


def _counter(metrics: Dict, name: str) -> int:
    v = metrics.get(name, 0)
    return int(v) if isinstance(v, (int, float)) else 0


def _hist_stat(metrics: Dict, name: str, stat: str) -> Optional[float]:
    h = metrics.get(name)
    if isinstance(h, dict):
        v = h.get(stat)
        return float(v) if v is not None else None
    return None


def _read_merged(paths: Sequence[str]) -> List[Dict]:
    """All records of all streams, merged on ts (stable: same-ts
    records keep their file order). Per-host pod streams each carry a
    monotone-nondecreasing ts, so a plain sort IS the timeline merge;
    the proc tags on each record keep per-process accounting apart
    downstream."""
    recs: List[Dict] = []
    for p in paths:
        recs.extend(read_jsonl(p))
    recs.sort(key=lambda r: (r.get("ts")
                             if isinstance(r.get("ts"), (int, float))
                             else 0.0))
    return recs


def aggregate(paths: Union[str, Sequence[str]]) -> Dict:
    """Parse metrics JSONL stream(s) into {rounds: [...], finals:
    {...}}. A single path or a list of per-host paths (merged on
    ts+proc tags).

    `finals` is keyed by "host/pid": counters are per-process, so on a
    merged multi-process stream one last-record-wins snapshot would
    silently report a single process's totals as the run's."""
    if isinstance(paths, str):
        paths = [paths]
    rounds: List[Dict] = []
    finals: Dict[str, Dict] = {}
    # counters are PER-PROCESS (the registry dies with the process) and
    # the streams are append-mode, so a resumed run restarts every
    # counter at zero mid-file; deltas must be tracked per (host, pid)
    # or a post-resume record would mis-subtract the dead process's
    # totals (under- or over-counting depending on magnitudes)
    prev_by_proc: Dict[str, Dict[str, int]] = {}
    for rec in _read_merged(paths):
        kind = rec.get("kind")
        metrics = rec.get("metrics") or {}
        if kind == "round":
            proc_key = f"{rec.get('host')}/{rec.get('pid')}"
            prev_counters = prev_by_proc.setdefault(proc_key, {})
            row = {
                "proc": proc_key,
                "round": rec.get("round"),
                "steps": rec.get("steps"),
                "examples": rec.get("examples"),
                "images_per_sec": rec.get("images_per_sec"),
                "step_p50_ms": rec.get("step_p50_ms"),
                "step_p99_ms": rec.get("step_p99_ms"),
                "data_total_ms": rec.get("data_total_ms"),
                "ckpt_save_s": _hist_stat(metrics, "checkpoint.save_s",
                                          "p50"),
            }
            for cname, label in DELTA_COUNTERS:
                cur = _counter(metrics, cname)
                row[label] = cur - prev_counters.get(cname, 0)
                prev_counters[cname] = cur
            rounds.append(row)
        elif kind in ("final", "heartbeat", "metrics"):
            # newest snapshot wins PER PROCESS (the `final` record on a
            # clean close; the last heartbeat after a preemption)
            if metrics:
                finals[f"{rec.get('host')}/{rec.get('pid')}"] = metrics
    return {"rounds": rounds, "finals": finals}


def _fmt(v, width: int, prec: int = 1) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.{prec}f}".rjust(width)
    return str(v).rjust(width)


def render(agg: Dict) -> str:
    lines: List[str] = []
    multi_proc = len({r["proc"] for r in agg["rounds"]}) > 1 \
        or len(agg["finals"]) > 1
    cols = ([("proc", 16)] if multi_proc else []) + \
           [("round", 5), ("steps", 6), ("examples", 8),
            ("img/s", 9), ("p50ms", 8), ("p99ms", 8),
            ("data_ms", 8), ("save_s", 7)] + \
           [(label, 7) for _, label in DELTA_COUNTERS]
    if agg["rounds"]:
        lines.append("per-round summary:")
        lines.append("  " + " ".join(name.rjust(w) for name, w in cols))
        for row in agg["rounds"]:
            vals = ([row["proc"].rjust(16)] if multi_proc else []) + [
                _fmt(row["round"], 5), _fmt(row["steps"], 6),
                _fmt(row["examples"], 8),
                _fmt(row["images_per_sec"], 9),
                _fmt(row["step_p50_ms"], 8, 2),
                _fmt(row["step_p99_ms"], 8, 2),
                _fmt(row["data_total_ms"], 8),
                _fmt(row["ckpt_save_s"], 7, 3),
            ] + [_fmt(row[label], 7) for _, label in DELTA_COUNTERS]
            lines.append("  " + " ".join(vals))
    else:
        lines.append("no per-round records found")
    for proc_key in sorted(agg["finals"]):
        final = agg["finals"][proc_key]
        lines.append("")
        lines.append("final counters/gauges"
                     + (f" [{proc_key}]" if multi_proc else "") + ":")
        for name in sorted(final):
            v = final[name]
            if isinstance(v, dict):
                p50 = v.get("p50")
                p99 = v.get("p99")
                lines.append(
                    f"  {name}: count={v.get('count')} "
                    f"sum={_fmt(v.get('sum'), 1, 4).strip()} "
                    f"p50={_fmt(p50, 1, 4).strip()} "
                    f"p99={_fmt(p99, 1, 4).strip()}")
            else:
                lines.append(f"  {name}: {v}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__)
        return 1
    agg = aggregate(paths)
    if as_json:
        print(json.dumps(agg, indent=2, default=str))
    else:
        print(render(agg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
