"""TVM-style autotuner: measure the dispatch/staging/serving knob
space and persist a per-platform tuning cache (arXiv:1802.04799;
docs/GRAPH_PASSES.md "Autotuner").

    python -m cxxnet_tpu.tools.autotune [--out models/tuning_cache.json]
        [--conf workload.conf] [--budget-secs N] [--serve 0|1]
        [--per-layer 0|1]

Searched knobs (nnet/tuning.py TUNABLE_KEYS):

- `steps_per_dispatch` x `prefetch_stage`: a bounded grid of fused
  dispatch depth against staging-prefetch depth, measured as e2e
  images/sec through the REAL trainer.update()/update_chunk path on
  synthetic host batches (both knobs interact: a deep prefetch feeds
  a fused chunk, a shallow one starves it);
- `serve_max_batch`: the serving bucket-ladder ceiling, measured as
  rows/sec through a real warmed `serve.Server` under a mixed-size
  request storm - and, from the storm's own request-size histogram
  (the Server's `request_sizes` telemetry), a SHAPED bucket ladder
  (`serve.ladder_from_histogram`) replacing the fixed power-of-two
  set, persisted as the v2 cache's `serve_ladder` when it measures
  at least as fast;
- `stage_dtype` (the staged-input layout axis): bf16 vs f32 H2D
  staging, measured only when the workload computes in bf16 (the
  knob is a no-op under f32 - docs/PERFORMANCE.md).

Per-layer search (`--per-layer 1`, schema-v2 `layers` plans -
nnet/tuning.py LAYER_TUNABLE_KEYS): a bounded greedy flip of
`space_to_depth` per strided conv and `layer_dtype` per conv/fullc
(bf16 + autocast workloads, feeding the autocast pass's dtype plan),
each candidate measured through the REAL cache-pickup path (a temp
tuning_cache the trainer replays), so a plan that wins the search is
by construction a plan the product applies. Workloads running the
`quantize_int8` pass additionally search `layer_quant` per eligible
conv/fullc (pin a layer back to float where int8 loses -
docs/GRAPH_PASSES.md "when int8 loses"), measured through the
INFERENCE path (calibrate once, then timed predict_dist) since
quantization never touches training.

The winners persist under `--out` keyed by jax backend platform
(cpu/gpu/tpu); `main.py` / `wrapper.Net` pick them up via
`tuning_cache = <path>` with explicit config keys always winning.
The default workload is the tiny synthetic MLP (dispatch-bound, so
the fused-dispatch axis is clearly visible); point `--conf` at a
real config to tune for a real model.

Exit 0 on success (cache written), 1 on a search failure.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

_DEFAULT_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 64
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 64
dev = cpu
eta = 0.1
silent = 1
seed = 11
"""

# bounded candidate grids: the cache is a default, not a proof - a
# coarse grid that always finishes beats an exhaustive one that
# blows the budget (per-cell step counts are sized from a timed
# probe step, bench.py _warm_and_size style)
_K_GRID = (1, 2, 4)
_PREFETCH_GRID = (0, 1, 2)
_SERVE_GRID = (8, 16, 32)


def _make_trainer(conf_pairs: Sequence[Tuple[str, str]],
                  extra: Sequence[Tuple[str, str]] = ()):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    tr = NetTrainer()
    for k, v in list(conf_pairs) + list(extra):
        tr.set_param(k, v)
    tr.init_model()
    return tr


def _synth_batches(tr, n: int) -> List:
    """Synthetic host batches matching the trainer's input/label
    shape (labels sized from the final node's width so loss layers
    index valid classes)."""
    from cxxnet_tpu.io.data import DataBatch
    c, y, x = tr.net_cfg.input_shape
    final = tr.net.node_shapes[tr.net_cfg.num_nodes - 1]
    nclass = max(2, int(np.prod(final[1:])))
    rng = np.random.RandomState(23)
    out = []
    for _ in range(n):
        out.append(DataBatch(
            data=rng.rand(tr.batch_size, c, y, x).astype(np.float32),
            label=rng.randint(0, nclass, size=(tr.batch_size, 1))
            .astype(np.float32)))
    return out


class _Cycle:
    """Minimal DataIter serving `n` host batches from a buffer."""

    def __init__(self, batches: List, n: int):
        self._b, self.n, self.i = batches, n, -1

    def before_first(self):
        self.i = -1

    def next(self):
        self.i += 1
        return self.i < self.n

    def value(self):
        return self._b[self.i % len(self._b)]


def measure_train_ips(tr, batches: List, k: int, prefetch: int,
                      budget_s: float) -> float:
    """e2e images/sec of the real update path at one
    (steps_per_dispatch, prefetch_stage) grid cell. K applies at the
    call level (update_chunk takes any chunk length), so one trainer
    serves the whole grid - no recompiles beyond the per-K chunk
    executable."""
    import jax
    nbuf = len(batches)

    def run_steps(n: int) -> None:
        if prefetch > 0:
            pf = tr.prefetch(_Cycle(batches, n), prefetch, chunk=k)
            try:
                pf.before_first()
                while pf.next():
                    tr.update(pf.value())
            finally:
                pf.close()
        elif k > 1:
            for i in range(0, n, k):
                tr.update_chunk(
                    [batches[(i + j) % nbuf]
                     for j in range(min(k, n - i))])
        else:
            for i in range(n):
                tr.update(batches[i % nbuf])

    # warm (compile) + size the window from one timed chunk
    run_steps(k)
    jax.block_until_ready(tr.state["epoch"])
    t0 = time.perf_counter()
    run_steps(k)
    jax.block_until_ready(tr.state["epoch"])
    per_step = max((time.perf_counter() - t0) / k, 1e-6)
    n = int(min(200, max(2 * k, budget_s / per_step)))
    t0 = time.perf_counter()
    run_steps(n)
    jax.block_until_ready(tr.state["epoch"])
    dt = max(time.perf_counter() - t0, 1e-9)
    return n * tr.batch_size / dt


def measure_serve_rows(tr, max_batch: int, budget_s: float,
                       ladder=None):
    """(rows/sec, stats) through a warmed continuous-batching Server
    at one bucket-ladder ceiling, under a mixed-size request storm.
    `ladder` passes an explicit bucket ladder (the shaped-ladder
    measurement); the stats carry the storm's request-size histogram
    (`request_sizes`) the ladder shaping reads."""
    from cxxnet_tpu.serve import Server
    c, y, x = tr.net_cfg.input_shape
    rng = np.random.RandomState(29)
    data = rng.rand(max_batch, c, y, x).astype(np.float32)
    srv = Server(tr, max_batch=max_batch, max_wait_ms=2.0, replicas=2,
                 ladder=ladder)
    srv.warmup()
    srv.start()
    try:
        sizes = [1, max_batch // 2 or 1, max_batch, 3,
                 max_batch // 4 or 1]
        # size the storm from one timed round of the cycle
        t0 = time.perf_counter()
        for n in sizes:
            srv.submit(data[:n]).result(timeout=120)
        per_round = max(time.perf_counter() - t0, 1e-6)
        rounds = int(min(50, max(2, budget_s / per_round)))
        total = 0
        t0 = time.perf_counter()
        futs = []
        for _ in range(rounds):
            for n in sizes:
                futs.append(srv.submit(data[:n]))
                total += n
        for f in futs:
            f.result(timeout=600)
        dt = max(time.perf_counter() - t0, 1e-9)
    finally:
        stats = srv.stop()
    if stats["errors"]:
        raise RuntimeError(f"{stats['errors']} serve dispatch errors")
    return total / dt, stats


def _measure_plan_ips(conf_pairs, extra, plan, batches,
                      budget_s: float) -> float:
    """e2e images/sec of a per-layer plan candidate, measured through
    the REAL pickup path: the plan is written to a temp tuning_cache
    and a fresh trainer replays it via `tuning_cache =` - so the
    search can never win with a plan the product would not apply."""
    import tempfile

    import jax
    from cxxnet_tpu.nnet import tuning
    fd, path = tempfile.mkstemp(suffix=".json", prefix="cxn_tune_")
    os.close(fd)
    os.unlink(path)
    try:
        tuning.save_entry(path, jax.default_backend(), {},
                          layers=plan)
        tr = _make_trainer(conf_pairs,
                           list(extra) + [("tuning_cache", path)])
        return measure_train_ips(tr, batches, 1, 0, budget_s)
    finally:
        if os.path.exists(path):
            os.unlink(path)


def _measure_infer_plan_ips(conf_pairs, extra, plan, batches,
                            budget_s: float) -> float:
    """Inference images/sec of a per-layer plan candidate through the
    REAL pickup path (the `layer_quant` axis: quantization only
    touches the infer executables, so its candidates must be priced
    on predict, not update): temp tuning_cache, fresh trainer,
    calibrate on the first batch (quant/fold scales freeze there,
    outside the timed window), then a timed predict_dist loop."""
    import tempfile

    import jax
    from cxxnet_tpu.nnet import tuning
    fd, path = tempfile.mkstemp(suffix=".json", prefix="cxn_tune_")
    os.close(fd)
    os.unlink(path)
    try:
        tuning.save_entry(path, jax.default_backend(), {},
                          layers=plan)
        tr = _make_trainer(conf_pairs,
                           list(extra) + [("tuning_cache", path)])
        tr.predict_dist(batches[0])  # compile + calibrate
        t0 = time.perf_counter()
        tr.predict_dist(batches[0])
        per = max(time.perf_counter() - t0, 1e-6)
        n = int(min(100, max(3, budget_s / per)))
        t0 = time.perf_counter()
        for i in range(n):
            tr.predict_dist(batches[i % len(batches)])
        dt = max(time.perf_counter() - t0, 1e-9)
        return n * tr.batch_size / dt
    finally:
        if os.path.exists(path):
            os.unlink(path)


def per_layer_search(conf_pairs: Sequence[Tuple[str, str]],
                     budget_s: float,
                     extra: Sequence[Tuple[str, str]] = (),
                     max_layers: int = 6) -> Dict:
    """Bounded greedy per-layer knob search (docs/GRAPH_PASSES.md
    "per-layer autotuner"): for each named strided conv flip
    `space_to_depth` 0/1; on bf16 workloads running the autocast
    pass flip conv/fullc layers' `layer_dtype` to f32; on workloads
    running quantize_int8 flip eligible conv/fullc layers'
    `layer_quant` to float (int8 is the pass default - the search
    pins back the layers where it loses), priced on the INFER path.
    A flip joins the plan only when it beats the incumbent by > 2%
    (hysteresis: measurement noise must not churn plans). Returns
    {"layers": plan, "grid": per-candidate ips}."""
    import jax.numpy as jnp
    base = _make_trainer(conf_pairs, extra)
    cands: List[Tuple[str, str, Tuple[str, ...]]] = []
    autocast_on = (base.compute_dtype == jnp.bfloat16
                   and base._pipeline is not None
                   and base._pipeline.has("autocast"))
    quant_on = (base._pipeline is not None
                and base._pipeline.has("quantize_int8"))
    for idx, info in enumerate(base.net_cfg.layers):
        if info.is_shared or not info.name:
            continue
        explicit = {k for k, _ in (base.net_cfg.defcfg
                                   + base.net_cfg.layercfg[idx])}
        lay = base.net.layer_objs[idx]
        if (info.type_name == "conv" and lay.param.stride > 1
                and "space_to_depth" not in explicit):
            cands.append((info.name, "space_to_depth", ("0", "1")))
        if (autocast_on and info.type_name in ("conv", "fullc")
                and "layer_dtype" not in explicit):
            cands.append((info.name, "layer_dtype", ("float32",)))
        if (quant_on and info.type_name in ("conv", "fullc")
                and "layer_quant" not in explicit):
            cands.append((info.name, "layer_quant", ("float",)))
    cands = cands[:max_layers]
    grid: Dict[str, float] = {}
    if not cands:
        return {"layers": {}, "grid": grid}
    batches = _synth_batches(base, 8)
    n_meas = 1 + sum(len(c[2]) for c in cands)
    per = max(1.0, budget_s / n_meas)
    plan: Dict[str, Dict[str, str]] = {}
    # two incumbents, one per measurement path: train-path flips
    # (s2d/dtype) and infer-path flips (quant) are priced against
    # their own baseline - the two clocks are not comparable
    best = _measure_plan_ips(conf_pairs, extra, {}, batches, per)
    grid["plan_default"] = round(best, 2)
    best_infer = None
    if any(key == "layer_quant" for _ln, key, _a in cands):
        best_infer = _measure_infer_plan_ips(conf_pairs, extra, {},
                                             batches, per)
        grid["plan_infer_default"] = round(best_infer, 2)
    infer_stale = False
    for lname, key, alts in cands:
        infer_axis = key == "layer_quant"
        for v in alts:
            if infer_axis and infer_stale:
                # a train-axis flip (s2d/dtype) joined the shared
                # plan since the infer incumbent was measured; those
                # flips change inference speed too, so re-base it or
                # the quant trial would be priced against the other
                # axis's infer-side gain. (The reverse never stales:
                # layer_quant only touches the infer executables.)
                best_infer = _measure_infer_plan_ips(
                    conf_pairs, extra, plan, batches, per)
                grid["plan_infer_rebase"] = round(best_infer, 2)
                infer_stale = False
            trial = {ln: dict(kv) for ln, kv in plan.items()}
            trial.setdefault(lname, {})[key] = v
            measure = (_measure_infer_plan_ips if infer_axis
                       else _measure_plan_ips)
            ips = measure(conf_pairs, extra, trial, batches, per)
            grid[f"{lname}.{key}={v}"] = round(ips, 2)
            if infer_axis:
                if ips > best_infer * 1.02:
                    best_infer = ips
                    plan = trial
            elif ips > best * 1.02:
                best = ips
                plan = trial
                infer_stale = True
    out = {"layers": plan, "grid": grid,
           "plan_best_ips": round(best, 2)}
    if best_infer is not None:
        out["plan_infer_best_ips"] = round(best_infer, 2)
    return out


def search(conf_pairs: Sequence[Tuple[str, str]], budget_s: float,
           serve: bool = True, per_layer: bool = True,
           extra: Sequence[Tuple[str, str]] = ()) -> Dict:
    """Run the bounded knob search; returns {knobs, measured, layers,
    serve_ladder}. The `default_ips` cell (K=1, prefetch_stage=1 -
    the shipped defaults) is always measured first so
    `tuned_over_default` is an in-window ratio, never a cross-run
    comparison."""
    tr = _make_trainer(conf_pairs, extra)
    batches = _synth_batches(tr, 8)
    cells = [(k, p) for k in _K_GRID for p in _PREFETCH_GRID]
    knob_share = 0.7 - (0.2 if per_layer else 0.0)
    per_cell = max(1.0, budget_s * knob_share / len(cells))
    measured: Dict[str, float] = {}
    grid: Dict[str, float] = {}
    best = (None, -1.0)
    for k, p in cells:
        ips = measure_train_ips(tr, batches, k, p, per_cell)
        grid[f"k{k}_p{p}"] = round(ips, 2)
        if k == 1 and p == 1:
            measured["default_ips"] = round(ips, 2)
        if ips > best[1]:
            best = ((k, p), ips)
    (bk, bp), best_ips = best
    measured["best_ips"] = round(best_ips, 2)
    knobs: Dict[str, object] = {"steps_per_dispatch": bk,
                                "prefetch_stage": bp}
    layers: Dict[str, Dict[str, str]] = {}
    serve_ladder = None
    if per_layer:
        pl = per_layer_search(conf_pairs, budget_s * 0.2, extra)
        layers = pl["layers"]
        grid.update(pl["grid"])
        if "plan_best_ips" in pl:
            measured["plan_best_ips"] = pl["plan_best_ips"]
        if "plan_infer_best_ips" in pl:
            measured["plan_infer_best_ips"] = pl["plan_infer_best_ips"]
    if serve:
        from cxxnet_tpu.serve import ladder_from_histogram
        sbest = (None, -1.0)
        hist: Dict[int, int] = {}
        per_mb = max(1.0, budget_s * 0.25 / (len(_SERVE_GRID) + 1))
        for mb in _SERVE_GRID:
            rows, stats = measure_serve_rows(tr, mb, per_mb)
            grid[f"serve_mb{mb}"] = round(rows, 2)
            for s, c in stats.get("request_sizes", {}).items():
                hist[int(s)] = hist.get(int(s), 0) + int(c)
            if rows > sbest[1]:
                sbest = (mb, rows)
        knobs["serve_max_batch"] = sbest[0]
        measured["serve_rows_per_s"] = round(sbest[1], 2)
        # ladder shaped from the storm's own request-size telemetry
        # (docs/SERVING.md "bucket ladder"): adopted only when it does
        # not lose to the power-of-two set at the winning ceiling;
        # rungs ceil to the workload mesh's data axis so the measured
        # ladder IS the persisted one (an unceiled rung would be
        # silently dropped by ladder_buckets at serve time)
        shaped = ladder_from_histogram(
            hist, sbest[0], tr.mesh.shape.get("data", 1))
        rows2, _st = measure_serve_rows(tr, sbest[0], per_mb,
                                        ladder=shaped)
        grid["serve_shaped_ladder"] = round(rows2, 2)
        if rows2 >= 0.98 * sbest[1]:
            serve_ladder = list(shaped)
            measured["serve_ladder_rows_per_s"] = round(rows2, 2)
    import jax.numpy as jnp
    if tr.compute_dtype == jnp.bfloat16:
        # the staged-input layout axis: bf16 host cast vs f32 bytes
        ips_by_layout = {}
        for layout in ("", "float32"):
            trl = _make_trainer(conf_pairs,
                                list(extra)
                                + [("stage_dtype", layout)])
            ips_by_layout[layout] = measure_train_ips(
                trl, _synth_batches(trl, 8), bk, bp,
                max(1.0, budget_s * 0.1))
        knobs["stage_dtype"] = max(ips_by_layout,
                                   key=ips_by_layout.get)
        grid["stage_dtype_ips"] = {
            k or "bfloat16": round(v, 2)
            for k, v in ips_by_layout.items()}
    measured["grid"] = grid
    return {"knobs": knobs, "measured": measured, "layers": layers,
            "serve_ladder": serve_ladder}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out",
                    default=os.path.join("models",
                                         "tuning_cache.json"))
    ap.add_argument("--conf", default="",
                    help="workload config (default: builtin tiny MLP)")
    ap.add_argument("--budget-secs", type=float, default=60.0)
    ap.add_argument("--serve", type=int, default=1)
    ap.add_argument("--per-layer", type=int, default=1,
                    help="greedy per-layer s2d/dtype plan search "
                    "(schema-v2 'layers' cache entries)")
    args = ap.parse_args()
    from cxxnet_tpu.utils.config import (parse_config_file,
                                         parse_config_string)
    pairs = (parse_config_file(args.conf) if args.conf
             else parse_config_string(_DEFAULT_CONF))
    import jax
    platform = jax.default_backend()
    kind = getattr(jax.devices()[0], "device_kind", "") or ""
    t0 = time.perf_counter()
    try:
        result = search(pairs, args.budget_secs,
                        serve=bool(args.serve),
                        per_layer=bool(args.per_layer))
    except Exception as e:  # noqa: BLE001 - CLI surface: say what broke
        print(f"autotune: search failed: {type(e).__name__}: {e}")
        return 1
    from cxxnet_tpu.nnet import tuning
    tuning.save_entry(args.out, platform, result["knobs"],
                      result["measured"], device_kind=kind,
                      layers=result.get("layers") or {},
                      serve_ladder=result.get("serve_ladder"))
    dt = time.perf_counter() - t0
    m = result["measured"]
    speedup = (m["best_ips"] / m["default_ips"]
               if m.get("default_ips") else float("nan"))
    print(f"autotune[{platform}]: best {result['knobs']} "
          f"({m['best_ips']} img/s, {speedup:.2f}x over default) "
          f"in {dt:.1f}s -> {args.out}")
    if result.get("layers"):
        print(f"  per-layer plan: {result['layers']}")
    if result.get("serve_ladder"):
        print(f"  serve ladder: {result['serve_ladder']}")
    print("  use it with: tuning_cache = " + args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
