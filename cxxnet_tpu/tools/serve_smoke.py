"""Serving smoke: `task = serve` must be `task = pred` with a queue.

    python -m cxxnet_tpu.tools.serve_smoke [--out DIR] [--keep]

Trains the tiny synthetic-MNIST MLP once through the real CLI
(`python -m cxxnet_tpu.main`), then predicts the test set twice from
the saved checkpoint - once batch-at-a-time (`task = pred`) and once
through the continuous-batching server (`task = serve`,
`serve_rows = 0`: the ragged request-size cycle, so every bucket size
and the round-padding path are exercised) - and asserts:

- identical prediction files line for line (the serving layer's
  bucketing/padding/coalescing provably changes no answer at the
  product surface);
- the serve run's metrics stream carries the `serve.latency_s`
  histogram (p50/p99) and the `serve.queue_depth` gauge - the SLO
  surface of docs/SERVING.md;
- the event stream shows warmup before traffic and a summary after,
  and ragged mode really exercised padding.

Both inference children run under `--xla_cpu_use_thunk_runtime=false`
(same scoped pin as the fused/zero smokes): bucket executables are
different program shapes from the pred batch, and the thunk runtime's
per-shape codegen drifts ~1 ULP - backend noise the argmax labels
must not inherit. Exit 0 iff all checks pass; CI uploads the JSONL
latency artifacts.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
pred = {d}/out.txt
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 2
max_round = 2
eta = 0.3
metric = error
silent = 1
"""


def _run_cli(out_dir: str, *overrides: str) -> subprocess.CompletedProcess:
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # append, don't replace: inherited flags must keep applying
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    return subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, "serve_smoke.conf"), *overrides],
        env=env, capture_output=True, text=True, timeout=540)


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu.telemetry.sink import read_jsonl
    write_synth_mnist(out_dir, 192, 0, "train")
    # 96 test instances = 3 full batches (the mnist iterator only
    # serves whole batches; the ragged REQUEST sizes below are what
    # exercise the serving layer's padding)
    write_synth_mnist(out_dir, 96, 1, "test")
    with open(os.path.join(out_dir, "serve_smoke.conf"), "w") as f:
        f.write(CONF.format(d=out_dir))
    mdir = os.path.join(out_dir, "models")
    model = os.path.join(mdir, "0002.model")
    direct = os.path.join(out_dir, "pred_direct.txt")
    served = os.path.join(out_dir, "pred_serve.txt")
    log = os.path.join(out_dir, "serve_events.jsonl")
    metrics = os.path.join(out_dir, "serve_metrics.jsonl")

    train = _run_cli(out_dir, f"model_dir={mdir}")
    pred = _run_cli(out_dir, "task=pred", f"model_in={model}",
                    f"pred={direct}")
    serve = _run_cli(out_dir, "task=serve", f"model_in={model}",
                     f"pred={served}", "serve_rows=0",
                     "serve_max_batch=8", "serve_replicas=2",
                     f"log_file={log}", f"metrics_file={metrics}")

    def lines(path):
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read().splitlines()

    d_lines, s_lines = lines(direct), lines(served)
    serve_recs = ([r for r in read_jsonl(metrics)
                   if r.get("kind") == "serve"]
                  if os.path.exists(metrics) else [])
    m = serve_recs[-1]["metrics"] if serve_recs else {}
    lat = m.get("serve.latency_s") or {}
    events = ([e for e in read_jsonl(log) if e.get("kind") == "serve"]
              if os.path.exists(log) else [])
    ops = [e.get("op") for e in events]

    checks = [
        ("train run completed", train.returncode == 0
         and os.path.exists(model)),
        ("pred run completed", pred.returncode == 0
         and bool(d_lines)),
        ("serve run completed", serve.returncode == 0
         and bool(s_lines)),
        ("identical predictions (96 lines)",
         d_lines is not None and d_lines == s_lines
         and len(d_lines) == 96),
        ("latency histogram on the metrics stream (p50/p99)",
         lat.get("count", 0) > 0 and lat.get("p50") is not None
         and lat.get("p99") is not None),
        ("queue-depth gauge on the metrics stream",
         "serve.queue_depth" in m),
        ("ragged mode exercised padding",
         m.get("serve.padding_rows", 0) > 0),
        ("event stream: warmup before traffic, summary after",
         "warmup" in ops and "summary" in ops
         and ops.index("warmup") < ops.index("summary")),
    ]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not ok:
        for tag, r in (("train", train), ("pred", pred),
                       ("serve", serve)):
            if r.returncode != 0:
                print(f"--- {tag} stderr tail ---")
                print(r.stderr[-2000:])
    n = len(s_lines or [])
    print(f"serve_smoke: {'PASS' if ok else 'FAIL'} "
          f"({n} predictions, p50 {lat.get('p50')}s, "
          f"p99 {lat.get('p99')}s)")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: serve_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="serve_smoke_")
        rc = run_smoke(d)
        print(f"serve_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
