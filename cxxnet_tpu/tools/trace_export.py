"""Render request-trace telemetry to Chrome trace-event JSON.

    python -m cxxnet_tpu.tools.trace_export run.events.jsonl \
        -o trace.json [--summary-json summary.json]

The serving layer's end-to-end request tracing
(docs/OBSERVABILITY.md "Request tracing") emits one ``trace`` event
per resolved request part on the event stream (``log_file=``): the
trace id minted at ``Server.submit``, the part/parts split indices of
an oversize request, the bucket + executable fingerprint it
dispatched under, and the monotonic ``t_submit`` / ``t_collect`` /
``t_dispatch`` / ``t_done`` stamps that cut each request into its
**queue** phase (submit -> dispatch, incl. the fill-or-timeout
coalesce wait) and **device** phase (dispatch -> result). This
tool renders those records into the Chrome trace-event format
(``{"traceEvents": [...]}``) loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``:

- one timeline lane per in-flight request slot (requests reuse freed
  lanes, so a storm renders as a compact band instead of 10k rows);
- per request part a parent ``request <id>`` span with nested
  ``queue`` and ``device`` child spans, args carrying rows / bucket /
  fingerprint / part indices;
- ``watchdog`` stall-dump and ``serve`` warmup/summary events as
  instant markers, so a hang investigation sees the dump next to the
  requests it interrupted.

A latency summary (count, queue/device/total p50+p99 ms, per-bucket
dispatch counts) prints to stdout and optionally lands in
``--summary-json`` - the p99-decomposes-into-queue-vs-device number
the serving SLO story wants. Timestamps are normalized to the first
record so the monotonic clock's epoch never leaks into the trace.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Tuple

from cxxnet_tpu.telemetry.registry import _percentile
from cxxnet_tpu.telemetry.sink import read_jsonl


def collect_traces(records) -> Tuple[List[Dict[str, Any]],
                                     List[Dict[str, Any]]]:
    """(trace part records, marker records) out of an event stream."""
    parts: List[Dict[str, Any]] = []
    markers: List[Dict[str, Any]] = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "trace" and "t_submit" in rec and "t_done" in rec:
            parts.append(rec)
        elif kind == "watchdog" and rec.get("op") == "stall_dump":
            markers.append(rec)
        elif kind == "serve" and rec.get("op") in ("warmup", "summary"):
            markers.append(rec)
    return parts, markers


def _lane_assign(parts: List[Dict[str, Any]]) -> Dict[Tuple, int]:
    """Greedy interval-graph coloring: each request part gets the
    lowest lane free over its [t_submit, t_done) interval, so
    concurrent requests stack and sequential ones reuse lanes."""
    order = sorted(parts, key=lambda r: float(r["t_submit"]))
    lane_free_at: List[float] = []
    lanes: Dict[Tuple, int] = {}
    for rec in order:
        t0 = float(rec["t_submit"])
        t1 = float(rec["t_done"])
        for i, free in enumerate(lane_free_at):
            if free <= t0:
                lane_free_at[i] = t1
                lanes[(rec.get("trace"), rec.get("part", 0))] = i
                break
        else:
            lane_free_at.append(t1)
            lanes[(rec.get("trace"), rec.get("part", 0))] = (
                len(lane_free_at) - 1)
    return lanes


def build_chrome_trace(parts: List[Dict[str, Any]],
                       markers: List[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Chrome trace-event JSON ({"traceEvents": [...]}) from trace
    part records: "X" complete events (ts/dur in microseconds) on one
    process, one lane (tid) per concurrent request slot."""
    events: List[Dict[str, Any]] = []
    if not parts and not markers:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # markers carry WALL ts while trace spans carry monotonic stamps.
    # Every part record carries BOTH (its record-level `ts` is stamped
    # at emission, within ~the event-write latency of its monotonic
    # t_done), so the wall->monotonic offset is derivable and the two
    # populations share ONE timeline - a stall dump renders next to
    # the requests it actually interrupted, not shifted by the
    # process-start gap. With no parts, markers anchor to their own
    # minimum (nothing to align against).
    offsets = sorted(float(r["ts"]) - float(r["t_done"])
                     for r in parts if "ts" in r)
    mono_base = min((float(r["t_submit"]) for r in parts),
                    default=0.0)
    if offsets:
        wall_off = offsets[len(offsets) // 2]
        marker_mono = [(float(r.get("ts", 0)) - wall_off, r)
                       for r in markers]
        mono_base = min([mono_base]
                        + [t for t, _ in marker_mono])
    else:
        wall_base = min((float(r.get("ts", 0)) for r in markers),
                        default=0.0)
        marker_mono = [(float(r.get("ts", 0)) - wall_base, r)
                       for r in markers]
        mono_base = 0.0
    lanes = _lane_assign(parts)
    pids = {rec.get("pid", 0) for rec in parts} or {0}
    for pid in sorted(pids):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"cxxnet serve (pid {pid})"}})
    for rec in parts:
        pid = rec.get("pid", 0)
        tid = lanes[(rec.get("trace"), rec.get("part", 0))]
        t_submit = float(rec["t_submit"]) - mono_base
        # the queue/device cut is the DISPATCH stamp (older streams
        # without it fall back to the coalesce stamp)
        cut = rec.get("t_dispatch",
                      rec.get("t_collect", rec["t_submit"]))
        t_cut = float(cut) - mono_base
        t_done = float(rec["t_done"]) - mono_base
        trace_id = rec.get("trace", "?")
        label = (f"request {trace_id}"
                 + (f" [{rec.get('part', 0) + 1}/{rec['parts']}]"
                    if rec.get("parts", 1) > 1 else ""))
        args = {"trace": trace_id, "rows": rec.get("rows"),
                "bucket": rec.get("bucket"), "fp": rec.get("fp"),
                "part": rec.get("part", 0),
                "parts": rec.get("parts", 1),
                "queue_ms": rec.get("queue_ms"),
                "device_ms": rec.get("device_ms")}
        events.append({"ph": "X", "name": label, "cat": "request",
                       "pid": pid, "tid": tid,
                       "ts": round(t_submit * 1e6, 3),
                       "dur": round((t_done - t_submit) * 1e6, 3),
                       "args": args})
        events.append({"ph": "X", "name": "queue", "cat": "queue",
                       "pid": pid, "tid": tid,
                       "ts": round(t_submit * 1e6, 3),
                       "dur": round((t_cut - t_submit) * 1e6, 3),
                       "args": {"trace": trace_id}})
        events.append({"ph": "X", "name": "device", "cat": "device",
                       "pid": pid, "tid": tid,
                       "ts": round(t_cut * 1e6, 3),
                       "dur": round((t_done - t_cut) * 1e6, 3),
                       "args": {"trace": trace_id,
                                "fp": rec.get("fp"),
                                "bucket": rec.get("bucket")}})
    for mono, rec in marker_mono:
        pid = rec.get("pid", 0)
        ts = (mono - mono_base) * 1e6
        name = ("watchdog stall_dump"
                if rec.get("kind") == "watchdog"
                else f"serve {rec.get('op')}")
        events.append({"ph": "i", "name": name, "cat": "marker",
                       "pid": pid, "tid": 0, "ts": round(ts, 3),
                       "s": "p"})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def summarize(parts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Queue-vs-device latency decomposition over the traced parts."""
    queue = [float(r.get("queue_ms", 0.0)) for r in parts]
    device = [float(r.get("device_ms", 0.0)) for r in parts]
    total = [(float(r["t_done"]) - float(r["t_submit"])) * 1e3
             for r in parts]
    by_bucket: Dict[str, int] = {}
    traces = set()
    complete = 0
    by_trace: Dict[str, set] = {}
    want_parts: Dict[str, int] = {}
    for r in parts:
        b = str(r.get("bucket"))
        by_bucket[b] = by_bucket.get(b, 0) + 1
        t = r.get("trace")
        traces.add(t)
        by_trace.setdefault(t, set()).add(r.get("part", 0))
        want_parts[t] = int(r.get("parts", 1))
    for t, seen in by_trace.items():
        if len(seen) == want_parts.get(t, 1):
            complete += 1
    out = {"parts": len(parts), "requests": len(traces),
           "complete_requests": complete,
           "dispatches_by_bucket": dict(sorted(by_bucket.items()))}
    for name, vals in (("queue", queue), ("device", device),
                       ("total", total)):
        if vals:
            # registry._percentile is THE percentile definition
            # (numpy's linear interpolation) - the summary's p99 must
            # match the Histogram p99 the registry reports for the
            # same stream; it takes pre-sorted values
            vals = sorted(vals)
            out[f"{name}_p50_ms"] = round(_percentile(vals, 50), 3)
            out[f"{name}_p99_ms"] = round(_percentile(vals, 99), 3)
    return out


def export(events_path: str, out_path: str,
           summary_path: str = "") -> Dict[str, Any]:
    parts, markers = collect_traces(read_jsonl(events_path))
    trace = build_chrome_trace(parts, markers)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    summary = summarize(parts)
    if summary_path:
        with open(summary_path, "w", encoding="utf-8") as f:
            json.dump(summary, f, indent=2)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render request-trace events to Chrome "
                    "trace-event JSON (Perfetto-loadable)")
    ap.add_argument("events", help="telemetry event JSONL (log_file=)")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output Chrome trace path")
    ap.add_argument("--summary-json", default="",
                    help="also write the latency summary JSON here")
    args = ap.parse_args(argv)
    summary = export(args.events, args.out, args.summary_json)
    if not summary["parts"]:
        print(f"trace_export: no trace events in {args.events} "
              "(serve with log_file= armed to record request traces)")
        return 1
    print(f"trace_export: {summary['parts']} part span(s) over "
          f"{summary['requests']} request(s) "
          f"({summary['complete_requests']} complete) -> {args.out}")
    for stem in ("queue", "device", "total"):
        if f"{stem}_p50_ms" in summary:
            print(f"  {stem:>6}: p50 {summary[f'{stem}_p50_ms']} ms, "
                  f"p99 {summary[f'{stem}_p99_ms']} ms")
    print(f"  dispatches by bucket: {summary['dispatches_by_bucket']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
