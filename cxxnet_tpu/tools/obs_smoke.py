"""Observability-plane end-to-end smoke (docs/OBSERVABILITY.md).

    python -m cxxnet_tpu.tools.obs_smoke [--out DIR] [--keep]
        [--parity-base DIR]

The acceptance proof the CI ``obs-smoke`` job runs: a short training
with the live plane armed (``metrics_port`` + ``watchdog_secs`` + an
absence alert rule) and a STALL injected mid-run (a ``delay`` fault at
the ``stage_batch`` fault point - the prefetch worker sleeps, the
update thread starves, ``train.step`` beacons stop: exactly the shape
of the hung-TPU rounds that motivated the watchdog). A poller thread
scrapes ``/healthz`` + ``/metrics`` + ``/varz`` throughout.

Exit 0 iff:

- every ``/metrics`` scrape parses as Prometheus text exposition
  (promtool-style line grammar) with the right content type;
- ``/healthz`` flips 200 -> 503 during the stall and recovers to 200
  once training resumes (the watchdog + alert hysteresis contract);
- the event stream carries the watchdog ``stall_dump`` (with thread
  stacks naming the sleeping fault point) and the alert rule's
  ``firing`` AND ``resolved`` events;
- the metrics stream ends with a ``final`` snapshot and a nonzero
  ``watchdog.stalls`` / ``alert.fired``;
- with ``--parity-base DIR`` (CI passes a checkout of the base
  commit): an UNARMED run of the same conf produces byte-identical
  stdout+stderr under this tree and the base tree - the pinned
  contract that the whole plane costs nothing when off.
"""

from __future__ import annotations

import gzip
import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

STALL_SECS = 8.0
# watchdog strictly below the absence rule's for_secs: the stack dump
# must land BEFORE the alert fires (the ordering the issue pins)
WATCHDOG_SECS = 2.0
ABSENCE_SECS = 4.0


def write_synth_mnist(dirname: str, n: int, seed: int,
                      prefix: str) -> None:
    """Separable 3-class idx-format set: class = f(mean intensity)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 3, size=n).astype(np.uint8)
    images = np.zeros((n, 6, 6), dtype=np.uint8)
    for i, y in enumerate(labels):
        base = 40 + 80 * int(y)
        images[i] = np.clip(rng.randn(6, 6) * 10 + base, 0, 255)
    with gzip.open(os.path.join(dirname, f"{prefix}-img.gz"), "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, 6, 6))
        f.write(images.tobytes())
    with gzip.open(os.path.join(dirname, f"{prefix}-lbl.gz"), "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())


CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 5
max_round = 5
eta = 0.3
metric = error
eval_train = 1
silent = 1
model_dir = {d}/models
"""

RULES = [{
    "name": "train-stalled",
    "type": "absence",
    "beacon": "train.step",
    "for_secs": ABSENCE_SECS,
}]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Poller(threading.Thread):
    """Samples /healthz (code timeline), /metrics (bodies + content
    type) and /varz while the run is live."""

    def __init__(self, port: int) -> None:
        super().__init__(name="obs-smoke-poller", daemon=True)
        self.base = f"http://127.0.0.1:{port}"
        self.stop = threading.Event()
        # sample fields shared with the main thread (read after
        # stop+join, but the lock makes the handoff explicit - the
        # GL012 lock-discipline rule flags bare cross-thread writes)
        self._lock = threading.Lock()
        self.codes = []          # de-duplicated /healthz code timeline
        self.metrics_bodies = []  # (healthz_code_at_sample, body)
        self.content_type = ""
        self.varz = None
        self.errors = 0

    def _healthz(self):
        try:
            with urllib.request.urlopen(self.base + "/healthz",
                                        timeout=1.0) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code
        except OSError:
            return None

    def run(self) -> None:
        while not self.stop.wait(0.1):
            code = self._healthz()
            if code is None:
                continue  # server not up yet / already gone
            if not self.codes or self.codes[-1] != code:
                self.codes.append(code)
            try:
                with urllib.request.urlopen(self.base + "/metrics",
                                            timeout=1.0) as r:
                    ctype = r.headers.get("Content-Type", "")
                    body = r.read().decode()
                with self._lock:
                    self.content_type = ctype
                    if (len(self.metrics_bodies) < 200
                            and (not self.metrics_bodies
                                 or self.metrics_bodies[-1][0] != code)):
                        self.metrics_bodies.append((code, body))
                    self.metrics_bodies[-1] = (code, body)  # keep newest
                with urllib.request.urlopen(self.base + "/varz",
                                            timeout=1.0) as r:
                    varz = json.load(r)
                with self._lock:
                    self.varz = varz
            except (OSError, ValueError):
                with self._lock:
                    self.errors += 1


def run_armed(out_dir: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.telemetry.http import validate_exposition
    from cxxnet_tpu.telemetry.sink import read_jsonl
    from cxxnet_tpu.utils import fault

    conf = os.path.join(out_dir, "obs_smoke.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(d=out_dir))
    rules = os.path.join(out_dir, "rules.json")
    with open(rules, "w") as f:
        json.dump(RULES, f)
    port = _free_port()

    # the injected hang: the prefetch worker sleeps inside
    # stage_batch, train.step beacons stop, the watchdog dumps stacks
    # showing exactly that frame - the forensics the hung-TPU rounds
    # never had. Hit 4 lands mid-round-1 (8 batches/round), leaving
    # 4+ rounds of live run for the recovery half of the proof
    fault.clear()
    fault.inject("stage_batch", "delay", arg=str(STALL_SECS), at=4)
    # pace the remaining batches (a tiny-MLP CPU round is ~30 ms -
    # nothing like a real training cadence): a modest per-batch delay
    # keeps the run alive long enough after the stall for the
    # recovery half of the proof (watchdog clears, alert resolves,
    # /healthz back to 200) to be OBSERVED by the poller, not just
    # recorded in the streams
    for hit in range(5, 5 * 8 + 1):
        fault.inject("stage_batch", "delay", arg="0.08", at=hit)
    poller = _Poller(port)
    poller.start()
    try:
        rc = LearnTask().run([
            conf,
            f"log_file={out_dir}/events.jsonl",
            f"metrics_file={out_dir}/metrics.jsonl",
            f"metrics_port={port}",
            f"watchdog_secs={WATCHDOG_SECS}",
            f"alert_rules={rules}",
        ])
    finally:
        fault.clear()
        time.sleep(0.25)  # let the poller observe the recovered tail
        poller.stop.set()
        poller.join(timeout=5.0)
    if rc != 0:
        print(f"obs_smoke: training failed rc={rc}")
        return 1

    events = list(read_jsonl(os.path.join(out_dir, "events.jsonl")))
    metrics = list(read_jsonl(os.path.join(out_dir, "metrics.jsonl")))
    dumps = [e for e in events if e.get("kind") == "watchdog"
             and e.get("op") == "stall_dump"]
    recovers = [e for e in events if e.get("kind") == "watchdog"
                and e.get("op") == "recovered"]
    alerts = [e for e in events if e.get("kind") == "alert"]
    finals = [m for m in metrics if m.get("kind") == "final"]

    def subsequence(seq, want):
        it = iter(seq)
        return all(any(x == w for x in it) for w in want)

    bad_lines = []
    for _, body in poller.metrics_bodies:
        bad_lines.extend(validate_exposition(body))
    last_metrics = (poller.metrics_bodies[-1][1]
                    if poller.metrics_bodies else "")
    checks = [
        ("healthz scraped", len(poller.codes) >= 1),
        ("healthz flipped 200 -> 503 -> 200",
         subsequence(poller.codes, [200, 503, 200])),
        ("prometheus content type",
         poller.content_type.startswith("text/plain")
         and "version=0.0.4" in poller.content_type),
        ("every /metrics scrape parses (promtool line grammar)",
         bool(poller.metrics_bodies) and not bad_lines),
        ("/metrics carries the step summary + checkpoint counter",
         "cxxnet_train_step_s" in last_metrics
         and "cxxnet_checkpoint_saves_total" in last_metrics),
        ("/varz is a metrics-stream-schema record",
         isinstance(poller.varz, dict)
         and poller.varz.get("kind") == "varz"
         and isinstance(poller.varz.get("metrics"), dict)
         and "ts" in poller.varz and "host" in poller.varz),
        ("watchdog stall dump event with thread stacks",
         any("stage_batch" in (d.get("stacks") or "")
             for d in dumps)),
        ("watchdog recovered event", len(recovers) >= 1),
        ("alert fired", any(a.get("state") == "firing"
                            and a.get("name") == "train-stalled"
                            for a in alerts)),
        ("alert resolved", any(a.get("state") == "resolved"
                               and a.get("name") == "train-stalled"
                               for a in alerts)),
        ("stall dump precedes the alert firing",
         bool(dumps) and any(
             a.get("state") == "firing"
             and a.get("ts", 0) >= dumps[0].get("ts", 0)
             for a in alerts)),
        ("final metrics snapshot with stall counters",
         bool(finals)
         and finals[-1]["metrics"].get("watchdog.stalls", 0) >= 1
         and finals[-1]["metrics"].get("alert.fired", 0) >= 1),
    ]
    failed = [name for name, ok in checks if not ok]
    for name, ok in checks:
        print(f"  [{'ok' if ok else 'FAIL'}] {name}")
    if bad_lines:
        print("  malformed exposition lines:")
        for line in bad_lines[:10]:
            print(f"    {line!r}")
    if failed:
        print(f"obs_smoke: FAILED: {failed}")
        print(f"  healthz timeline: {poller.codes}")
        return 1
    print(f"obs_smoke: armed run ok (healthz timeline "
          f"{poller.codes}, {len(dumps)} stall dump(s), "
          f"{len(alerts)} alert event(s))")
    return 0


def run_parity(out_dir: str, base_dir: str) -> int:
    """Unarmed byte-parity A/B: the same conf (no observability keys)
    run under THIS tree and under `base_dir` (a checkout of the base
    commit) must produce byte-identical stdout and stderr."""
    here = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(os.path.join(base_dir, "cxxnet_tpu")):
        print(f"obs_smoke: parity base {base_dir!r} has no "
              "cxxnet_tpu/ - skipping the A/B leg")
        return 0
    outs = []
    for tag, tree in (("head", here), ("base", base_dir)):
        d = os.path.join(out_dir, f"parity-{tag}")
        os.makedirs(d, exist_ok=True)
        write_synth_mnist(d, 256, 0, "train")
        write_synth_mnist(d, 64, 1, "test")
        conf = os.path.join(d, "parity.conf")
        with open(conf, "w") as f:
            f.write(CONF.format(d=d).replace(
                "num_round = 5", "num_round = 2").replace(
                "max_round = 5", "max_round = 2"))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.abspath(tree))
        p = subprocess.run(
            [sys.executable, "-m", "cxxnet_tpu.main", conf],
            capture_output=True, env=env, cwd=d, timeout=600)
        if p.returncode != 0:
            print(f"obs_smoke: parity run [{tag}] failed "
                  f"rc={p.returncode}:\n{p.stderr.decode()[-2000:]}")
            return 1
        outs.append((tag, p.stdout, p.stderr))
    (_, out_a, err_a), (_, out_b, err_b) = outs
    if out_a != out_b or err_a != err_b:
        print("obs_smoke: UNARMED OUTPUT DIVERGED from base:")
        if out_a != out_b:
            print(f"  stdout head: {out_a[:400]!r}")
            print(f"  stdout base: {out_b[:400]!r}")
        if err_a != err_b:
            print(f"  stderr head: {err_a[:400]!r}")
            print(f"  stderr base: {err_b[:400]!r}")
        return 1
    print("obs_smoke: unarmed run byte-identical to base "
          f"({len(out_a)} stdout + {len(err_a)} stderr bytes)")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out_dir = ""
    base_dir = ""
    keep = "--keep" in argv
    if "--out" in argv:
        out_dir = argv[argv.index("--out") + 1]
    if "--parity-base" in argv:
        base_dir = argv[argv.index("--parity-base") + 1]
    tmp = None
    if not out_dir:
        tmp = tempfile.TemporaryDirectory(prefix="obs_smoke_")
        out_dir = tmp.name
    os.makedirs(out_dir, exist_ok=True)
    try:
        write_synth_mnist(out_dir, 256, 0, "train")
        write_synth_mnist(out_dir, 64, 1, "test")
        rc = run_armed(out_dir)
        if rc == 0 and base_dir:
            rc = run_parity(out_dir, base_dir)
        return rc
    finally:
        if tmp is not None and not keep:
            tmp.cleanup()


if __name__ == "__main__":
    sys.exit(main())
