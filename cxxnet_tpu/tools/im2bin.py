#!/usr/bin/env python3
"""im2bin: pack images listed in a .lst into a BinaryPage .bin file.

Tool parity with tools/im2bin.cpp:6-67: reads `index \\t label \\t filename`
lines and appends each image file's raw bytes as one blob.

Usage: im2bin.py <image.lst> <image_root> <output.bin>
"""

import sys

from cxxnet_tpu.io.iter_img import parse_list_file
from cxxnet_tpu.utils.binary_page import BinaryPageWriter


def im2bin(list_path: str, image_root: str, out_path: str) -> int:
    entries = parse_list_file(list_path)
    count = 0
    with open(out_path, "wb") as fo:
        writer = BinaryPageWriter(fo)
        for _, _, fname in entries:
            with open(image_root + fname, "rb") as f:
                writer.push(f.read())
            count += 1
            if count % 1000 == 0:
                print(f"{count} images packed")
        writer.close()
    print(f"im2bin: packed {count} images into {out_path}")
    return count


def cli_main() -> None:
    if len(sys.argv) != 4:
        print(__doc__)
        sys.exit(1)
    im2bin(sys.argv[1], sys.argv[2], sys.argv[3])


if __name__ == "__main__":
    cli_main()
