"""Request-tracing + executable-introspection smoke (CI `trace-smoke`).

    python -m cxxnet_tpu.tools.trace_smoke [--out DIR]

Arms the observability plane (event sink + ephemeral `/metrics`
server), drives an in-process serve storm over a tiny MLP - ragged
request sizes including oversize requests that split - and asserts
the third observability tier end-to-end (docs/OBSERVABILITY.md):

- `/executables` lists exactly the warmed bucket executables, each
  with a compile wall-time, and the entry SET stays flat over the
  storm (the registry twin of the zero-recompile audit) while
  dispatch counts accumulate;
- every submitted request appears in the exported Chrome trace as a
  COMPLETE span tree (all split parts present, each with queue +
  device child spans), and the trace file parses as trace-event JSON
  loadable in Perfetto;
- the storm's p99 decomposes into queue vs device time (both
  histograms populated, summary carries the numbers);
- every `/metrics` scrape - including the new per-executable series
  and the `serve.request_rows` histogram - passes the promtool-style
  exposition grammar;
- the flight recorder's ring holds the storm's dispatches and the
  stall-dump path can name them (`format_tail` smoke).

Exit 0 iff every check passes; the events JSONL, Chrome trace and
summary land in `--out` for CI artifact upload.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

import numpy as np

MLP_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
eta = 0.3
silent = 1
seed = 7
"""

# ragged storm: every bucket size hit, several OVERSIZE requests
# (rows > max_batch=8) that split into parts - the trace must re-join
# them into one span tree per request
STORM_SIZES = [1, 3, 8, 2, 12, 5, 7, 20, 4, 6, 1, 9, 2, 16, 8, 3]


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10.0) as r:
        return r.read()


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu import telemetry
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve import Server
    from cxxnet_tpu.telemetry.http import validate_exposition
    from cxxnet_tpu.tools import trace_export
    from cxxnet_tpu.utils.config import parse_config_string

    events = os.path.join(out_dir, "trace_events.jsonl")
    trace_path = os.path.join(out_dir, "trace.json")
    summary_path = os.path.join(out_dir, "trace_summary.json")

    telemetry.configure(log_file=events)
    http = telemetry.arm_observability(metrics_port=0,
                                       metrics_host="127.0.0.1")
    base = f"http://127.0.0.1:{http.port}"

    tr = NetTrainer()
    for k, v in parse_config_string(MLP_CFG):
        tr.set_param(k, v)
    tr.init_model()
    srv = Server(tr, max_batch=8, max_wait_ms=2.0, replicas=2)
    srv.warmup()

    # /executables after warmup: exactly the bucket set, compile times
    execs0 = json.loads(_get(base + "/executables"))
    serve0 = {e["fingerprint"]: e
              for e in execs0.get("executables", [])
              if e.get("kind") == "serve"}
    scrape_ok = []
    for _ in range(2):
        bad = validate_exposition(_get(base + "/metrics").decode())
        scrape_ok.append(not bad)

    rng = np.random.RandomState(5)
    srv.start()
    futs = [srv.submit(rng.rand(n, 1, 1, 36).astype(np.float32))
            for n in STORM_SIZES]
    for f in futs:
        f.result(timeout=120)
    bad = validate_exposition(_get(base + "/metrics").decode())
    scrape_ok.append(not bad)
    metrics_txt = _get(base + "/metrics").decode()
    execs1 = json.loads(_get(base + "/executables"))
    serve1 = {e["fingerprint"]: e
              for e in execs1.get("executables", [])
              if e.get("kind") == "serve"}
    varz = json.loads(_get(base + "/varz"))
    flight_tail_txt = telemetry.flight().format_tail(8)
    n_flight = len(telemetry.flight().snapshot())
    stats = srv.stop()
    telemetry.close()

    summary = trace_export.export(events, trace_path, summary_path)
    with open(trace_path, "r", encoding="utf-8") as f:
        trace = json.load(f)
    tev = trace.get("traceEvents", [])
    spans = [e for e in tev if e.get("ph") == "X"]
    # expected split parts: ceil(n / max_batch) per request
    want_parts = sum(-(-n // 8) for n in STORM_SIZES)

    checks = [
        ("/executables lists the warmed bucket executables",
         len(serve0) == len(srv.buckets)
         and all(e.get("compile_s") is not None
                 for e in serve0.values())),
        ("executable cost analysis recorded (flops/bytes)",
         all(e.get("flops") is not None for e in serve0.values())),
        ("executable set flat after the storm",
         set(serve1) == set(serve0)),
        ("dispatch counts accumulated over the storm",
         sum(e["dispatches"] for e in serve1.values())
         >= stats["batches"] > 0),
        ("every /metrics scrape parses (incl. executable series)",
         all(scrape_ok)),
        ("serve.request_rows histogram exported",
         "cxxnet_serve_request_rows_bucket" in metrics_txt),
        ("/varz carries the flight tail",
         bool(varz.get("flight"))),
        ("flight ring recorded the storm's dispatches",
         n_flight >= stats["batches"]
         and "fp=" in flight_tail_txt),
        ("chrome trace parses with span events",
         isinstance(tev, list) and len(spans) == 3 * want_parts),
        ("every submitted request is a complete span tree",
         summary.get("requests") == len(STORM_SIZES)
         and summary.get("complete_requests") == len(STORM_SIZES)
         and summary.get("parts") == want_parts),
        ("p99 decomposes into queue vs device time",
         summary.get("queue_p99_ms") is not None
         and summary.get("device_p99_ms") is not None
         and summary.get("total_p99_ms") is not None),
        ("server stats carry the queue/device breakdown",
         stats.get("queue_p99_ms") is not None
         and stats.get("device_p99_ms") is not None),
        ("no dispatch errors", stats["errors"] == 0),
    ]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    print(f"trace_smoke: {'PASS' if ok else 'FAIL'} "
          f"({summary.get('parts')} parts / "
          f"{summary.get('requests')} requests, queue p99 "
          f"{summary.get('queue_p99_ms')} ms, device p99 "
          f"{summary.get('device_p99_ms')} ms, buckets "
          f"{summary.get('dispatches_by_bucket')})")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: trace_smoke [--out DIR]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
