"""Production serving-front smoke: overload + hot-swap end to end.

    python -m cxxnet_tpu.tools.serve_http_smoke [--out DIR] [--keep]

Trains the tiny synthetic-MNIST MLP through the real CLI (two rounds,
two checkpoints with genuinely different weights), then drives a live
HTTP server (`Server(http_port=..., queue_limit=..., swap_watch=...)`)
through the overload matrix of docs/SERVING.md "Serving over HTTP":

- the `serve_dispatch_delay` fault injector pins every dispatch to a
  fixed service time first: the tiny MLP is otherwise so fast that a
  GIL-bound python client can never exceed capacity, and "2x the
  sustainable rate" would depend on the CI machine. With service time
  pinned, sustainable capacity is deterministic everywhere;
- an uncontended leg measures the baseline p99 (sequential) and the
  sustainable rate (concurrent closed-loop burst - a single blocked
  client measures latency, not capacity), and every /metrics scrape
  must be exposition-valid;
- an OPEN-LOOP storm at ~2x sustainable past `queue_limit` must shed
  (429 + Retry-After observed) while the ACCEPTED requests keep p99
  within 3x uncontended - bounded latency is what shedding buys;
- a fresh checkpoint atomically published MID-STORM must be picked up
  live (swap event, zero errored requests - every response a 200 or a
  429, never a 5xx) and the post-swap answers must match a cold
  Server restarted on the new checkpoint bit for bit;
- a torn publish (CXXNET_FAULT `swap_torn_checkpoint:corrupt` writes
  half the bytes, trailer missing) must be REJECTED (`swap.rejected`)
  with serving uninterrupted on the last good weights.

Exit 0 iff all checks pass; CI uploads the response-code tallies and
latency summaries as artifacts.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 2
max_round = 2
eta = 0.3
metric = error
silent = 1
"""

# the same net, sans data/training keys: the in-process servers load
# the CLI-trained checkpoints into this config
NET_CFG = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,36
batch_size = 32
dev = cpu
silent = 1
"""


def _run_cli(out_dir: str, *overrides: str) -> subprocess.CompletedProcess:
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    return subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, "serve_http_smoke.conf"), *overrides],
        env=env, capture_output=True, text=True, timeout=540)


def _post(port: int, payload: dict, timeout: float = 120.0):
    """POST /predict; returns (status, headers, parsed body)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        return r.read().decode()


def _p99(lat_ms: list) -> float:
    if not lat_ms:
        return 0.0
    s = sorted(lat_ms)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu import telemetry
    from cxxnet_tpu.nnet import checkpoint
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve import Server
    from cxxnet_tpu.telemetry.http import validate_exposition
    from cxxnet_tpu.utils import fault

    write_synth_mnist(out_dir, 192, 0, "train")
    conf = os.path.join(out_dir, "serve_http_smoke.conf")
    with open(conf, "w") as f:
        f.write(CONF.format(d=out_dir))
    mdir = os.path.join(out_dir, "models")
    ck_old = os.path.join(mdir, "0001.model")
    ck_new = os.path.join(mdir, "0002.model")
    publish = os.path.join(out_dir, "publish.model")

    train = _run_cli(out_dir, f"model_dir={mdir}")
    trained = (train.returncode == 0 and os.path.exists(ck_old)
               and os.path.exists(ck_new))

    checks = [("train run produced two checkpoints", trained)]
    tally = {"200": 0, "429": 0, "other": 0}
    storm_p99 = uncont_p99 = 0.0
    bad_scrapes = []
    stats = {}
    swap_before_storm_end = post_matches_cold = served_through_torn = \
        saw_retry_after = False

    if trained:
        tr = NetTrainer(dev="cpu", cfg=NET_CFG)
        with open(ck_old, "rb") as f:
            tr.load_model(f)
        srv = Server(tr, max_batch=8, max_wait_ms=2.0, replicas=2,
                     http_port=0, queue_limit=8,
                     swap_watch=publish, swap_poll_ms=25.0)
        srv.warmup()
        # pin the service time: 30ms per dispatch, armed for far more
        # hits than the whole smoke dispatches
        fault.clear()
        for k in range(2000):
            fault.inject("serve_dispatch_delay", "delay", "0.03",
                         at=k + 1)
        srv.start()
        port = srv.metrics_server.port
        rng = np.random.RandomState(29)
        probe = rng.randn(4, 36).astype(np.float32).tolist()
        payload = {"data": probe, "raw": True}
        lock = threading.Lock()

        def timed_post(sink):
            ts = time.perf_counter()
            code, headers, _ = _post(port, payload)
            dt = (time.perf_counter() - ts) * 1e3
            with lock:
                tally[str(code) if str(code) in tally
                      else "other"] += 1
                if sink is not None and code == 200:
                    sink.append(dt)
            return code, headers

        # --- leg 1: uncontended p99, sequential ----------------------
        lat = []
        for _ in range(40):
            timed_post(lat)
        uncont_p99 = _p99(lat)
        # with service time pinned at 30ms/dispatch, sustainable
        # capacity is known analytically: replicas * max_batch rows
        # per dispatch window, in 4-row requests
        sustainable_rps = (2 * 8 / 0.03) / 4.0
        pre_swap = _post(port, payload)[2].get("outputs")
        bad_scrapes.extend(validate_exposition(_scrape(port)))

        # --- leg 2: open-loop storm at ~2x + mid-storm publish ------
        n_req = 160
        gaps = rng.exponential(1.0 / (2.0 * sustainable_rps), n_req)
        arrivals = np.cumsum(gaps)
        acc_lat = []
        storm_shed = 0

        def fire(i):
            nonlocal saw_retry_after, storm_shed
            ts = time.perf_counter()
            code, headers, _ = _post(port, payload)
            dt = (time.perf_counter() - ts) * 1e3
            with lock:
                tally[str(code) if str(code) in tally else
                      "other"] += 1
                if code == 200:
                    acc_lat.append(dt)
                elif code == 429:
                    storm_shed += 1
                    if "Retry-After" in headers:
                        saw_retry_after = True

        threads = []
        t_start = time.perf_counter()
        for i in range(n_req):
            pause = t_start + float(arrivals[i]) - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            if i == n_req // 3:
                # mid-storm: atomically publish the round-2 weights
                # to the watched path - the poller must pick it up
                # while the storm is still running
                checkpoint.publish_model(ck_new, publish)
            t = threading.Thread(target=fire, args=(i,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=300)
        swap_before_storm_end = srv.stats()["swaps"] >= 1
        storm_p99 = _p99(acc_lat)
        bad_scrapes.extend(validate_exposition(_scrape(port)))

        # --- leg 3: post-swap answers == cold restart on ck_new -----
        post_swap = _post(port, payload)[2].get("outputs")

        # --- leg 4: torn publish rejected, serving uninterrupted ----
        # clear first: hit counters only tick while faults are armed,
        # and the delay entries armed above mean the mid-storm publish
        # already consumed this point's hit 1
        fault.clear()
        fault.inject("swap_torn_checkpoint", "corrupt")
        try:
            checkpoint.publish_model(ck_new, publish)
        finally:
            fault.clear()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if srv.stats()["swap_rejected"] >= 1:
                break
            time.sleep(0.05)
        code, _, body = _post(port, payload)
        served_through_torn = (
            srv.stats()["swap_rejected"] >= 1 and code == 200
            and body.get("outputs") == post_swap)
        bad_scrapes.extend(validate_exposition(_scrape(port)))
        stats = srv.stop()

        tr_new = NetTrainer(dev="cpu", cfg=NET_CFG)
        with open(ck_new, "rb") as f:
            tr_new.load_model(f)
        srv2 = Server(tr_new, max_batch=8, max_wait_ms=2.0,
                      replicas=1, http_port=0)
        srv2.warmup()
        srv2.start()
        cold = _post(srv2.metrics_server.port, payload)[2].get(
            "outputs")
        srv2.stop()
        post_matches_cold = (post_swap == cold
                             and post_swap != pre_swap)
        telemetry.reset_for_tests()

        checks += [
            ("storm shed: 429s observed with Retry-After",
             storm_shed > 0 and saw_retry_after),
            ("storm accepted requests resolved (200s on both sides "
             "of the swap)", tally["200"] >= 41 and bool(acc_lat)),
            ("no 5xx / dropped requests across the storm + swap",
             tally["other"] == 0 and stats.get("errors") == 0),
            ("accepted p99 bounded: storm within 3x uncontended",
             0 < storm_p99 <= 3.0 * uncont_p99),
            ("mid-storm publish swapped live (swap event, no drain)",
             swap_before_storm_end and stats.get("swaps") == 1),
            ("post-swap answers == cold restart on the new "
             "checkpoint", post_matches_cold),
            ("torn publish rejected; serving uninterrupted",
             served_through_torn
             and stats.get("swap_rejected") == 1),
            ("every /metrics scrape exposition-valid",
             not bad_scrapes),
        ]

    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not trained:
        print("--- train stderr tail ---")
        print(train.stderr[-2000:])
    for line in bad_scrapes[:5]:
        print(f"  bad exposition line: {line}")
    with open(os.path.join(out_dir, "storm_summary.json"), "w") as f:
        json.dump({"codes": tally, "uncontended_p99_ms": uncont_p99,
                   "storm_p99_ms": storm_p99,
                   "server_stats": stats}, f, indent=1, default=str)
    print(f"serve_http_smoke: {'PASS' if ok else 'FAIL'} "
          f"(codes {tally}, p99 uncontended {uncont_p99:.1f}ms "
          f"storm {storm_p99:.1f}ms)")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: serve_http_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="serve_http_smoke_")
        rc = run_smoke(d)
        print(f"serve_http_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
