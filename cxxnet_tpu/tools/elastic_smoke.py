#!/usr/bin/env python3
"""elastic_smoke: the elastic-pod end-to-end proof (CI: elastic-smoke).

    env JAX_PLATFORMS=cpu python -m cxxnet_tpu.tools.elastic_smoke --out DIR

Drives the full product surface - the elastic supervisor
(parallel/elastic.py) over real ``python -m cxxnet_tpu.main`` worker
processes on the CPU/gloo backend - through a deterministic worker
murder, and asserts the whole robustness story of
docs/FAULT_TOLERANCE.md "Elastic pod":

1. a 3-process pod trains with coordinated checkpoint barriers; the
   ``collective:kill_rank=0@K`` injector kills the LEADER mid-round;
2. the supervisor reshapes: generation 1 runs with the 2 surviving
   members, a NEW leader (lowest live member) is elected, and training
   continues from the published rollback checkpoint to completion;
3. exactly ONE process published every checkpoint (manifest + event
   logs + no orphan ``*.tmp`` in the model dir);
4. the final checkpoint is byte-identical (sha256) to an UNINTERRUPTED
   2-process run resumed from the same rollback checkpoint, and the
   per-round eval lines after the rollback match line for line - the
   reshape cost one rolled-back round, not correctness.

Run in fresh subprocesses by construction (every worker is its own
process): the long-lived many-jit jax-cpu SIGSEGV pattern and the rare
device_put segfault flake (PR 1 / PR 6 precedent) never share a
process with the assertions here.
"""

from __future__ import annotations

import glob
import gzip
import hashlib
import json
import os
import re
import shutil
import struct
import sys
from typing import Dict, List


def _write_dataset(dirname: str, n: int = 48) -> Dict[str, str]:
    """Tiny deterministic MNIST-format dataset (same recipe as the
    distributed CLI tests)."""
    import numpy as np
    rng = np.random.RandomState(7)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    images = rng.randint(0, 255, size=(n, 12, 12)).astype(np.uint8)
    os.makedirs(dirname, exist_ok=True)
    img = os.path.join(dirname, "img.gz")
    lbl = os.path.join(dirname, "lbl.gz")
    with gzip.open(img, "wb") as f:
        f.write(struct.pack(">iiii", 2051, n, 12, 12))
        f.write(images.tobytes())
    with gzip.open(lbl, "wb") as f:
        f.write(struct.pack(">ii", 2049, n))
        f.write(labels.tobytes())
    return {"img": img, "lbl": lbl}


CONF = """
data = train
iter = mnist
    path_img = "{img}"
    path_label = "{lbl}"
    input_flat = 1
iter = end
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 10
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,144
random_type = xavier
batch_size = 24
eta = 0.1
momentum = 0.9
num_round = {rounds}
max_round = {rounds}
save_model = 1
metric = error
eval_train = 1
dev = cpu
silent = 1
model_dir = {model_dir}
barrier_secs = 60
leader_lease_secs = 5
elastic_nproc = {nproc}
elastic_respawn = {respawn}
elastic_stale_secs = 0
elastic_absence_secs = 0
{extra}
"""


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _run_pod(conf_path: str) -> int:
    from cxxnet_tpu.parallel.elastic import ElasticPod
    return ElasticPod(conf_path).run()


def _events(coord_dir: str) -> List[Dict]:
    out = []
    for path in sorted(glob.glob(os.path.join(coord_dir,
                                              "events.*.jsonl"))):
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


def _eval_lines(coord_dir: str) -> Dict[int, str]:
    """round -> eval stderr line, from the worker logs (any member's
    copy; every member prints the same line for the same round)."""
    out: Dict[int, str] = {}
    for path in sorted(glob.glob(os.path.join(coord_dir,
                                              "worker.*.log"))):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                m = re.match(r"^\[(\d+)\]\ttrain-error:", line)
                if m:
                    out[int(m.group(1))] = line.rstrip("\n")
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    out = "elastic-smoke-out"
    nproc, rounds, kill_hit = 3, 6, 7
    i = 0
    while i < len(argv):
        if argv[i] == "--out":
            out = argv[i + 1]
            i += 2
        elif argv[i] == "--nproc":
            nproc = int(argv[i + 1])
            i += 2
        elif argv[i] == "--rounds":
            rounds = int(argv[i + 1])
            i += 2
        else:
            print(__doc__)
            return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # one CPU device per worker: the pytest parent's virtual-device
    # XLA_FLAGS must not leak into the pod
    os.environ["XLA_FLAGS"] = ""
    os.makedirs(out, exist_ok=True)
    data = _write_dataset(os.path.join(out, "data"))

    # ---- run A: the interrupted pod ------------------------------------
    # 2 dispatches per round (48 samples / batch 24); member 0 (the
    # generation-0 leader) dies at collective hit `kill_hit` - mid
    # round ceil(kill_hit/2), after rounds 1..ceil-1 published
    dir_a = os.path.join(out, "run_a")
    conf_a = os.path.join(out, "a.conf")
    with open(conf_a, "w") as f:
        f.write(CONF.format(
            img=data["img"], lbl=data["lbl"], rounds=rounds,
            model_dir=dir_a, nproc=nproc, respawn=0,
            extra=('elastic_fault = '
                   f'"collective:kill_rank=0@{kill_hit}"')))
    print(f"elastic-smoke: run A ({nproc}-process pod, leader killed "
          f"at collective hit {kill_hit})")
    rc = _run_pod(conf_a)
    assert rc == 0, f"interrupted pod did not recover: rc={rc}"

    coord_a = os.path.join(dir_a, "coord")
    events = _events(coord_a)
    gens = {e["generation"]: e for e in events
            if e["kind"] == "generation_start"}
    assert len(gens) >= 2, f"no reshape happened: {sorted(gens)}"
    g0, g1 = gens[0]["members"], gens[1]["members"]
    assert len(g1) == nproc - 1 and 0 not in g1, \
        f"expected N-1 reshape without member 0: g0={g0} g1={g1}"
    print(f"elastic-smoke: reshape ok: generation 0 {g0} -> "
          f"generation 1 {g1}")

    # leader re-election: generation-0 barriers led by member 0,
    # generation-1 barriers led by the lowest survivor
    leaders = {(e["generation"], e["leader"]) for e in events
               if e["kind"] == "barrier"}
    assert (0, 0) in leaders, f"gen-0 leader was not member 0: {leaders}"
    assert (1, min(g1)) in leaders, \
        f"gen-1 leader was not re-elected to {min(g1)}: {leaders}"
    print(f"elastic-smoke: leader re-election ok: 0 -> {min(g1)}")

    # single-publisher: exactly one publish event per round, and the
    # checkpoint dir holds no orphan tmp files
    pubs: Dict[int, List[Dict]] = {}
    for e in events:
        if e["kind"] == "publish":
            pubs.setdefault(e["round"], []).append(e)
    for rnd, recs in sorted(pubs.items()):
        assert len(recs) == 1, \
            f"round {rnd} published by {len(recs)} writers: {recs}"
    assert not glob.glob(os.path.join(dir_a, "*.tmp")), \
        "orphan .tmp files in the checkpoint dir"
    for rnd in range(rounds + 1):
        assert rnd in pubs, f"round {rnd} never published: {sorted(pubs)}"
    # the generation-0 publishes stop at the rollback point
    g0_pubs = [r for r, recs in pubs.items()
               if recs[0]["who"] == "m0"]
    rollback = max(g0_pubs)
    assert rollback < rounds, "the kill round was published?!"
    print(f"elastic-smoke: single-publisher ok "
          f"({len(pubs)} rounds); rollback point = round {rollback}")

    # ---- run B: uninterrupted N-1 run from the rollback point ----------
    dir_b = os.path.join(out, "run_b")
    os.makedirs(dir_b, exist_ok=True)
    shutil.copy(os.path.join(dir_a, f"{rollback:04d}.model"),
                os.path.join(dir_b, f"{rollback:04d}.model"))
    conf_b = os.path.join(out, "b.conf")
    with open(conf_b, "w") as f:
        f.write(CONF.format(
            img=data["img"], lbl=data["lbl"], rounds=rounds,
            model_dir=dir_b, nproc=nproc - 1, respawn=0, extra=""))
    print(f"elastic-smoke: run B (uninterrupted {nproc - 1}-process "
          f"pod from round {rollback})")
    rc = _run_pod(conf_b)
    assert rc == 0, f"reference pod failed: rc={rc}"

    # ---- the equivalence proof -----------------------------------------
    final_a = os.path.join(dir_a, f"{rounds:04d}.model")
    final_b = os.path.join(dir_b, f"{rounds:04d}.model")
    sha_a, sha_b = _sha256(final_a), _sha256(final_b)
    assert sha_a == sha_b, (
        f"final checkpoints diverge: interrupted {sha_a} vs "
        f"uninterrupted {sha_b}")
    ev_a = _eval_lines(coord_a)
    ev_b = _eval_lines(os.path.join(dir_b, "coord"))
    for rnd in range(rollback + 1, rounds + 1):
        assert rnd in ev_a and rnd in ev_b, \
            f"missing eval line for round {rnd}"
        assert ev_a[rnd] == ev_b[rnd], (
            f"loss trajectory diverges at round {rnd}: "
            f"{ev_a[rnd]!r} vs {ev_b[rnd]!r}")
    print(f"elastic-smoke: final checkpoint sha256 identical "
          f"({sha_a[:16]}...), eval lines for rounds "
          f"{rollback + 1}..{rounds} match")

    summary = {
        "nproc": nproc, "rounds": rounds, "kill_hit": kill_hit,
        "generations": {str(g): gens[g]["members"] for g in gens},
        "rollback_round": rollback, "final_sha256": sha_a,
        "manifest": json.load(open(os.path.join(coord_a,
                                                "published.json"))),
    }
    with open(os.path.join(out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print("elastic-smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
