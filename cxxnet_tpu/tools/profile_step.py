"""Capture + summarize a device profile of the AlexNet train step.

The reference exposes wall-clock timing only (cxxnet_main.cpp's elapsed
prints); the TPU-native replacement is a real device trace:
`jax.profiler` captures an XSpace, and this tool aggregates per-op
device time so "where does the step go" is a committed number, not a
guess (VERDICT r2 weak #3). Output: top-N ops by self time + total
step accounting, printed and optionally written as markdown.

Usage:
  python -m cxxnet_tpu.tools.profile_step [--steps N] [--out FILE.md]
                                          [--trace-dir DIR]

Runs the same end-to-end loop bench.py times (trainer.update on host
batches), wrapped in jax.profiler.start_trace/stop_trace, then parses
the .xplane.pb with jax.profiler.ProfileData.
"""

from __future__ import annotations

import glob
import os
import sys
import tempfile
from collections import defaultdict


def capture(trace_dir: str, steps: int = 20) -> str:
    """Run bench.py's e2e loop under the profiler; returns the xplane
    path. Reuses the exact harness the headline number comes from so the
    trace explains the benchmark, not a lookalike loop."""
    import jax
    try:
        import bench
        from __graft_entry__ import _ALEXNET_CONF, _make_trainer
    except ImportError as e:
        raise RuntimeError(
            "profile_step reuses the repo-root bench.py harness; run it "
            "from a source checkout root (bench/__graft_entry__ are not "
            "packaged)") from e
    from cxxnet_tpu.utils.config import parse_config_file
    from cxxnet_tpu.utils.platform import ensure_env_platform

    ensure_env_platform()
    platform = jax.devices()[0].platform
    batch = 256 if platform != "cpu" else 8
    trainer = _make_trainer(
        parse_config_file(_ALEXNET_CONF),
        [("batch_size", str(batch)), ("dev", "tpu"), ("silent", "1"),
         ("eval_train", "0"), ("save_model", "0")])
    ips, n = bench._measure_e2e(trainer, batch, steps, trace_dir)
    print(f"traced {n} steps at {ips:.1f} images/sec")

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    return max(paths, key=os.path.getmtime)


def op_table(xplane_path: str, top: int = 25):
    """Aggregate device-plane op self-times from an XSpace dump.
    Returns ([(op_name, total_ns)] sorted desc, total_ns) - the data
    behind summarize(), reused by bench.py's compact top_ops field."""
    from jax.profiler import ProfileData
    data = ProfileData.from_file(xplane_path)
    dev_planes = [p for p in data.planes if "/device:" in p.name]
    if not dev_planes:  # CPU runs put XLA ops on the host plane
        dev_planes = [p for p in data.planes if p.name == "/host:CPU"]
    op_time = defaultdict(float)
    total = 0.0
    for plane in dev_planes:
        # a device plane carries parallel lines (Steps / XLA Modules /
        # XLA Ops) covering the same wall time - summing all of them
        # would triple-count; the "XLA Ops" line holds the leaf op
        # self-times. Host planes (CPU smoke runs) have thread lines
        # only, which don't nest the same way.
        lines = [l for l in plane.lines if l.name == "XLA Ops"] \
            or list(plane.lines)
        for line in lines:
            for ev in line.events:
                dur = ev.duration_ns
                name = ev.name
                op_time[name] += dur
                total += dur
    return sorted(op_time.items(), key=lambda kv: -kv[1])[:top], total


def summarize(xplane_path: str, top: int = 25) -> str:
    """Markdown table of op_table()."""
    rows, total = op_table(xplane_path, top)
    out = ["| op | total ms | % of device time |",
           "|---|---|---|"]
    for name, ns in rows:
        out.append(f"| `{name[:70]}` | {ns / 1e6:.2f} | "
                   f"{100.0 * ns / max(total, 1):.1f}% |")
    out.append(f"\nTotal accounted {total / 1e6:.1f} ms")
    return "\n".join(out)


def main(argv) -> int:
    steps = 20
    out_file = ""
    trace_dir = ""
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    if "--out" in argv:
        out_file = argv[argv.index("--out") + 1]
    if "--trace-dir" in argv:
        trace_dir = argv[argv.index("--trace-dir") + 1]
    tmp = trace_dir or tempfile.mkdtemp(prefix="cxn_profile_")
    xplane = capture(tmp, steps)
    md = summarize(xplane)
    print(md)
    if out_file:
        with open(out_file, "w") as fo:
            fo.write("# AlexNet train-step device profile\n\n"
                     f"Captured from `{xplane}`, {steps} steps.\n\n"
                     + md + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
