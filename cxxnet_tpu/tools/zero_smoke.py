"""ZeRO smoke: zero_stage=2/3 vs zero_stage=0 must be the SAME run.

    python -m cxxnet_tpu.tools.zero_smoke [--out DIR] [--keep]

Trains the tiny synthetic-MNIST MLP through the real CLI
(`python -m cxxnet_tpu.main`) on an 8-FAKE-DEVICE CPU mesh
(`--xla_force_host_platform_device_count=8`, `mesh=data:8`) four
times - replicated baseline (zero_stage=0), ZeRO-2, ZeRO-2 fused with
steps_per_dispatch=4 (chunked staging + the round-boundary short
chunk), and ZeRO-3 - then asserts:

- every run's final checkpoint has the SAME sha256 as the stage-0
  baseline: reduce-scatter + sharded update + all-gather is bitwise
  the replicated update (docs/parallel.md), and stage 3's
  gather-on-save keeps the checkpoint byte-compatible;
- identical per-round eval lines on stderr for every run.

All children run under `--xla_cpu_use_thunk_runtime=false` - the same
scoped pin the fused-dispatch smoke uses: the thunk runtime's codegen
picks different float contractions per program shape (~1 ULP between
the replicated and zero-region executables), which is backend noise,
not a sharding-path property. Exit 0 iff all checks pass.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import tempfile

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
mesh = data:8
save_model = 1
save_optimizer = 1
num_round = 3
max_round = 3
eta = 0.3
metric = error
eval_train = 1
silent = 1
"""


def _run_cli(out_dir: str, tag: str, overrides) -> dict:
    """One `python -m cxxnet_tpu.main` child; returns its artifacts."""
    mdir = os.path.join(out_dir, f"models_{tag}")
    flags = [t for t in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in t
             and "xla_cpu_use_thunk_runtime" not in t]
    flags += ["--xla_force_host_platform_device_count=8",
              "--xla_cpu_use_thunk_runtime=false"]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=" ".join(flags))
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, "zero_smoke.conf"),
         f"model_dir={mdir}"] + list(overrides),
        env=env, capture_output=True, text=True, timeout=540)
    path = os.path.join(mdir, "0003.model")
    sha = ""
    if os.path.exists(path):
        with open(path, "rb") as f:
            sha = hashlib.sha256(f.read()).hexdigest()
    return {
        "rc": r.returncode, "stderr": r.stderr, "sha": sha,
        "evals": [ln for ln in r.stderr.splitlines()
                  if ln.startswith("[")],
    }


def run_smoke(out_dir: str) -> int:
    # 288 instances = 9 batches/round at b32, so the K=4 variant chunks
    # as 4+4+1 and every round crosses the short-chunk path too
    write_synth_mnist(out_dir, 288, 0, "train")
    write_synth_mnist(out_dir, 64, 1, "test")
    with open(os.path.join(out_dir, "zero_smoke.conf"), "w") as f:
        f.write(CONF.format(d=out_dir))

    runs = {
        "z0": _run_cli(out_dir, "z0", ["zero_stage=0"]),
        "z2": _run_cli(out_dir, "z2", ["zero_stage=2"]),
        "z2k4": _run_cli(out_dir, "z2k4",
                         ["zero_stage=2", "steps_per_dispatch=4"]),
        "z3": _run_cli(out_dir, "z3", ["zero_stage=3"]),
    }
    base = runs["z0"]
    checks = [(f"{tag} run completed", r["rc"] == 0 and bool(r["sha"]))
              for tag, r in runs.items()]
    checks += [
        (f"{tag} final checkpoint sha256 == zero_stage=0",
         bool(base["sha"]) and r["sha"] == base["sha"])
        for tag, r in runs.items() if tag != "z0"]
    checks += [
        (f"{tag} per-round eval lines == zero_stage=0",
         len(base["evals"]) == 3 and r["evals"] == base["evals"])
        for tag, r in runs.items() if tag != "z0"]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not ok:
        for tag, r in runs.items():
            if r["rc"] != 0:
                print(f"--- {tag} stderr tail ---")
                print(r["stderr"][-2000:])
    shas = {tag: r["sha"][:12] for tag, r in runs.items()}
    print(f"zero_smoke: {'PASS' if ok else 'FAIL'} {shas}")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: zero_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="zero_smoke_")
        rc = run_smoke(d)
        print(f"zero_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
