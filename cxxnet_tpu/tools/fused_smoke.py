"""Fused-dispatch smoke: K=4 vs K=1 must be the SAME training run.

    python -m cxxnet_tpu.tools.fused_smoke [--out DIR] [--keep]

Trains the tiny synthetic-MNIST MLP twice through the real CLI
(`python -m cxxnet_tpu.main`) - once streamed (steps_per_dispatch=1)
and once fused (steps_per_dispatch=4, exercising the chunked staging
prefetcher, the jitted scan, and the round-boundary short chunk) -
with telemetry armed, then asserts:

- identical final checkpoint SHA-256 (the bitwise trajectory-equality
  acceptance proof of docs/PERFORMANCE.md at the product surface);
- identical per-round eval lines on stderr;
- the fused run's event stream carries `train.chunk` spans with
  per-microstep loss vectors.

Both children run under `--xla_cpu_use_thunk_runtime=false`: the
thunk runtime's codegen picks different float contractions per
program shape (~1 ULP between the per-step and fused executables),
which is backend noise, not a dispatch-path property - see
docs/PERFORMANCE.md. Exit 0 iff all checks pass; CI uploads the
produced JSONL streams next to the telemetry-smoke artifacts.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import tempfile

from cxxnet_tpu.tools.telemetry_smoke import write_synth_mnist

CONF = """
data = train
iter = mnist
    path_img = "{d}/train-img.gz"
    path_label = "{d}/train-lbl.gz"
    shuffle = 1
iter = end
eval = test
iter = mnist
    path_img = "{d}/test-img.gz"
    path_label = "{d}/test-lbl.gz"
iter = end

netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1:sg1] = tanh
layer[sg1->fc2] = fullc:fc2
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end

input_shape = 1,1,36
batch_size = 32
dev = cpu
save_model = 1
num_round = 3
max_round = 3
eta = 0.3
metric = error
eval_train = 1
silent = 1
"""


def _run_cli(out_dir: str, tag: str, k: int) -> dict:
    """One `python -m cxxnet_tpu.main` child; returns its artifacts."""
    mdir = os.path.join(out_dir, f"models_{tag}")
    log = os.path.join(out_dir, f"events_{tag}.jsonl")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        # append, don't replace: inherited flags (device counts,
        # memory fractions) must keep applying to the children
        XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                   + " --xla_cpu_use_thunk_runtime=false").strip())
    r = subprocess.run(
        [sys.executable, "-m", "cxxnet_tpu.main",
         os.path.join(out_dir, "fused_smoke.conf"),
         f"model_dir={mdir}", f"steps_per_dispatch={k}",
         f"log_file={log}",
         f"metrics_file={os.path.join(out_dir, f'metrics_{tag}.jsonl')}"],
        env=env, capture_output=True, text=True, timeout=540)
    path = os.path.join(mdir, "0003.model")
    sha = ""
    if os.path.exists(path):
        with open(path, "rb") as f:
            sha = hashlib.sha256(f.read()).hexdigest()
    return {
        "rc": r.returncode, "stderr": r.stderr, "sha": sha,
        "log": log,
        "evals": [l for l in r.stderr.splitlines()
                  if l.startswith("[")],
    }


def run_smoke(out_dir: str) -> int:
    from cxxnet_tpu.telemetry.sink import read_jsonl
    # 288 instances = 9 batches/round at b32: K=4 chunks as 4+4+1, so
    # every round exercises the round-boundary SHORT chunk too
    write_synth_mnist(out_dir, 288, 0, "train")
    write_synth_mnist(out_dir, 64, 1, "test")
    with open(os.path.join(out_dir, "fused_smoke.conf"), "w") as f:
        f.write(CONF.format(d=out_dir))

    streamed = _run_cli(out_dir, "k1", 1)
    fused = _run_cli(out_dir, "k4", 4)
    chunks = []
    if os.path.exists(fused["log"]):
        chunks = [e for e in read_jsonl(fused["log"])
                  if e.get("kind") == "span"
                  and e.get("name") == "train.chunk"]
    checks = [
        ("K=1 run completed", streamed["rc"] == 0 and streamed["sha"]),
        ("K=4 run completed", fused["rc"] == 0 and fused["sha"]),
        ("identical final checkpoint sha256",
         bool(streamed["sha"]) and streamed["sha"] == fused["sha"]),
        ("identical per-round eval lines",
         len(streamed["evals"]) == 3
         and streamed["evals"] == fused["evals"]),
        ("fused run emitted train.chunk spans (3 rounds x 4+4+1)",
         len(chunks) == 9),
        ("chunk spans carry per-microstep losses",
         bool(chunks)
         and all(len(c.get("loss", [])) == c.get("steps")
                 for c in chunks)),
        ("round-boundary short chunk present",
         sum(1 for c in chunks if c.get("steps") == 1) == 3),
    ]
    ok = True
    for label, passed in checks:
        print(f"  [{'ok' if passed else 'FAIL'}] {label}")
        ok = ok and bool(passed)
    if not ok:
        for tag, run in (("k1", streamed), ("k4", fused)):
            if run["rc"] != 0:
                print(f"--- {tag} stderr tail ---")
                print(run["stderr"][-2000:])
    print(f"fused_smoke: {'PASS' if ok else 'FAIL'} "
          f"(sha {streamed['sha'][:12]} vs {fused['sha'][:12]}, "
          f"{len(chunks)} chunk spans)")
    return 0 if ok else 1


def main() -> int:
    args = sys.argv[1:]
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            print("usage: fused_smoke [--out DIR] [--keep]")
            return 2
        out = args[i + 1]
        os.makedirs(out, exist_ok=True)
        return run_smoke(out)
    if "--keep" in args:
        d = tempfile.mkdtemp(prefix="fused_smoke_")
        rc = run_smoke(d)
        print(f"fused_smoke: artifacts kept in {d}")
        return rc
    with tempfile.TemporaryDirectory() as d:
        return run_smoke(d)


if __name__ == "__main__":
    sys.exit(main())
