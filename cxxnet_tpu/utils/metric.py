"""Evaluation metrics.

Behavioral parity with the reference metrics (src/utils/metric.h:20-236),
vectorized over the batch with numpy instead of per-instance loops:

- ``error``:   argmax(pred) != label[0]; when pred has a single column the
  decision is ``pred > 0`` (metric.h:91-110).
- ``rmse``:    per-instance SUM of squared differences across the output
  dimension, averaged over instances. NOTE: despite its name the reference
  never takes a square root (metric.h:72-88 CalcMetric returns the squared
  sum and Get() divides by instance count only) - we reproduce that exactly.
- ``logloss``: -log(p[target]) clipped to [1e-15, 1-1e-15]; binary form for
  single-column predictions (metric.h:113-132).
- ``rec@n``:   fraction of the instance's labels found in the top-n
  predictions (metric.h:135-177). The reference randomly shuffles before the
  stable sort so ties are broken randomly; we add a tiny random jitter key
  for the same effect.

MetricSet mirrors src/utils/metric.h:175-236 + the trainer-side parsing of
``metric = name`` and ``metric[label_name,node_name] = name``
(nnet_impl-inl.hpp:57-67): each metric is bound to a label field name and
Print renders ``\\t{evname}-{metric}[{field}]:{value}`` (field suffix omitted
for the default "label" field).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class Metric:
    """Accumulating metric over batches of (pred, label) numpy arrays."""

    name: str

    def __init__(self, name: str):
        self.name = name
        self.clear()

    def clear(self) -> None:
        self._sum = 0.0
        self._cnt = 0

    def add_eval(self, pred: np.ndarray, label: np.ndarray,
                 mask: Optional[np.ndarray] = None) -> None:
        """Accumulate over a batch.

        pred: (n, k) prediction scores; label: (n, label_width);
        mask: optional (n,) boolean selecting valid (non-padding) rows.
        """
        pred = np.asarray(pred)
        label = np.asarray(label)
        if pred.ndim == 1:
            pred = pred[:, None]
        if label.ndim == 1:
            label = label[:, None]
        if mask is not None:
            mask = np.asarray(mask).astype(bool)
            pred, label = pred[mask], label[mask]
        if pred.shape[0] == 0:
            return
        vals = self._calc(pred.astype(np.float64), label.astype(np.float64))
        self._sum += float(np.sum(vals))
        self._cnt += int(pred.shape[0])

    def get(self) -> float:
        return self._sum / self._cnt if self._cnt else float("nan")

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class MetricError(Metric):
    def __init__(self) -> None:
        super().__init__("error")

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        if pred.shape[1] == 1:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        else:
            maxidx = np.argmax(pred, axis=1)
        return (maxidx != label[:, 0].astype(np.int64)).astype(np.float64)


class MetricRMSE(Metric):
    def __init__(self) -> None:
        super().__init__("rmse")

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        if pred.shape != label.shape:
            raise ValueError(
                "rmse metric requires pred and label of identical shape")
        diff = pred - label
        return np.sum(diff * diff, axis=1)


class MetricLogloss(Metric):
    def __init__(self) -> None:
        super().__init__("logloss")

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        eps = 1e-15
        if pred.shape[1] == 1:
            p = np.clip(pred[:, 0], eps, 1.0 - eps)
            y = label[:, 0]
            return -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        target = label[:, 0].astype(np.int64)
        p = np.clip(pred[np.arange(pred.shape[0]), target], eps, 1.0 - eps)
        return -np.log(p)


class MetricRecall(Metric):
    """rec@n: fraction of labels recalled in the top-n predictions."""

    def __init__(self, name: str):
        if not name.startswith("rec@"):
            raise ValueError("must specify n for rec@n")
        self.topn = int(name[4:])
        self._rng = np.random.RandomState(0)
        super().__init__(name)

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        n, k = pred.shape
        if k < self.topn:
            raise ValueError(
                f"rec@{self.topn} meaningless for prediction list of size {k}")
        # random tie-break (reference shuffles before sorting)
        jitter = self._rng.uniform(0.0, 1.0, size=pred.shape)
        order = np.lexsort((jitter, -pred), axis=1)
        top = order[:, :self.topn]  # (n, topn) candidate indices
        labels = label.astype(np.int64)  # (n, label_width)
        hits = (top[:, :, None] == labels[:, None, :]).any(axis=1)
        return hits.sum(axis=1) / labels.shape[1]


def create_metric(name: str) -> Metric:
    if name == "rmse":
        return MetricRMSE()
    if name == "error":
        return MetricError()
    if name == "logloss":
        return MetricLogloss()
    if name.startswith("rec@"):
        return MetricRecall(name)
    raise ValueError(f"Metric: unknown metric name: {name}")


class MetricSet:
    """A set of metrics, each bound to a label field name."""

    def __init__(self) -> None:
        self._metrics: List[Metric] = []
        self._fields: List[str] = []

    def add_metric(self, name: str, field: str = "label") -> None:
        self._metrics.append(create_metric(name))
        self._fields.append(field)

    def __len__(self) -> int:
        return len(self._metrics)

    @property
    def fields(self) -> List[str]:
        return list(self._fields)

    @property
    def specs(self) -> List[tuple]:
        """[(metric_name, label_field)] in declaration order."""
        return [(m.name, f) for m, f in zip(self._metrics, self._fields)]

    def clear(self) -> None:
        for m in self._metrics:
            m.clear()

    def add_eval(self, preds: List[np.ndarray], labels: dict,
                 mask: Optional[np.ndarray] = None) -> None:
        """preds: one prediction array per metric; labels: field -> array."""
        if len(preds) != len(self._metrics):
            raise ValueError(
                "Metric: number of prediction arrays must equal "
                "number of metrics")
        for m, field, pred in zip(self._metrics, self._fields, preds):
            if field not in labels:
                raise KeyError(f"Metric: unknown target = {field}")
            m.add_eval(pred, labels[field], mask=mask)

    def print(self, evname: str) -> str:
        out = []
        for m, field in zip(self._metrics, self._fields):
            tag = f"{evname}-{m.name}"
            if field != "label":
                tag += f"[{field}]"
            out.append(f"\t{tag}:{m.get():g}")
        return "".join(out)
