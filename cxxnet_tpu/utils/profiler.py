"""Step/pipeline profiler - an observability subsystem the reference
lacks (SURVEY.md par.5: "no per-op timing, no profiler hooks"; it only
prints wall-clock round times, cxxnet_main.cpp:376-387).

Two levels:
- `profile = 1`: per-round summaries of device step time vs host data
  time (p50/p99/images-per-sec), printed to stderr next to the metrics.
- `profile_dir = <path>`: additionally dumps an XLA/TensorBoard trace
  via jax.profiler for the first profiled round (op-level timeline on
  TPU; view with tensorboard or xprof).
"""

from __future__ import annotations

from typing import List

import numpy as np


class StepProfiler:
    """Accumulates step + data timings for one round at a time."""

    def __init__(self, trace_dir: str = ""):
        self.trace_dir = trace_dir
        self._tracing = False
        self._traced_once = False
        self.reset()

    def reset(self) -> None:
        self.step_s: List[float] = []
        self.data_s: List[float] = []
        self.examples = 0

    # -- hooks -------------------------------------------------------------
    def round_start(self) -> None:
        self.reset()
        if self.trace_dir and not self._traced_once:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def round_end(self) -> None:
        if self._tracing:
            import jax
            jax.profiler.stop_trace()
            self._tracing = False
            self._traced_once = True

    def add_step(self, seconds: float, n_examples: int) -> None:
        self.step_s.append(seconds)
        self.examples += n_examples

    def add_data(self, seconds: float) -> None:
        self.data_s.append(seconds)

    # -- reporting ---------------------------------------------------------
    def summary(self) -> str:
        if not self.step_s:
            return "\tprofile: no steps"
        s = np.asarray(self.step_s)
        total = s.sum() + sum(self.data_s)
        ips = self.examples / total if total > 0 else float("nan")
        out = (f"\tprofile: {len(s)} steps, "
               f"step p50 {np.percentile(s, 50) * 1e3:.2f} ms "
               f"p99 {np.percentile(s, 99) * 1e3:.2f} ms, "
               f"data {sum(self.data_s) * 1e3:.1f} ms total, "
               f"{ips:.1f} images/sec")
        if self.trace_dir:
            out += f", trace -> {self.trace_dir}"
        return out
