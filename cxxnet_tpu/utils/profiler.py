"""Step/pipeline profiler - an observability subsystem the reference
lacks (SURVEY.md par.5: "no per-op timing, no profiler hooks"; it only
prints wall-clock round times, cxxnet_main.cpp:376-387).

Two levels:
- `profile = 1`: per-round summaries of device step time vs host data
  time (p50/p99/images-per-sec), printed to stderr next to the metrics.
- `profile_dir = <path>`: additionally dumps an XLA/TensorBoard trace
  via jax.profiler for ONE profiled round (op-level timeline on TPU;
  view with tensorboard or xprof). `trace_round = N` selects WHICH
  profiled round is traced (1-based, default 1: the first) - round 1
  is dominated by XLA compilation, so steady-state traces want N >= 2.

The telemetry subsystem (cxxnet_tpu/telemetry) reuses this accumulator
for its per-round stats records even when profile=0; see
NetTrainer.round_stats and docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class StepProfiler:
    """Accumulates step + data timings for one round at a time."""

    def __init__(self, trace_dir: str = "", trace_round: int = 1):
        self.trace_dir = trace_dir
        # which profiled round gets the jax.profiler trace (1-based
        # count of round_start calls); exactly one round is ever traced
        self.trace_round = max(1, int(trace_round))
        self._round_idx = 0
        self._tracing = False
        self._traced_once = False
        self.reset()

    def reset(self) -> None:
        self.step_s: List[float] = []
        self.data_s: List[float] = []
        self.examples = 0

    # -- hooks -------------------------------------------------------------
    def round_start(self) -> None:
        self.reset()
        self._round_idx += 1
        if (self.trace_dir and not self._traced_once
                and self._round_idx == self.trace_round):
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def round_end(self) -> None:
        if self._tracing:
            import jax
            jax.profiler.stop_trace()
            self._tracing = False
            self._traced_once = True

    def add_step(self, seconds: float, n_examples: int) -> None:
        self.step_s.append(seconds)
        self.examples += n_examples

    def add_data(self, seconds: float) -> None:
        self.data_s.append(seconds)

    def add_chunk(self, seconds: float, n_steps: int,
                  n_examples: int) -> None:
        """One fused dispatch of n_steps microsteps (trainer
        update_chunk): recorded as n_steps equal per-step entries so
        stats()/summary() keep reporting PER-STEP p50/p99 and
        images/sec comparable across steps_per_dispatch settings
        (the chunk total is preserved: sum == seconds)."""
        n = max(1, int(n_steps))
        per = seconds / n
        self.step_s.extend([per] * n)
        self.examples += n_examples

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Optional[Dict[str, float]]:
        """Round stats as a JSON-ready dict (None when no steps ran).
        Robust to an empty data_s (staged/membuffer paths can deliver
        rounds with zero recorded host-data time) and to zero counted
        examples (test_io rounds)."""
        if not self.step_s:
            return None
        s = np.asarray(self.step_s, dtype=np.float64)
        data_total = float(sum(self.data_s))
        total = float(s.sum()) + data_total
        return {
            "steps": len(self.step_s),
            "examples": self.examples,
            "step_p50_ms": float(np.percentile(s, 50)) * 1e3,
            "step_p99_ms": float(np.percentile(s, 99)) * 1e3,
            "step_total_s": float(s.sum()),
            "data_total_ms": data_total * 1e3,
            "images_per_sec": (self.examples / total if total > 0
                               else float("nan")),
        }

    def summary(self) -> str:
        st = self.stats()
        if st is None:
            return "\tprofile: no steps"
        out = (f"\tprofile: {st['steps']} steps, "
               f"step p50 {st['step_p50_ms']:.2f} ms "
               f"p99 {st['step_p99_ms']:.2f} ms, "
               f"data {st['data_total_ms']:.1f} ms total, "
               f"{st['images_per_sec']:.1f} images/sec")
        if self.trace_dir:
            out += f", trace -> {self.trace_dir}"
        return out
