"""Train metrics computed inside the jitted train step.

The reference computes train-metrics from the forward pass it already ran
(nnet_impl-inl.hpp:174-180) without any extra device sync. The round-1
trainer instead read the eval nodes back to the host every step
(fetch_local per batch), serializing the device. These are the same
metric formulas as utils/metric.py (behavioral parity with
src/utils/metric.h:20-236) expressed as jnp ops so the accumulation
lives ON DEVICE: each metric contributes a (sum, count) pair that the
train step adds into a carried `(n_metrics, 2)` float32 accumulator;
the host reads it back once per round (or print_step), not per batch.

Masking: padded rows (validity mask == 0) contribute to neither sum nor
count, matching MetricSet.add_eval(mask=...).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

StepFn = Callable[[jax.Array, jax.Array, jax.Array, jax.Array],
                  Tuple[jax.Array, jax.Array]]


def _masked(vals: jax.Array, mask: jax.Array):
    """(sum over valid rows, number of valid rows)."""
    m = mask > 0
    return (jnp.sum(jnp.where(m, vals, 0.0)),
            jnp.sum(m.astype(jnp.float32)))


def _error(pred, label, mask, rng):
    """argmax != label[:,0]; single column decides by pred>0
    (metric.h:91-110)."""
    if pred.shape[1] == 1:
        maxidx = (pred[:, 0] > 0.0).astype(jnp.int32)
    else:
        maxidx = jnp.argmax(pred, axis=1).astype(jnp.int32)
    wrong = (maxidx != label[:, 0].astype(jnp.int32)).astype(jnp.float32)
    return _masked(wrong, mask)


def _rmse(pred, label, mask, rng):
    """Per-row SUM of squared differences, no sqrt (the reference quirk,
    metric.h:72-88)."""
    if pred.shape != label.shape:
        raise ValueError(
            "rmse metric requires pred and label of identical shape")
    diff = pred - label
    return _masked(jnp.sum(diff * diff, axis=1), mask)


def _logloss(pred, label, mask, rng):
    # the host path clips p to [eps, 1-eps] in float64; in float32
    # 1-1e-15 rounds to 1.0, so clip each log argument instead - a
    # saturated p==1.0 then yields log(clip(1-p)) = log(eps), not -inf
    eps = 1e-15
    if pred.shape[1] == 1:
        p = pred[:, 0]
        y = label[:, 0]
        vals = -(y * jnp.log(jnp.clip(p, eps, 1.0))
                 + (1.0 - y) * jnp.log(jnp.clip(1.0 - p, eps, 1.0)))
    else:
        target = label[:, 0].astype(jnp.int32)
        p = jnp.take_along_axis(pred, target[:, None], axis=1)[:, 0]
        vals = -jnp.log(jnp.clip(p, eps, 1.0))
    return _masked(vals, mask)


def _make_recall(topn: int) -> StepFn:
    def rec(pred, label, mask, rng):
        n, k = pred.shape
        if k < topn:
            raise ValueError(
                f"rec@{topn} meaningless for prediction list of size {k}")
        # random tie-break like the reference's pre-sort shuffle
        # (metric.h:149-153); jitter only reorders exact ties
        jitter = jax.random.uniform(rng, pred.shape)
        order = jnp.lexsort((jitter, -pred), axis=1)
        top = order[:, :topn]
        labels = label.astype(jnp.int32)
        hits = jnp.any(top[:, :, None] == labels[:, None, :], axis=1)
        vals = hits.sum(axis=1) / labels.shape[1]
        return _masked(vals.astype(jnp.float32), mask)
    return rec


def create_step_fn(name: str) -> StepFn:
    """Factory mirroring utils.metric.create_metric; each returned fn maps
    (pred2d, label, mask, rng) -> (sum, count) as traced scalars."""
    if name == "error":
        return _error
    if name == "rmse":
        return _rmse
    if name == "logloss":
        return _logloss
    if name.startswith("rec@"):
        return _make_recall(int(name[4:]))
    raise ValueError(f"Metric: unknown metric name: {name}")


def format_metrics(evname: str, specs, sums_counts) -> str:
    """Render accumulated (sum, count) rows in the reference print format
    `\\t{evname}-{metric}[{field}]:{value}` (metric.h:216-235; field
    suffix omitted for the default "label" field)."""
    out = []
    for (name, field), (s, c) in zip(specs, sums_counts):
        val = s / c if c else float("nan")
        tag = f"{evname}-{name}"
        if field != "label":
            tag += f"[{field}]"
        out.append(f"\t{tag}:{val:g}")
    return "".join(out)
