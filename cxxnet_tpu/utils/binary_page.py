"""BinaryPage: the fixed-size packed-blob page format of imgbin datasets.

Byte-compatible with the reference format (src/utils/io.h:254-326):

- A page is exactly 64 MiB (``4 * (64 << 18)`` bytes), zero-initialized.
- ``int32[0]`` = number of objects N.
- ``int32[1..N+1]`` = cumulative end offsets; object r occupies the byte
  range ``[page_size - off[r+1], page_size - off[r])`` counted from the
  page start, i.e. blobs are packed backwards from the end of the page.
- A page file (.bin) is a plain concatenation of such pages.

This Python implementation is the portable fallback; the native C++
reader (native/) mmaps pages and decodes JPEGs off-thread.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Iterator, List, Optional

# 64 << 18 int32 slots = 64 MiB
K_PAGE_NUM_INTS = 64 << 18
K_PAGE_SIZE = 4 * K_PAGE_NUM_INTS


class BinaryPage:
    """One fixed 64MiB page holding a stack of binary blobs."""

    def __init__(self, buf: Optional[bytearray] = None):
        if buf is None:
            buf = bytearray(K_PAGE_SIZE)
        if len(buf) != K_PAGE_SIZE:
            raise ValueError("BinaryPage buffer must be exactly 64MiB")
        self._buf = buf

    def clear(self) -> None:
        self._buf[:] = bytes(K_PAGE_SIZE)

    def _get_int(self, i: int) -> int:
        return struct.unpack_from("<i", self._buf, 4 * i)[0]

    def _set_int(self, i: int, v: int) -> None:
        struct.pack_into("<i", self._buf, 4 * i, v)

    @property
    def size(self) -> int:
        return self._get_int(0)

    def _free_bytes(self) -> int:
        n = self.size
        return (K_PAGE_NUM_INTS - (n + 2)) * 4 - self._get_int(n + 1)

    def push(self, blob: bytes) -> bool:
        """Append a blob; returns False when the page is full."""
        if self._free_bytes() < len(blob) + 4:
            return False
        n = self.size
        end = self._get_int(n + 1) + len(blob)
        self._set_int(n + 2, end)
        self._buf[K_PAGE_SIZE - end:K_PAGE_SIZE - end + len(blob)] = blob
        self._set_int(0, n + 1)
        return True

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, r: int) -> bytes:
        if not 0 <= r < self.size:
            raise IndexError("BinaryPage index out of bounds")
        start = self._get_int(r + 1)
        end = self._get_int(r + 2)
        return bytes(self._buf[K_PAGE_SIZE - end:K_PAGE_SIZE - start])

    def save(self, fo: BinaryIO) -> None:
        fo.write(self._buf)

    @classmethod
    def load(cls, fi: BinaryIO) -> Optional["BinaryPage"]:
        buf = fi.read(K_PAGE_SIZE)
        if len(buf) < K_PAGE_SIZE:
            return None
        return cls(bytearray(buf))


class BinaryPageWriter:
    """Streams blobs into consecutive pages of an output file."""

    def __init__(self, fo: BinaryIO):
        self._fo = fo
        self._page = BinaryPage()

    def push(self, blob: bytes) -> None:
        if not self._page.push(blob):
            self._page.save(self._fo)
            self._page.clear()
            if not self._page.push(blob):
                raise ValueError(
                    f"blob of {len(blob)} bytes exceeds 64MiB page capacity")

    def close(self) -> None:
        if self._page.size > 0:
            self._page.save(self._fo)
            self._page.clear()


def iter_page_blobs(fi: BinaryIO) -> Iterator[List[bytes]]:
    """Yield the blob list of each page in a .bin file."""
    while True:
        page = BinaryPage.load(fi)
        if page is None:
            return
        yield [page[i] for i in range(page.size)]
