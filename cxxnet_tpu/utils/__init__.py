"""Dependency-free utility layer: config parsing, metrics, binary page
IO, fault-tolerance primitives (retry / fault injection / atomic
writes)."""

from cxxnet_tpu.utils.config import ConfigIterator, parse_config_string, parse_config_file
from cxxnet_tpu.utils.fault import (DivergenceError, InjectedFault,
                                    atomic_writer, fault_point, retry)
from cxxnet_tpu.utils.metric import MetricSet, create_metric

__all__ = [
    "ConfigIterator",
    "parse_config_string",
    "parse_config_file",
    "MetricSet",
    "create_metric",
    "DivergenceError",
    "InjectedFault",
    "atomic_writer",
    "fault_point",
    "retry",
]
