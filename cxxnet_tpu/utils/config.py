"""`key = value` config tokenizer.

Behavioral parity with the reference tokenizer (src/utils/config.h:20-186):

- `#` starts a comment that runs to end of line.
- Tokens are whitespace-separated; `=` is its own token even when glued to
  neighbours (``a=b`` tokenizes as ``a``, ``=``, ``b``).
- Double-quoted strings are single-line, support backslash escapes, and must
  terminate before the newline; single-quoted strings may span lines.
- A quote may only open a token at the token's start.
- The stream is consumed as (name, '=', value) triples; anything else is a
  parse error (the reference silently stops - we raise, which is strictly
  more helpful and only differs on already-broken files).
"""

from __future__ import annotations

import io
from typing import Iterator, List, Tuple


class ConfigError(ValueError):
    """Raised on malformed config input."""


_EOF = ""


class _Tokenizer:
    """Character-level tokenizer mirroring ConfigReaderBase::GetNextToken."""

    def __init__(self, stream: io.TextIOBase):
        self._stream = stream
        self._ch = self._stream.read(1)

    def _next_char(self) -> None:
        self._ch = self._stream.read(1)

    def _skip_line(self) -> None:
        while self._ch not in (_EOF, "\n", "\r"):
            self._next_char()

    def _parse_quoted(self, terminator: str, allow_newline: bool) -> str:
        out: List[str] = []
        while True:
            self._next_char()
            ch = self._ch
            if ch == _EOF:
                raise ConfigError("ConfigReader: unterminated string")
            if ch == "\\":
                self._next_char()
                out.append(self._ch)
                continue
            if ch == terminator:
                return "".join(out)
            if ch in ("\r", "\n") and not allow_newline:
                raise ConfigError("ConfigReader: unterminated string")
            out.append(ch)

    def next_token(self) -> str | None:
        """Return the next token, or None at end of stream. Sets
        `last_token_new_line` when a newline (or line comment) was
        crossed before the token - the reference's new_line flag
        (config.h GetNextToken), used to reject key/'='/value split
        across lines."""
        tok: List[str] = []
        self.last_token_new_line = False
        while self._ch != _EOF:
            ch = self._ch
            if ch == "#":
                self._skip_line()
                if not tok:
                    self.last_token_new_line = True
            elif ch in ('"', "'"):
                if tok:
                    raise ConfigError(
                        "ConfigReader: token followed directly by string")
                s = self._parse_quoted(ch, allow_newline=(ch == "'"))
                self._next_char()
                return s
            elif ch == "=":
                if not tok:
                    self._next_char()
                    return "="
                return "".join(tok)
            elif ch in (" ", "\t", "\r", "\n"):
                self._next_char()
                if tok:
                    return "".join(tok)
                if ch in ("\r", "\n"):
                    self.last_token_new_line = True
            else:
                tok.append(ch)
                self._next_char()
        if tok:
            return "".join(tok)
        return None


class ConfigIterator:
    """Iterates (name, value) pairs from a config stream.

    Mirrors utils::ConfigIterator (src/utils/config.h:169-186): pulls
    (token, '=', token) triples until the stream ends.
    """

    def __init__(self, stream: io.TextIOBase):
        self._tok = _Tokenizer(stream)

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        return self

    def __next__(self) -> Tuple[str, str]:
        name = self._tok.next_token()
        if name is None:
            raise StopIteration
        if name == "=":
            raise ConfigError("ConfigReader: stray '='")
        eq = self._tok.next_token()
        if eq != "=":
            raise ConfigError(
                f"ConfigReader: expected '=' after {name!r}, got {eq!r}")
        if self._tok.last_token_new_line:
            # the reference's reader refuses a key/'='/value pair split
            # across lines (config.h Next's new_line bail) - but it
            # does so by SILENTLY ignoring the rest of the file; we
            # fail loudly instead
            raise ConfigError(
                f"ConfigReader: '=' for {name!r} must be on the same "
                "line as the key")
        val = self._tok.next_token()
        if val is None or val == "=":
            raise ConfigError(f"ConfigReader: missing value for {name!r}")
        if self._tok.last_token_new_line:
            raise ConfigError(
                f"ConfigReader: value for {name!r} must be on the same "
                "line as the key")
        return name, val


def parse_config_string(text: str) -> List[Tuple[str, str]]:
    """Parse a config document into an ordered list of (name, value)."""
    return list(ConfigIterator(io.StringIO(text)))


def parse_config_file(fname: str) -> List[Tuple[str, str]]:
    """Parse a config file into an ordered list of (name, value)."""
    with open(fname, "r", encoding="utf-8") as f:
        return list(ConfigIterator(f))


def validate_known_keys(pairs: List[Tuple[str, str]],
                        source: str = "") -> None:
    """Schema check on parsed pairs: every key must be recognized by
    some component's set_param handler (the generated registry of
    analysis/schema.py) - an unknown key raises ConfigError with a
    did-you-mean suggestion instead of silently configuring nothing
    (the reference routes every pair to every component and nobody
    owns the typo). The CLI runs this on every parsed config unless
    `schema_check = 0`."""
    from cxxnet_tpu.analysis import schema
    schema.validate_pairs(pairs, source=source)
