"""Fault-tolerance primitives: retry, fault injection, durable writes.

The reference treats a crash as fatal: CXXNetLearnTask writes model
files with a bare fopen (cxxnet_main.cpp:165-180) and a process killed
mid-save leaves a truncated checkpoint that silently poisons the next
`continue=1` restart. Production TPU training is defined by preemption,
so this module supplies the three primitives the rest of the stack
builds durability from:

- ``retry``: decorator for transient-failure paths (iterator reads,
  network mounts) with exponential backoff, jitter, and an optional
  total deadline.
- a process-wide **fault-injection registry** driven by the
  ``CXXNET_FAULT`` env var (``point:mode@N`` specs) or the ``inject``
  API, so tests and bench.py can kill / delay / corrupt named fault
  points deterministically.
- ``atomic_writer``: tmp-file + fsync + ``os.replace`` so a file either
  appears complete or not at all - a crash can leave a ``*.tmp`` but
  never a truncated final artifact.

See docs/FAULT_TOLERANCE.md for the full spec.
"""

from __future__ import annotations

import contextlib
import functools
import os
import random
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Type


class InjectedFault(RuntimeError):
    """Raised by a ``crash``-mode fault point (fault injection only)."""


class InjectedIOError(OSError):
    """Raised by an ``ioerror``-mode fault point: a *transient* IO
    error, the class the retry decorator absorbs."""


class DivergenceError(RuntimeError):
    """Training diverged: ``max_bad_rounds`` consecutive non-finite
    update rounds (nnet/trainer.py divergence guard)."""


def default_on_retry(fn, attempt, total, exc, sleep_s):
    """Per-retry notification: the exact pre-telemetry stderr text,
    routed through the central logger (a structured ``fault`` event
    when a sink is armed) plus a ``fault.retry`` counter, so retry
    storms are countable instead of vanishing into stderr."""
    from cxxnet_tpu import telemetry
    telemetry.inc("fault.retry")
    telemetry.stderr(
        f"retry: {getattr(fn, '__qualname__', fn)} failed "
        f"(attempt {attempt}/{total}: {type(exc).__name__}: {exc}); "
        f"retrying in {sleep_s:.2f}s\n",
        event_kind="fault", type="retry",
        fn=str(getattr(fn, "__qualname__", fn)), attempt=attempt,
        attempts=total, error=f"{type(exc).__name__}: {exc}",
        sleep_s=sleep_s)


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------
def retry(attempts: int = 3, backoff: float = 0.05, jitter: float = 0.05,
          retry_on: Tuple[Type[BaseException], ...] = (OSError,),
          deadline: Optional[float] = None,
          on_retry: Optional[Callable] = None):
    """Decorator: retry on transient errors with exponential backoff.

    - ``attempts``: total call attempts (1 = no retry).
    - ``backoff``: initial sleep between attempts, doubled each retry.
    - ``jitter``: uniform [0, jitter) seconds added to each sleep so
      many workers retrying the same shared resource don't stampede.
    - ``retry_on``: exception classes considered transient; anything
      else propagates immediately.
    - ``deadline``: optional cap on TOTAL elapsed seconds (including
      the pending sleep); when exceeded the last error propagates even
      if attempts remain.
    - ``on_retry(fn, attempt, attempts, exc, sleep_s)``: hook for the
      per-retry warning; default logs to stderr.
    """
    if attempts < 1:
        raise ValueError("retry: attempts must be >= 1")

    notify = on_retry or default_on_retry

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            start = time.monotonic()
            delay = backoff
            for attempt in range(1, attempts + 1):
                try:
                    return fn(*args, **kwargs)
                except retry_on as exc:
                    if attempt >= attempts:
                        raise
                    sleep_s = delay + random.uniform(0.0, jitter)
                    if (deadline is not None and
                            time.monotonic() - start + sleep_s > deadline):
                        raise
                    notify(fn, attempt, attempts, exc, sleep_s)
                    time.sleep(sleep_s)
                    delay *= 2
            raise AssertionError("unreachable")  # pragma: no cover
        return wrapped
    return deco


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------
FAULT_ENV = "CXXNET_FAULT"
KILL_EXIT_CODE = 117  # distinctive: assertable from subprocess tests
# a worker that convicts an absent peer at a checkpoint barrier exits
# with this code so the elastic supervisor (parallel/elastic.py) knows
# to reshape the pod rather than treat it as a crash
RESHAPE_EXIT_CODE = 118


def current_rank() -> int:
    """This process's identity for the rank-scoped fault modes
    (kill_rank/hang_rank/delay_collective). Under the elastic
    supervisor this is the STABLE pod member id (CXN_MEMBER_ID) -
    generation ranks renumber after a reshape, so a spec pinned to a
    plain rank would re-fire on a different worker in every
    generation; otherwise the launcher's CXN_WORKER_RANK. The env vars
    are authoritative - they exist before jax initializes and reading
    them cannot drag the backend up inside a fault point;
    jax.process_index is only consulted when jax is ALREADY imported
    (a fault point must never be the thing that initializes the
    platform)."""
    for key in ("CXN_MEMBER_ID", "CXN_WORKER_RANK"):
        v = os.environ.get(key)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                return 0
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 - backend not up yet: rank 0
            return 0
    return 0


class _Fault:
    __slots__ = ("mode", "arg", "at")

    def __init__(self, mode: str, arg: Optional[str], at: int):
        self.mode = mode
        self.arg = arg
        self.at = at


class FaultRegistry:
    """Process-wide registry of injected faults keyed by fault-point
    name. Specs come from the ``CXXNET_FAULT`` env var (re-parsed
    whenever its value changes, so monkeypatched env vars work
    in-process) or the programmatic ``inject`` API.

    Spec grammar (comma-separated)::

        point:mode@N        trigger `mode` on the Nth hit of `point`
        point:mode=ARG@N    mode with an argument (e.g. delay=0.5)

    ``@N`` defaults to 1; the fault fires exactly on hit N (hits are
    counted per process since the registry was last cleared).

    Built-in modes handled inside ``fault_point``:

    - ``crash``   raise InjectedFault
    - ``kill``    os._exit(KILL_EXIT_CODE) - simulates preemption; no
                  cleanup handlers run, exactly like SIGKILL
    - ``ioerror`` raise InjectedIOError (transient; retry-absorbable)
    - ``delay``   sleep arg seconds (default 0.05)

    Collective-scope (rank-aware) modes, for murdering a specific
    worker of a multi-controller pod deterministically (the elastic
    e2e suite - docs/FAULT_TOLERANCE.md "Elastic pod"). The SAME spec
    is exported to every worker; only the named rank acts, and hit
    counting stays per-process (every rank hits the same fault points
    in the same order under SPMD, so ``@N`` picks the same step on
    every worker):

    - ``kill_rank=R``        ``kill``, only when current_rank() == R
    - ``hang_rank=R``        wedge the calling thread forever (a live
                             but stalled worker - the absence-alert /
                             STALE-verdict detection path), only on
                             rank R
    - ``delay_collective=S`` sleep S seconds (straggler injection);
      ``delay_collective=R:S`` restricts the delay to rank R

    Any other mode (``corrupt``, ...) is returned to the CALLER, which
    gives each fault point site-specific sabotage: checkpoint.py
    truncates the blob being written, trainer.stage_batch NaN-poisons
    the batch, the serving canary NaN-poisons the candidate's shadow
    outputs (``canary_divergence:corrupt``) so the rollback verdict
    trips, and the HTTP body reader stalls mid-read
    (``serve_slow_client:delay``) so the connection deadline cuts it.
    The full point table lives in docs/FAULT_TOLERANCE.md.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # the registry's shared state: fault points fire from every
        # io/trainer thread, so all four fields move only under the
        # lock (checked statically - docs/STATIC_ANALYSIS.md GL016)
        # guarded-by: self._lock
        self._faults: Dict[str, List[_Fault]] = {}
        # guarded-by: self._lock
        self._env_faults: Dict[str, List[_Fault]] = {}
        # guarded-by: self._lock
        self._hits: Dict[str, int] = {}
        # guarded-by: self._lock
        self._env_seen: Optional[str] = None

    # -- configuration -----------------------------------------------------
    @staticmethod
    def parse(spec: str) -> Dict[str, List[_Fault]]:
        faults: Dict[str, List[_Fault]] = {}
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if ":" not in entry:
                raise ValueError(
                    f"bad {FAULT_ENV} entry {entry!r}: want point:mode[@N]")
            point, mode = entry.split(":", 1)
            at = 1
            if "@" in mode:
                mode, at_s = mode.rsplit("@", 1)
                at = int(at_s)
            arg = None
            if "=" in mode:
                mode, arg = mode.split("=", 1)
            if not point or not mode:
                raise ValueError(
                    f"bad {FAULT_ENV} entry {entry!r}: empty point/mode")
            faults.setdefault(point, []).append(_Fault(mode, arg, at))
        return faults

    def configure(self, spec: str) -> None:
        """Replace all injected faults with the parsed `spec` (hit
        counters reset)."""
        with self._lock:
            self._faults = self.parse(spec)
            self._hits = {}

    def inject(self, point: str, mode: str, arg: Optional[str] = None,
               at: int = 1) -> None:
        with self._lock:
            self._faults.setdefault(point, []).append(_Fault(mode, arg, at))

    def clear(self) -> None:
        with self._lock:
            self._faults = {}
            self._env_faults = {}
            self._hits = {}
            # forget the env value so a still-set CXXNET_FAULT is
            # re-armed on the next hit (clear = reset, not disable)
            self._env_seen = None

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    # -- the hot path ------------------------------------------------------
    def fault_point(self, point: str) -> Optional[str]:
        """Mark a named fault point. No-op (returns None) unless a
        fault is armed for `point` at the current hit count; then the
        built-in modes act here and caller-handled modes are returned
        as the action string."""
        env = os.environ.get(FAULT_ENV)
        with self._lock:
            if env != self._env_seen:
                # env faults layer over programmatic ones and are
                # REPLACED whenever the value changes (unset disarms
                # them); hit counters are preserved
                self._env_seen = env
                self._env_faults = self.parse(env) if env else {}
            if not self._faults and not self._env_faults:
                return None
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            armed = ([f for f in self._faults.get(point, ()) if f.at == hit]
                     + [f for f in self._env_faults.get(point, ())
                        if f.at == hit])
        for f in armed:
            if f.mode == "crash":
                raise InjectedFault(
                    f"injected crash at fault point {point!r} (hit {hit})")
            if f.mode == "kill":
                sys.stderr.write(
                    f"fault: killing process at fault point {point!r} "
                    f"(hit {hit})\n")
                sys.stderr.flush()
                os._exit(KILL_EXIT_CODE)
            if f.mode == "ioerror":
                raise InjectedIOError(
                    f"injected transient IO error at {point!r} (hit {hit})")
            if f.mode == "delay":
                time.sleep(float(f.arg) if f.arg else 0.05)
                continue
            if f.mode == "kill_rank":
                if f.arg is not None and current_rank() == int(f.arg):
                    sys.stderr.write(
                        f"fault: killing rank {f.arg} at fault point "
                        f"{point!r} (hit {hit})\n")
                    sys.stderr.flush()
                    os._exit(KILL_EXIT_CODE)
                continue
            if f.mode == "hang_rank":
                if f.arg is not None and current_rank() == int(f.arg):
                    sys.stderr.write(
                        f"fault: hanging rank {f.arg} at fault point "
                        f"{point!r} (hit {hit})\n")
                    sys.stderr.flush()
                    while True:  # wedged, not dead: detection's job
                        time.sleep(0.5)
                continue
            if f.mode == "delay_collective":
                spec = f.arg or "0.05"
                if ":" in spec:
                    rk, secs = spec.split(":", 1)
                    if current_rank() == int(rk):
                        time.sleep(float(secs))
                else:
                    time.sleep(float(spec))
                continue
            return f.mode  # site-handled action (e.g. "corrupt")
        return None


_REGISTRY = FaultRegistry()

# module-level convenience API (the registry is process-wide state,
# like the reference's global singletons)
fault_point = _REGISTRY.fault_point
inject = _REGISTRY.inject
clear = _REGISTRY.clear
configure = _REGISTRY.configure
hits = _REGISTRY.hits


# ---------------------------------------------------------------------------
# durable writes
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def atomic_writer(path: str, mode: str = "wb", fsync: bool = True,
                  tmp_suffix: str = ".tmp"):
    """Write `path` atomically: the body writes to ``path + tmp_suffix``
    and a successful exit fsyncs + ``os.replace``s it into place, so
    `path` either holds the complete new content or is untouched. On
    error the tmp file is removed and the error propagates; on a hard
    kill mid-write only the tmp file can be left behind.
    """
    tmp = path + tmp_suffix
    fo = open(tmp, mode)
    try:
        yield fo
        fo.flush()
        if fsync:
            os.fsync(fo.fileno())
        fo.close()
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        with contextlib.suppress(OSError):
            fo.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def _fsync_dir(dirname: str) -> None:
    """fsync a directory so the rename itself is durable (best-effort:
    some filesystems refuse O_RDONLY dir fds)."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
