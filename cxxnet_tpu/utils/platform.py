"""Backend-platform selection guard.

The TPU tunnel's sitecustomize registers its PJRT plugin into every
python process; a bare `jax.devices()` initializes ALL registered
platforms, so it can touch (and hang on) the tunnel even when the
caller exported JAX_PLATFORMS=cpu. Calling this before the first
device access makes an explicit env choice actually bind.
"""

from __future__ import annotations

import os


def ensure_env_platform() -> None:
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax
    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass  # backend already initialized


def setup_scoped_cache(platform_name: str, base: str = "") -> None:
    """Persistent-compile-cache setup shared by bench.py and the
    kernel-tuning tools: honors CXN_BENCH_CACHE=0 / CXN_BENCH_CACHE_DIR,
    keeps TPU entries at the cache root (device-targeted, host-
    independent), and scopes CPU entries per host-CPU fingerprint -
    XLA:CPU AOT results baked for another machine's features load with
    SIGILL warnings (seen round 4). With no fingerprint available the
    CPU cache is skipped entirely: a cold compile beats a crash."""
    if os.environ.get("CXN_BENCH_CACHE") == "0":
        return
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base = (base or os.environ.get("CXN_BENCH_CACHE_DIR")
            or os.path.join(repo, ".jax_cache"))
    if platform_name == "cpu":
        import hashlib
        fp = ""
        try:
            with open("/proc/cpuinfo") as f:
                # x86 lists ISA extensions under "flags", ARM under
                # "Features"; anything else is NO fingerprint - a
                # machine()-style fallback would be near-constant
                # across hosts with different ISA features, silently
                # re-creating the cross-host SIGILL hazard
                fp = next((ln for ln in f
                           if ln.startswith(("flags", "Features"))), "")
        except OSError:
            pass
        if not fp:
            return
        base = os.path.join(
            base, "cpu-" + hashlib.md5(fp.encode()).hexdigest()[:10])
    set_compilation_cache_dir(base)


def set_compilation_cache_dir(path: str) -> None:
    """Point XLA's persistent compilation cache at `path` (and make
    tiny/fast compiles eligible, so tests can observe it).

    jax initializes the process-global cache object ONCE, at the first
    cached compile - a later `jax_compilation_cache_dir` update changes
    the config value but the live cache keeps writing to the old dir.
    jax 0.9 has no public reset, so force re-initialization through the
    private flags (guarded: on any jax-internals drift the config
    update alone still works for the first-writer case)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        from jax._src import compilation_cache as cc
        with cc._cache_initialized_mutex:
            cc._cache_initialized = False
            cc._cache = None
    except Exception:  # noqa: BLE001 - private-API drift must not break
        pass
