"""Backend-platform selection guard.

The TPU tunnel's sitecustomize registers its PJRT plugin into every
python process; a bare `jax.devices()` initializes ALL registered
platforms, so it can touch (and hang on) the tunnel even when the
caller exported JAX_PLATFORMS=cpu. Calling this before the first
device access makes an explicit env choice actually bind.
"""

from __future__ import annotations

import os


def ensure_env_platform() -> None:
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax
    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass  # backend already initialized
