"""Backend-platform selection guard.

The TPU tunnel's sitecustomize registers its PJRT plugin into every
python process; a bare `jax.devices()` initializes ALL registered
platforms, so it can touch (and hang on) the tunnel even when the
caller exported JAX_PLATFORMS=cpu. Calling this before the first
device access makes an explicit env choice actually bind.
"""

from __future__ import annotations

import os


def ensure_env_platform() -> None:
    want = os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    import jax
    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass  # backend already initialized


def set_compilation_cache_dir(path: str) -> None:
    """Point XLA's persistent compilation cache at `path` (and make
    tiny/fast compiles eligible, so tests can observe it).

    jax initializes the process-global cache object ONCE, at the first
    cached compile - a later `jax_compilation_cache_dir` update changes
    the config value but the live cache keeps writing to the old dir.
    jax 0.9 has no public reset, so force re-initialization through the
    private flags (guarded: on any jax-internals drift the config
    update alone still works for the first-writer case)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        from jax._src import compilation_cache as cc
        with cc._cache_initialized_mutex:
            cc._cache_initialized = False
            cc._cache = None
    except Exception:  # noqa: BLE001 - private-API drift must not break
        pass
