"""SGD / NAG / Adam as pure per-tensor transforms.

Each updater is a pure function (state, w, grad, epoch) -> (state', w')
applied inside the jitted train step; the AsyncUpdater push/pull role of
the reference collapses into "gradients are already all-reduced by the
time this runs" (SURVEY.md par.2.7).

Formula parity:
- SGD   (sgd_updater-inl.hpp:72-84):
    m = mom*m - lr*(clip(grad) + wd*w); w += m
  where clip() clamps to +-clip_gradient and maps NaN -> 0 (:15-22).
- NAG   (nag_updater-inl.hpp:65-72):
    m_old = m; m = mom*m - lr*(grad + wd*w); w += (1+mom)*m - mom*m_old
- Adam  (adam_updater-inl.hpp:17-83) with decay1/decay2 = 0.1/0.001
  (beta expressed as 1-beta), bias-corrected lr, eps=1e-8, and the
  reference's weight-decay sign quirk `grad -= wd*w` preserved.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from cxxnet_tpu.updater.param import UpdaterParam

State = Dict[str, jax.Array]


def _clip_nan(grad: jax.Array, bound: float) -> jax.Array:
    """clip functor: clamp to [-bound, bound], NaN -> 0 (sgd_updater:15)."""
    grad = jnp.where(jnp.isnan(grad), 0.0, grad)
    return jnp.clip(grad, -bound, bound)


class Updater:
    """Base per-tensor updater bound to an UpdaterParam.

    Shard-shape contract (`zero_shardable`): under zero_stage >= 2 the
    trainer calls `apply` with SHARD-shaped tensors - the weight,
    gradient and every state leaf are one device's cut of the tensor
    along the zero partition dim (parallel/sharding.py), and the
    returned state/weight must be that same shard. An updater whose
    math is elementwise over the tensor (all the shipped ones) is
    shard-exact by construction: applying it per shard IS applying it
    to the full tensor. An updater that reduces OVER the tensor (a
    LARS/LAMB-style trust ratio from the global weight/grad norm) is
    not - its per-shard application would use per-shard norms - and
    must set `zero_shardable = False`; the trainer refuses to enable
    stage 2/3 with it rather than silently training different math.
    init_state must stay shape-polymorphic (zeros_like et al), so
    shard-shaped weights produce shard-shaped state."""

    kind = ""
    zero_shardable = True

    def __init__(self, param: UpdaterParam):
        self.param = param

    def init_state(self, w: jax.Array) -> State:
        raise NotImplementedError

    def apply(self, state: State, w: jax.Array, grad: jax.Array,
              epoch) -> Tuple[State, jax.Array]:
        raise NotImplementedError


class SGDUpdater(Updater):
    kind = "sgd"

    def init_state(self, w: jax.Array) -> State:
        return {"m": jnp.zeros_like(w)}

    def apply(self, state, w, grad, epoch):
        p = self.param
        lr, mom = p.schedule(epoch)
        if p.clip_gradient != 0.0:
            grad = _clip_nan(grad, p.clip_gradient)
        m = mom * state["m"] - lr * (grad + p.wd * w)
        return {"m": m}, w + m


class NAGUpdater(Updater):
    kind = "nag"

    def init_state(self, w: jax.Array) -> State:
        return {"m": jnp.zeros_like(w)}

    def apply(self, state, w, grad, epoch):
        p = self.param
        lr, mom = p.schedule(epoch)
        m_old = state["m"]
        m = mom * m_old - lr * (grad + p.wd * w)
        w = w + (1 + mom) * m - mom * m_old
        return {"m": m}, w


class AdamUpdater(Updater):
    kind = "adam"

    def __init__(self, param: UpdaterParam, decay1: float = 0.1,
                 decay2: float = 0.001):
        super().__init__(param)
        self.decay1 = decay1
        self.decay2 = decay2

    def init_state(self, w: jax.Array) -> State:
        return {"m1": jnp.zeros_like(w), "m2": jnp.zeros_like(w)}

    def apply(self, state, w, grad, epoch):
        p = self.param
        epoch = jnp.asarray(epoch, dtype=jnp.float32)
        if p.wd > 0.0:
            grad = grad - p.wd * w  # reference sign quirk
        fix1 = 1.0 - jnp.power(1.0 - self.decay1, epoch + 1)
        fix2 = 1.0 - jnp.power(1.0 - self.decay2, epoch + 1)
        lr_t = p.base_lr * jnp.sqrt(fix2) / fix1
        m1 = state["m1"] + self.decay1 * (grad - state["m1"])
        m2 = state["m2"] + self.decay2 * (grad * grad - state["m2"])
        w = w - lr_t * (m1 / (jnp.sqrt(m2) + 1e-8))
        return {"m1": m1, "m2": m2}, w


_UPDATERS = {"sgd": SGDUpdater, "nag": NAGUpdater, "adam": AdamUpdater}


def create_updater(kind: str, param: UpdaterParam, **kwargs) -> Updater:
    """Factory (updater_impl-inl.hpp:18-40 CreateUpdater_)."""
    if kind not in _UPDATERS:
        raise ValueError(f"unknown updater type {kind}")
    return _UPDATERS[kind](param, **kwargs)
