"""UpdaterParam: learning-rate/momentum schedules + tag scoping.

Parity with src/updater/param.h:13-133:

- params: lr|eta, wd, momentum, clip_gradient, momentum_schedule,
  base/final_momentum, saturation_epoch, lr:schedule|gamma|alpha|step|
  factor|minimum_lr|start_epoch.
- tag scoping: a param set as "<tag>:<name>" (e.g. `wmat:lr`, `bias:wd`)
  only applies to updaters whose tag matches - the prefix is stripped and
  the rest processed normally (param.h:100-105).
- schedules (ScheduleEpoch, param.h:76-94), `epoch` = number of updates:
    constant:  lr = base_lr
    expdecay:  lr = base_lr * gamma^(epoch / step)        (continuous)
    polydecay: lr = base_lr * (1 + (epoch//step)*gamma)^(-alpha)
    factor:    lr = base_lr * factor^(epoch // step)      (integer div)
  then lr clamped to >= minimum_lr; epochs before start_epoch use base_lr.
- momentum schedule: the reference statefully accumulates
  `momentum += (final-base)/saturation*epoch + base` each update then
  clamps to final_momentum - after the very first scheduled update it is
  already clamped for all practical settings, so the stateless equivalent
  used here evaluates the same expression from the current epoch and
  clamps identically.

Schedule math is written in jax.numpy so `epoch` may be a traced scalar
inside the jitted train step (no recompilation per epoch).
"""

from __future__ import annotations

import jax.numpy as jnp

_SCHEDULES = {"constant": 0, "expdecay": 1, "polydecay": 2, "factor": 3}


class UpdaterParam:
    def __init__(self, tag: str = ""):
        self.tag = tag
        self.base_lr = 0.01
        self.wd = 0.0
        self.momentum = 0.9
        self.clip_gradient = 0.0
        self.lr_schedule = 0
        self.momentum_schedule = 0
        self.lr_step = 1
        self.lr_gamma = 0.5
        self.lr_alpha = 0.5
        self.lr_factor = 0.1
        self.lr_minimum = 0.00001
        self.start_epoch = 0
        self.base_momentum = 0.5
        self.final_momentum = 0.90
        self.saturation_epoch = 0
        self.silent = 0

    def set_param(self, name: str, val: str) -> None:
        if self.tag and name.startswith(self.tag + ":"):
            name = name[len(self.tag) + 1:]
        if name == "lr" or name == "eta":
            self.base_lr = float(val)
        if name == "wd":
            self.wd = float(val)
        if name == "momentum":
            self.momentum = float(val)
        if name == "silent":
            self.silent = int(val)
        if name == "momentum_schedule":
            self.momentum_schedule = int(val)
        if name == "clip_gradient":
            self.clip_gradient = float(val)
        if name == "final_momentum":
            self.final_momentum = float(val)
        if name == "base_momentum":
            self.base_momentum = float(val)
        if name == "saturation_epoch":
            self.saturation_epoch = int(val)
        for prefix in ("lr:", "eta:"):
            if name.startswith(prefix):
                sub = name[len(prefix):]
                if sub == "schedule":
                    if val in _SCHEDULES:
                        self.lr_schedule = _SCHEDULES[val]
                if sub == "gamma":
                    self.lr_gamma = float(val)
                if sub == "alpha":
                    self.lr_alpha = float(val)
                if sub == "step":
                    self.lr_step = int(val)
                if sub == "factor":
                    self.lr_factor = float(val)
                if sub == "minimum_lr":
                    self.lr_minimum = float(val)
                if sub == "start_epoch":
                    self.start_epoch = int(val)

    # ------------------------------------------------------------------
    def schedule(self, epoch):
        """Return (learning_rate, momentum) at `epoch` (may be traced)."""
        epoch = jnp.asarray(epoch, dtype=jnp.float32)
        if self.lr_schedule == 0:
            lr = jnp.full_like(epoch, self.base_lr)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma,
                                          epoch / self.lr_step)
        elif self.lr_schedule == 2:
            steps = jnp.floor(epoch / self.lr_step)
            lr = self.base_lr * jnp.power(1.0 + steps * self.lr_gamma,
                                          -self.lr_alpha)
        elif self.lr_schedule == 3:
            steps = jnp.floor(epoch / self.lr_step)
            lr = self.base_lr * jnp.power(self.lr_factor, steps)
        else:
            raise ValueError("unknown schedule type")

        momentum = jnp.full_like(epoch, self.momentum)
        if self.momentum_schedule and self.saturation_epoch:
            momentum = (momentum + (self.final_momentum - self.base_momentum)
                        / self.saturation_epoch * epoch + self.base_momentum)
        momentum = jnp.minimum(momentum, self.final_momentum)
        lr = jnp.maximum(lr, self.lr_minimum)
        lr = jnp.where(epoch < self.start_epoch, self.base_lr, lr)
        return lr, momentum
