"""Updaters: SGD / NAG / Adam with the reference's schedule semantics."""

from cxxnet_tpu.updater.param import UpdaterParam
from cxxnet_tpu.updater.updaters import (
    Updater, create_updater, SGDUpdater, NAGUpdater, AdamUpdater)

__all__ = [
    "UpdaterParam", "Updater", "create_updater",
    "SGDUpdater", "NAGUpdater", "AdamUpdater",
]
