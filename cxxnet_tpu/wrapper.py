"""numpy-facing wrapper API.

API parity with wrapper/cxxnet.py:64-312 (`Net`, `DataIter`, `train()`):
the reference reaches the C++ core over a ctypes C ABI
(wrapper/cxxnet_wrapper.cpp); here the same surface binds directly to the
in-process trainer - same call signatures and semantics, numpy in/out.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from cxxnet_tpu.io import create_iterator
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string


class DataIter:
    """Config-built data iterator (CXNIOCreateFromConfig semantics)."""

    def __init__(self, cfg: str):
        self._it = create_iterator(parse_config_string(cfg))
        self._it.init()
        self.head = True
        self.tail = False

    def next(self) -> bool:
        ret = self._it.next()
        self.head = False
        self.tail = not ret
        return ret

    def before_first(self) -> None:
        self._it.before_first()
        self.head = True
        self.tail = False

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError(
                "iterator at head state, call next to get to valid state")
        if self.tail:
            raise RuntimeError("iterator reaches end")

    def get_data(self) -> np.ndarray:
        self.check_valid()
        return self._it.value().data

    def get_label(self) -> np.ndarray:
        self.check_valid()
        return self._it.value().label

    @property
    def value(self) -> DataBatch:
        self.check_valid()
        return self._it.value()


def _batch_from_numpy(data: np.ndarray,
                      label: Optional[np.ndarray]) -> DataBatch:
    if data.ndim != 4:
        raise ValueError(
            "need 4 dimensional tensor (batch, channel, height, width)")
    if label is None:
        label = np.zeros((data.shape[0], 1), dtype=np.float32)
    label = np.asarray(label, dtype=np.float32)
    if label.ndim == 1:
        label = label.reshape(-1, 1)
    if label.shape[0] != data.shape[0]:
        raise ValueError("data size mismatch")
    return DataBatch(data=np.asarray(data, dtype=np.float32), label=label)


class Net:
    """Neural net object (CXNNetCreate semantics)."""

    def __init__(self, dev: str = "cpu", cfg: str = ""):
        self._net = NetTrainer(dev=dev, cfg=cfg)

    def set_param(self, name, value) -> None:
        self._net.set_param(str(name), str(value))

    def init_model(self) -> None:
        self._net.init_model()

    def load_model(self, fname: str) -> None:
        with open(fname, "rb") as f:
            self._net.load_model(f)

    def save_model(self, fname: str) -> None:
        with open(fname, "wb") as f:
            self._net.save_model(f)

    def start_round(self, round_counter: int) -> None:
        self._net.start_round(round_counter)

    def update(self, data: Union[DataIter, np.ndarray],
               label: Optional[np.ndarray] = None) -> None:
        if isinstance(data, DataIter):
            data.check_valid()
            self._net.update(data.value)
        elif isinstance(data, np.ndarray):
            if label is None:
                raise ValueError("need label to use update")
            self._net.update(_batch_from_numpy(data, label))
        else:
            raise TypeError(f"update does not support type {type(data)}")

    def evaluate(self, data: DataIter, name: str) -> str:
        if not isinstance(data, DataIter):
            raise TypeError("evaluate expects a DataIter")
        return self._net.evaluate(data._it, name)

    def predict(self, data: Union[DataIter, np.ndarray]) -> np.ndarray:
        if isinstance(data, DataIter):
            data.check_valid()
            return self._net.predict(data.value)
        return self._net.predict(_batch_from_numpy(data, None))

    def predict_dist(self,
                     data: Union[DataIter, np.ndarray]) -> np.ndarray:
        if isinstance(data, DataIter):
            data.check_valid()
            return self._net.predict_dist(data.value)
        return self._net.predict_dist(_batch_from_numpy(data, None))

    def extract(self, data: Union[DataIter, np.ndarray],
                node_name: str) -> np.ndarray:
        if isinstance(data, DataIter):
            data.check_valid()
            return self._net.extract_feature(data.value, node_name)
        return self._net.extract_feature(_batch_from_numpy(data, None),
                                         node_name)

    def calibrate_passes(self, data: np.ndarray,
                         label: Optional[np.ndarray] = None) -> bool:
        """Capture fold_conv_bn calibration statistics from one numpy
        batch (graph_passes - docs/GRAPH_PASSES.md). predict/extract
        self-calibrate on their first batch; call this before
        serve_start so the serving executables compile FOLDED (an
        uncalibrated Server serves the unfolded graph and warns).
        Returns True when stats were captured."""
        return self._net.calibrate_graph_passes(
            _batch_from_numpy(np.asarray(data, dtype=np.float32),
                              label))

    # -- serving (docs/SERVING.md) -------------------------------------
    def serve_start(self, max_batch: int = 0,
                    max_wait_ms: Optional[float] = None,
                    replicas: Optional[int] = None,
                    http_port: Optional[int] = None,
                    queue_limit: Optional[int] = None,
                    deadline_ms: Optional[float] = None,
                    swap_watch: Optional[str] = None) -> None:
        """Start the continuous-batching server over this net's
        inference executable: bucket executables compiled + warmed
        here, dispatcher replicas spawned. Unset arguments fall back
        to the net's serve_* config keys (serve_max_batch /
        serve_max_wait_ms / serve_replicas / serve_port /
        serve_queue_limit / serve_deadline_ms / swap_watch -
        docs/SERVING.md). http_port attaches the /predict HTTP
        request path (0 = ephemeral; read the bound port off
        `net._server.metrics_server.port`); queue_limit arms load
        shedding (QueueFullError / HTTP 429); swap_watch arms the
        zero-downtime checkpoint hot-swap poller."""
        if getattr(self, "_server", None) is not None:
            raise RuntimeError("server already started")
        from cxxnet_tpu.serve import Server
        srv = Server(self._net, max_batch=max_batch,
                     max_wait_ms=max_wait_ms, replicas=replicas,
                     http_port=http_port, queue_limit=queue_limit,
                     deadline_ms=deadline_ms, swap_watch=swap_watch)
        # attach only once running: a warmup failure (compile error,
        # OOM) must leave serve_start retryable, not wedge the Net
        # behind "server already started"
        srv.warmup()
        srv.start()
        self._server = srv

    def serve_submit(self, data: np.ndarray,
                     block: bool = True):
        """Submit numpy rows ((n, c, y, x) or one (c, y, x) instance)
        to the running server. block=True (default) returns the raw
        final-node rows, (n, width) - the predict_dist surface;
        block=False returns a future whose result() yields them
        (concurrent submitters are what continuous batching
        coalesces). cxxnet_tpu.serve.predictions_from_rows converts
        rows to predict()-style labels."""
        if getattr(self, "_server", None) is None:
            raise RuntimeError("call serve_start first")
        fut = self._server.submit(np.asarray(data, dtype=np.float32))
        return fut.result() if block else fut

    def serve_swap(self, path: str) -> bool:
        """Hot-swap the running server's weights from an on-disk
        checkpoint (docs/SERVING.md "Hot-swap runbook"): validated,
        staged and switched between batches with zero dropped
        requests. Returns False (and keeps the old weights serving)
        when the file is torn/corrupt/shape-mismatched."""
        if getattr(self, "_server", None) is None:
            raise RuntimeError("call serve_start first")
        return self._server.swap_to(path)

    def serve_stop(self) -> dict:
        """Drain + stop the server; returns its stats() summary
        (request/batch/padding counts, latency p50/p99 ms)."""
        if getattr(self, "_server", None) is None:
            raise RuntimeError("no server running")
        srv, self._server = self._server, None
        return srv.stop()

    def serve_drain(self) -> dict:
        """Graceful shutdown (docs/SERVING.md "Connection limits &
        drain"): reject new submissions, flip /healthz to draining,
        resolve every queued request, then stop. Returns stats()."""
        if getattr(self, "_server", None) is None:
            raise RuntimeError("no server running")
        srv, self._server = self._server, None
        return srv.drain()

    def has_layer(self, layer_name: str) -> bool:
        return layer_name in self._net.net_cfg.layer_name_map

    def get_weight(self, layer_name: str, tag: str) -> np.ndarray:
        w, _ = self._net.get_weight(layer_name, tag)
        return w

    def set_weight(self, weight: np.ndarray, layer_name: str,
                   tag: str) -> None:
        self._net.set_weight(np.asarray(weight, dtype=np.float32),
                             layer_name, tag)


# train()'s device-resident cutoff: datasets under this many bytes are
# staged once (module-level so tests can force either path)
_STAGE_BYTES_LIMIT = 256 * 2 ** 20


def train(cfg: str, data, label, num_round: int,
          param, eval_data=None, batch_size: int = 128,
          dev: str = "cpu") -> Net:
    """Convenience trainer over numpy arrays (cxxnet.py:301-312).

    eval_data: optional (data, label) pair; CLASSIFICATION error is
    computed after every round (batch_size chunks) and printed to
    stderr like the CLI round loop - regression nets should evaluate
    manually. The final partial batch of each round trains too (padded
    internally)."""
    from cxxnet_tpu import telemetry
    net = Net(dev=dev, cfg=cfg)
    net.set_param("batch_size", batch_size)
    for k, v in (param.items() if isinstance(param, dict) else param):
        net.set_param(k, v)
    net.init_model()
    n = data.shape[0]
    # small datasets train device-resident: stage every batch's device
    # buffers ONCE (trainer.stage_batch, trajectory bit-identical to
    # streaming - tests/test_trainer.py) instead of re-padding/casting/
    # staging the same slices every round. Gated by a memory bound so a
    # large numpy dataset streams exactly as before instead of pinning
    # itself into device memory.
    staged = None
    # bound the STAGED footprint (f32, padded to full batches), not the
    # source nbytes: a uint8 source stages at 4x its own size
    c, hh, ww = net._net.net_cfg.input_shape
    n_batches = (n + batch_size - 1) // batch_size
    staged_bytes = n_batches * batch_size * c * hh * ww * 4
    if staged_bytes < _STAGE_BYTES_LIMIT:
        try:
            staged = [net._net.stage_batch(_batch_from_numpy(
                data[i:i + batch_size], label[i:i + batch_size]))
                for i in range(0, n, batch_size)]
            if net._net.steps_per_dispatch > 1:
                # fused dispatch (docs/PERFORMANCE.md): stack the
                # device-resident batches into K-step chunks ONCE;
                # each round then costs one dispatch per chunk
                # (update() routes StagedChunk to update_chunk)
                k = net._net.steps_per_dispatch
                staged = [net._net.stage_chunk(staged[i:i + k])
                          for i in range(0, len(staged), k)]
        except Exception:  # noqa: BLE001 - staging is an optimization
            staged = None
    pf = None
    if staged is None:
        # large datasets stream - through the H2D staging prefetcher
        # (io/prefetch.py): batch k+1 padded/cast/device_put on a
        # worker thread while step k runs, same batches in the same
        # order as the direct slice loop
        class _Slices:
            def before_first(self):
                self.i = -batch_size

            def next(self):
                self.i += batch_size
                return self.i < n

            def value(self):
                i = self.i
                return _batch_from_numpy(data[i:i + batch_size],
                                         label[i:i + batch_size])

        # chunk=K assembles fused-dispatch chunks on the worker when
        # steps_per_dispatch is configured (1 = unchanged streaming)
        pf = net._net.prefetch(_Slices(), depth=1,
                               chunk=net._net.steps_per_dispatch)
    try:
        for r in range(num_round):
            net.start_round(r)
            if staged is not None:
                for s in staged:
                    net._net.update(s)
            else:
                pf.before_first()
                while pf.next():
                    net._net.update(pf.value())
            if eval_data is not None:
                ed, el = eval_data
                preds = [net.predict(ed[i:i + batch_size])
                         for i in range(0, ed.shape[0], batch_size)]
                pred = np.concatenate(preds)
                err = float((pred != np.asarray(el).reshape(-1)).mean())
                telemetry.stderr(f"[{r}]\teval-error:{err:g}\n",
                                 event_kind="eval", round=r,
                                 values={"eval-error": err})
    finally:
        if pf is not None:
            pf.close()  # a mid-round error must not leak the worker
    return net
