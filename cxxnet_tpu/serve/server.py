"""Continuous-batching inference server (docs/SERVING.md).

The predict/extract tasks are batch-at-a-time, train-shaped code: one
caller, one fixed batch, one padded dispatch. Production serving is
the opposite shape - many concurrent callers submitting a few rows
each - and the TF-paper framing (PAPERS.md, arXiv:1605.08695) treats
it as the same dataflow system with a different driver. This module is
that driver:

- a **shared request queue**: `submit()` is thread-safe and returns a
  future; requests larger than the biggest bucket split internally and
  re-join on `result()`;
- **continuous/dynamic batching into padded buckets**: dispatchers
  coalesce queued requests up to `max_batch` rows and run the smallest
  power-of-two bucket that covers them, padding the tail. Every bucket
  size is a distinct program shape of ONE jitted inference executable
  (trainer's `infer_fn`), so the bucket set compiles once;
- **warmed executables**: `warmup()` runs every bucket once at
  startup. Steady state then performs ZERO recompiles - provable via
  the same `_cache_size` technique the jaxpr audit uses
  (`executable_cache_size()` == `len(buckets)` and stays flat);
- **replica fan-out**: `replicas` dispatcher threads drain the shared
  queue; each dispatch is the SPMD executable over the full mesh (on
  `mesh = data:N` the bucket's rows spread over the data axis), and
  jax's async dispatch lets replicas pipeline host staging against
  device compute. `zero_stage = 3` params are consumed directly at
  their stored (sharded) layout - the executable's in_shardings are
  the trainer's `pstore`, so no host-side gather ever runs;
- an **admission/flush policy**: a dispatcher waits up to
  `max_wait_ms` for the bucket to fill, then flushes what it has
  (fill-or-timeout), so p99 latency stays bounded under low load.

Telemetry (docs/OBSERVABILITY.md): `serve.latency_s` histogram
(p50/p99 through the registry), the `serve.queue_s` / `serve.device_s`
per-request breakdown (request tracing: queue = submit -> dispatch,
incl. the fill-or-timeout coalesce wait; device = dispatch -> result
readback), the `serve.request_rows` Prometheus
histogram over the bucket ladder, `serve.queue_depth` gauge,
`serve.requests`/`serve.rows`/`serve.batches`/`serve.padding_rows`/
`serve.errors` counters. These accumulate unconditionally (they are
the product surface, queried via `Server.stats()`), like the fault
counters - no per-row device sync is added beyond the result readback
serving inherently requires. With the observability plane armed every
dispatch additionally lands in the flight recorder (executable
fingerprint + bucket + trace id - telemetry/flight.py), each warmed
bucket registers on `/executables`, and resolved requests emit `trace`
events that `tools/trace_export.py` renders to Perfetto-loadable
Chrome trace JSON.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.telemetry.flight import fingerprint as exec_fingerprint


def bucket_sizes(max_batch: int, data_axis: int = 1) -> Tuple[int, ...]:
    """The padded-batch bucket set: powers of two up to `max_batch`
    that the mesh's data axis divides (a bucket's rows must split
    evenly over the axis), plus `max_batch` itself. At least one
    bucket must exist - a `max_batch` the data axis does not divide
    cannot be dispatched and is rejected here, at configure time."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    out = set()
    b = 1
    while b <= max_batch:
        if b % data_axis == 0:
            out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_buckets(ladder: Sequence[int], max_batch: int,
                   data_axis: int = 1) -> Tuple[int, ...]:
    """An EXPLICIT bucket ladder (the autotuner's telemetry-shaped
    rungs, or `serve_bucket_ladder =` - docs/GRAPH_PASSES.md) folded
    into a valid bucket set: rungs outside [1, max_batch] or not
    divisible by the mesh's data axis are dropped (the
    inapplicable-tuned-value rule - a cache shaped on one mesh must
    not break another), and `max_batch` itself always closes the
    ladder. The max_batch/data-axis contract is bucket_sizes'."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    axis = max(data_axis, 1)
    out = {int(b) for b in ladder
           if 1 <= int(b) <= max_batch and int(b) % axis == 0}
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_from_histogram(hist, max_batch: int, data_axis: int = 1,
                          rungs: int = 4) -> Tuple[int, ...]:
    """Shape a bucket ladder from an observed request-size histogram
    ({size: count}, the Server's `request_sizes` stat): one rung at
    each 1/rungs quantile of the size distribution, rounded UP to the
    data axis, closed by `max_batch`. Sizes the traffic actually
    sends get tight buckets (less padding); sizes it never sends get
    no bucket (fewer warmed executables) - the TVM move of shaping
    the search space from the workload instead of a fixed
    power-of-two set. Falls back to bucket_sizes on an empty
    histogram."""
    sizes = sorted((int(s), int(c)) for s, c in dict(hist).items()
                   if int(c) > 0 and int(s) >= 1)
    if not sizes:
        return bucket_sizes(max_batch, data_axis)
    axis = max(data_axis, 1)
    total = sum(c for _, c in sizes)
    ladder = []
    for r in range(1, max(rungs, 1) + 1):
        target = r * total / max(rungs, 1)
        acc = 0
        for s, c in sizes:
            acc += c
            if acc >= target:
                ladder.append(-(-s // axis) * axis)  # ceil to axis
                break
    return ladder_buckets(ladder, max_batch, data_axis)


def predictions_from_rows(rows: np.ndarray) -> np.ndarray:
    """The TransformPred rule (trainer.predict) applied to raw final-
    node rows: single-column output passes through as scalars, wider
    output argmaxes - so a serve result file is comparable line-for-
    line with a `task = pred` file."""
    rows = np.asarray(rows)
    flat = rows.reshape(rows.shape[0], -1)
    if flat.shape[1] == 1:
        return flat[:, 0]
    return np.argmax(flat, axis=1).astype(np.float32)


class _Future:
    """Minimal one-shot result future (no concurrent.futures executor
    to tie its lifetime to)."""

    __slots__ = ("_ev", "_value", "_error")

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def _set(self, value) -> None:
        self._value = value
        self._ev.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._value


class _JoinedFuture:
    """A request that split into several work items: result() is the
    row-concatenation of the parts, in submission order."""

    __slots__ = ("_parts",)

    def __init__(self, parts: List[_Future]) -> None:
        self._parts = parts

    def done(self) -> bool:
        return all(p.done() for p in self._parts)

    def result(self, timeout: Optional[float] = None):
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for p in self._parts:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            out.append(p.result(left))
        return np.concatenate(out, axis=0)


class _WorkItem:
    __slots__ = ("data", "extras", "n", "t_submit", "future",
                 "trace", "part", "nparts", "t_collect")

    def __init__(self, data, extras, t_submit, trace="",
                 part=0, nparts=1) -> None:
        self.data = data
        self.extras = extras
        self.n = data.shape[0]
        self.t_submit = t_submit
        self.future = _Future()
        # end-to-end request tracing (docs/OBSERVABILITY.md "Request
        # tracing"): the trace id minted at submit(), the part index
        # for oversize requests that split, and the coalesce time a
        # dispatcher stamps when it pops the item; the queue/device
        # latency cut itself is the DISPATCH stamp (_run_batch) -
        # the fill wait after the pop is still queue time
        self.trace = trace
        self.part = part
        self.nparts = nparts
        self.t_collect = 0.0


class Server:
    """Continuous-batching server over a trainer's inference
    executable. The trainer must hold a model (init_model or
    load_model); its mesh, dtype and device_augment spec all apply
    unchanged - serving is the same compiled forward predict runs,
    driven by a queue instead of an iterator.

    start() spawns the dispatcher replicas (warmup() first unless you
    want the first requests to pay the compiles); submit() from any
    thread; stop() drains the queue, joins the replicas and returns
    stats(). Usable as a context manager."""

    def __init__(self, trainer, max_batch: int = 0,
                 max_wait_ms: Optional[float] = None,
                 replicas: Optional[int] = None,
                 node: int = -1,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "0.0.0.0",
                 ladder: Optional[Sequence[int]] = None) -> None:
        import jax
        if trainer.state is None:
            raise RuntimeError(
                "Server needs an initialized trainer (init_model or "
                "load_model first)")
        if jax.process_count() > 1:
            raise RuntimeError(
                "serving a multi-controller job is not supported; run "
                "the server on a single-process mesh")
        self.trainer = trainer
        self.max_batch = int(max_batch or trainer.serve_max_batch
                             or trainer.batch_size)
        self.max_wait_ms = float(
            trainer.serve_max_wait_ms if max_wait_ms is None
            else max_wait_ms)
        self.replicas = int(trainer.serve_replicas if replicas is None
                            else replicas)
        if self.replicas < 1:
            raise ValueError("serve_replicas must be >= 1")
        self.node = (node if node >= 0
                     else trainer.net_cfg.num_nodes - 1)
        dsize = trainer.mesh.shape.get("data", 1)
        # explicit ladder > trainer's (tuned or serve_bucket_ladder =)
        # ladder > the power-of-two default - the same
        # explicit-keys-win chain the scalar serve knobs ride
        lad = (ladder if ladder is not None
               else getattr(trainer, "serve_ladder", None))
        self.buckets = (ladder_buckets(lad, self.max_batch, dsize)
                        if lad else
                        bucket_sizes(self.max_batch, dsize))
        if getattr(trainer, "passes_need_calibration",
                   lambda: False)():
            # a calibrating pass (fold_conv_bn / quantize_int8)
            # without stats: the infer executable built below is the
            # un-rewritten FLOAT graph (safe, just unoptimized) and
            # stays so for this Server's lifetime - warmup on zeros
            # must never become the calibration batch (zero-input
            # moments and activation ranges would be garbage).
            # task=serve calibrates from the first pred batch before
            # building the Server (main.py); programmatic users call
            # trainer.calibrate_graph_passes (or predict once) first.
            telemetry.stderr(
                "serve: graph passes (fold_conv_bn/quantize_int8) "
                "have no calibration stats; serving the unoptimized "
                "float graph (calibrate before Server creation to "
                "fold/quantize)\n",
                event_kind="serve", op="fold_uncalibrated")
        self._fn = trainer._infer_fn(self.node)
        c, y, x = trainer.net_cfg.input_shape
        self._input_dims = (c, y, x)
        self._extra_dims = [
            tuple(trainer.net.node_shapes[1 + i][1:])
            for i in range(trainer.net_cfg.extra_data_num)]
        # attachable live-exposition server (docs/OBSERVABILITY.md):
        # metrics_port=N serves /metrics + /healthz + /varz for the
        # Server's lifetime (0 = ephemeral bind, read .metrics_server
        # .port). None = off; programmatic twins of the CLI key, which
        # arms the process-wide plane in main.run instead
        self.metrics_port = metrics_port
        self.metrics_host = metrics_host
        self.metrics_server = None
        if metrics_port is not None:
            # the attached exposition endpoint is a flight-recorder
            # consumer (it serves the /varz tail and /executables) -
            # arm the recorder for this Server's lifetime, the same
            # rule arm_observability applies to the process-wide
            # plane. Armed HERE (not in start()) so warmup()'s cost
            # enrichment sees it: warmup conventionally runs before
            # start(). stop() re-derives from the remaining consumers.
            telemetry.get().flight.enabled = True
        self._cond = threading.Condition()
        # admission state: the queue, its row count and the drain flag
        # move together under the condition (checked statically -
        # docs/STATIC_ANALYSIS.md GL016)
        self._queue: collections.deque = collections.deque()
        # guarded-by: self._cond
        self._queued_rows = 0
        self._threads: List[threading.Thread] = []
        # guarded-by: self._cond
        self._draining = False
        self._started = False
        self.warmup_s = 0.0
        # product-surface accounting, independent of the process-wide
        # registry (a second Server in one process must not inherit
        # the first one's counts OR its latency window); the registry
        # mirrors everything for the metrics stream/report
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._n_requests = 0
        # guarded-by: self._lock
        self._n_rows = 0
        # guarded-by: self._lock
        self._n_batches = 0
        # guarded-by: self._lock
        self._n_padding = 0
        # guarded-by: self._lock
        self._n_errors = 0
        # guarded-by: self._lock
        self._bucket_hits: Dict[int, int] = {b: 0 for b in self.buckets}
        # request-size histogram: the serve telemetry the autotuner's
        # ladder_from_histogram shapes the bucket ladder from
        # (docs/GRAPH_PASSES.md "per-layer autotuner"); counts per
        # submitted work-item row count
        # guarded-by: self._lock
        self._size_hist: Dict[int, int] = {}
        self._lat = telemetry.Histogram()
        # per-request queue-vs-device decomposition (request tracing):
        # queue = submit -> coalesce, device = coalesce -> result
        self._qlat = telemetry.Histogram()
        self._dlat = telemetry.Histogram()
        # request-size distribution as a proper Prometheus histogram
        # on /metrics (bounds = this Server's bucket ladder); the
        # dict-shaped stats()["request_sizes"] stays for the autotuner
        self._req_hist = telemetry.get().registry.bucket_histogram(
            "serve.request_rows", bounds=self.buckets)
        # request-trace ids minted at submit(); executable
        # fingerprints per warmed bucket (filled by warmup) feed the
        # flight recorder + /executables registry (telemetry/flight.py)
        self._trace_seq = itertools.count(1)
        self._exec_fp: Dict[int, str] = {}

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> float:
        """Compile + run every bucket executable once (zeros input) so
        steady-state serving never compiles. Returns the wall seconds
        spent; also recorded as `serve.warmup_s`."""
        import jax
        t0 = time.perf_counter()
        params = self.trainer.state["params"]
        tel = telemetry.get()
        epoch = getattr(self.trainer, "_fold_epoch", 0)
        for b in self.buckets:
            data = np.zeros((b,) + self._input_dims, np.float32)
            extras = [np.zeros((b,) + d, np.float32)
                      for d in self._extra_dims]
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            tb = time.perf_counter()
            jax.block_until_ready(self._fn(params, gdata, gextras))
            compile_s = time.perf_counter() - tb
            # executable registry (telemetry/flight.py): one entry per
            # warmed bucket program shape, stamped with its compile
            # wall-time (warmup's block IS the compile window). The
            # fingerprint is what flight entries and stall dumps name.
            fp = exec_fingerprint(
                "serve.infer", self.node, b, self._input_dims,
                epoch)
            self._exec_fp[b] = fp
            tel.executables.register(
                fp, name=f"serve.infer:b{b}", kind="serve",
                shape=str((b,) + self._input_dims),
                arg_bytes=int(data.nbytes
                              + sum(e.nbytes for e in extras)),
                device=jax.default_backend(), donated=0,
                compile_s=compile_s)
            if tel.flight.enabled:
                # armed plane: enrich with XLA cost analysis + output
                # footprint (one extra trace/lowering per bucket,
                # sanctioned here in the warmup window; the jit cache
                # the zero-recompile audit counts is untouched)
                tel.executables.enrich(fp, self._fn,
                                       (params, gdata, gextras))
        self.warmup_s = time.perf_counter() - t0
        telemetry.observe("serve.warmup_s", self.warmup_s)
        telemetry.event("serve", op="warmup", buckets=list(self.buckets),
                        secs=self.warmup_s)
        return self.warmup_s

    def executable_cache_size(self) -> Optional[int]:
        """Compiled-program count of the inference executable (the
        jaxpr audit's `_cache_size` technique): after warmup this
        equals len(buckets) and must stay flat under any steady-state
        request mix - the zero-recompile proof."""
        fn = getattr(self._fn, "_cache_size", None)
        return fn() if callable(fn) else None

    def start(self) -> "Server":
        if self._started:
            return self
        if self.metrics_port is not None and self.metrics_server is None:
            from cxxnet_tpu.telemetry.http import ObservabilityServer
            self.metrics_server = ObservabilityServer(
                telemetry.get(), int(self.metrics_port),
                host=self.metrics_host)
            self.metrics_server.start()
            telemetry.event("observability", op="http_start",
                            port=self.metrics_server.port,
                            host=self.metrics_host)
        with self._cond:
            # published under the lock that guards it: a replica from
            # a previous start/stop cycle draining late must not read
            # a torn flag
            self._draining = False
        self._started = True
        for i in range(self.replicas):
            t = threading.Thread(target=self._replica_loop,
                                 name=f"serve-replica-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        """Stop the replicas - after draining the queue (default), or
        immediately failing queued requests (drain=False) - and return
        stats(). Idempotent."""
        with self._cond:
            self._draining = True
            if not drain:
                while self._queue:
                    it = self._queue.popleft()
                    self._queued_rows -= it.n
                    it.future._set_error(
                        RuntimeError("server stopped before dispatch"))
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=60.0)
        self._threads = []
        self._started = False
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self.metrics_port is not None:
            # this Server's endpoint was a flight consumer; re-derive
            # the recorder's armed state from whatever remains (sinks,
            # the process-wide plane, an explicit flight_recorder=1)
            telemetry.get()._refresh_flight()
        telemetry.set_gauge("serve.queue_depth", 0.0)
        stats = self.stats()
        telemetry.event("serve", op="stop", **{
            k: v for k, v in stats.items() if not isinstance(v, dict)})
        return stats

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- submission --------------------------------------------------------
    def submit(self, data: np.ndarray, extras: Sequence = ()):
        """Enqueue one request: data is (n, c, y, x) rows or a single
        (c, y, x) instance; extras (if the net declares extra inputs)
        ride along row-aligned. Returns a future whose result() is the
        raw final-node rows, (n, width) - predictions_from_rows turns
        them into predict()-style labels. Thread-safe; requests wider
        than the largest bucket split transparently."""
        if not self._started:
            raise RuntimeError("Server not started (call start())")
        data = np.ascontiguousarray(data)
        if data.ndim == 3:
            data = data[None]
        if data.ndim != 4 or data.shape[1:] != self._input_dims:
            raise ValueError(
                f"serve request must be (n, {self._input_dims[0]}, "
                f"{self._input_dims[1]}, {self._input_dims[2]}) or a "
                f"single instance; got {data.shape}")
        if data.shape[0] < 1:
            raise ValueError("serve request needs at least one row")
        extras = [np.ascontiguousarray(e, dtype=np.float32)
                  for e in extras]
        if len(extras) != len(self._extra_dims):
            raise ValueError(
                f"net declares {len(self._extra_dims)} extra inputs "
                f"but the request carries {len(extras)}")
        for e in extras:
            if e.shape[0] != data.shape[0]:
                raise ValueError("extras must be row-aligned with data")
        t_submit = time.monotonic()
        # request trace id (docs/OBSERVABILITY.md "Request tracing"):
        # minted once per submit and shared by every split part, so an
        # oversize request renders as ONE span tree in the exported
        # Chrome trace; pid-scoped so multi-process traces merge
        trace = f"{os.getpid():x}-{next(self._trace_seq):06d}"
        nparts = -(-data.shape[0] // self.max_batch)
        items = []
        for part, lo in enumerate(
                range(0, data.shape[0], self.max_batch)):
            hi = lo + self.max_batch
            items.append(_WorkItem(
                data[lo:hi], [e[lo:hi] for e in extras], t_submit,
                trace=trace, part=part, nparts=nparts))
        with self._cond:
            if self._draining:
                raise RuntimeError("server is stopping")
            for it in items:
                self._queue.append(it)
                self._queued_rows += it.n
            depth = self._queued_rows
            self._cond.notify_all()
        with self._lock:
            self._n_requests += 1
            self._n_rows += data.shape[0]
            for it in items:
                self._size_hist[it.n] = self._size_hist.get(it.n, 0) + 1
        for it in items:
            self._req_hist.observe(it.n)
        telemetry.inc("serve.requests")
        telemetry.inc("serve.rows", data.shape[0])
        telemetry.set_gauge("serve.queue_depth", depth)
        if len(items) == 1:
            return items[0].future
        return _JoinedFuture([it.future for it in items])

    # -- dispatchers -------------------------------------------------------
    def _collect(self) -> Optional[List[_WorkItem]]:
        """Admission policy: block for work, then coalesce queued
        items up to max_batch rows, waiting at most max_wait_ms past
        the FIRST item's submit time for the batch to fill
        (fill-or-timeout). Returns None when stopping and drained."""
        with self._cond:
            while not self._queue:
                if self._draining:
                    return None
                self._cond.wait(0.05)
            first = self._queue.popleft()
            # coalesce stamp: end of this item's queue phase (request
            # tracing's queue-vs-device cut)
            first.t_collect = time.monotonic()
            items = [first]
            total = first.n
            deadline = first.t_submit + self.max_wait_ms / 1e3
            while total < self.max_batch:
                if self._queue:
                    if self._queue[0].n <= self.max_batch - total:
                        it = self._queue.popleft()
                        it.t_collect = time.monotonic()
                        items.append(it)
                        total += it.n
                        continue
                    break  # head doesn't fit: ship what we have
                wait = deadline - time.monotonic()
                if wait <= 0 or self._draining:
                    break
                self._cond.wait(min(wait, 0.05))
            self._queued_rows -= total
            telemetry.set_gauge("serve.queue_depth", self._queued_rows)
            return items

    def _run_batch(self, items: List[_WorkItem]) -> None:
        from cxxnet_tpu.parallel import distributed
        total = sum(it.n for it in items)
        bucket = next(b for b in self.buckets if b >= total)
        data = np.concatenate([it.data for it in items], axis=0)
        extras = [
            np.concatenate([it.extras[i] for it in items], axis=0)
            for i in range(len(self._extra_dims))]
        if bucket > total:
            pad = bucket - total
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], data.dtype)],
                axis=0)
            extras = [np.concatenate(
                [e, np.zeros((pad,) + e.shape[1:], e.dtype)], axis=0)
                for e in extras]
        tel = telemetry.get()
        fp = self._exec_fp.get(bucket, "")
        fl = None
        if tel.flight.enabled:
            # dispatch flight record: opened BEFORE staging (a hung
            # backend blocks inside device_put / the dispatch / the
            # readback below, leaving this entry in-flight with the
            # exact executable fingerprint + request trace on it)
            fl = tel.flight.start(
                "serve", fp=fp, bucket=bucket, nbytes=int(data.nbytes),
                trace=items[0].trace,
                fields={"rows": total, "requests": len(items)})
        t_dispatch = time.monotonic()
        try:
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            out = self._fn(self.trainer.state["params"], gdata, gextras)
            rows = distributed.fetch_local(out)
        except BaseException as e:
            # a FAILED dispatch must not read as a hung one: the
            # replica recovers and keeps serving, so close the flight
            # entry with the error instead of leaving it in-flight
            # forever (only a dispatch that never returns stays open)
            tel.flight.fail(fl, f"{type(e).__name__}: {e}")
            raise
        rows = rows.reshape(bucket, -1)
        t_done = time.monotonic()
        tel.flight.finish(fl)
        if fp:
            tel.executables.count_dispatch(fp, secs=t_done - t_dispatch)
        off = 0
        for it in items:
            it.future._set(rows[off:off + it.n])
            off += it.n
            self._lat.observe(t_done - it.t_submit)
            telemetry.observe("serve.latency_s", t_done - it.t_submit)
            # queue-vs-device breakdown per traced request part: the
            # cut is at DISPATCH, not at queue-pop - the fill-or-
            # timeout coalesce wait after the pop is host-side
            # admission latency and must not be billed to the device
            # (it would misdirect a p99 investigation toward the
            # accelerator); t_collect still rides the trace record so
            # the export can render the coalesce boundary
            queue_s = max(t_dispatch - it.t_submit, 0.0)
            device_s = max(t_done - t_dispatch, 0.0)
            self._qlat.observe(queue_s)
            self._dlat.observe(device_s)
            telemetry.observe("serve.queue_s", queue_s)
            telemetry.observe("serve.device_s", device_s)
            # one trace record per resolved part (no-op with no event
            # sink armed): the complete span set tools/trace_export.py
            # renders to Chrome trace-event JSON
            tel.event("trace", trace=it.trace, part=it.part,
                      parts=it.nparts, rows=it.n, bucket=bucket,
                      fp=fp, t_submit=round(it.t_submit, 6),
                      t_collect=round(it.t_collect, 6),
                      t_dispatch=round(t_dispatch, 6),
                      t_done=round(t_done, 6),
                      queue_ms=round(queue_s * 1e3, 3),
                      device_ms=round(device_s * 1e3, 3))
        with self._lock:
            self._n_batches += 1
            self._n_padding += bucket - total
            self._bucket_hits[bucket] += 1
        telemetry.inc("serve.batches")
        telemetry.inc("serve.padding_rows", bucket - total)
        # serving progress beacon: a wedged dispatch (hung backend)
        # stops marking and the watchdog dumps the stuck replica stack
        telemetry.beacon("serve.batch")

    def _replica_loop(self) -> None:
        while True:
            items = self._collect()
            if items is None:
                return
            try:
                self._run_batch(items)
            except BaseException as e:  # noqa: BLE001 - delivered via futures
                with self._lock:
                    self._n_errors += 1
                telemetry.inc("serve.errors")
                telemetry.stderr(
                    f"serve: dispatch failed: {type(e).__name__}: {e}\n",
                    event_kind="serve", op="error",
                    error=f"{type(e).__name__}: {e}")
                for it in items:
                    if not it.future.done():
                        it.future._set_error(e)

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Product-surface summary: request/row/batch/padding counts,
        per-bucket dispatch counts, and latency p50/p99 (ms) from the
        registry histogram."""
        with self._lock:
            out: Dict[str, Any] = {
                "requests": self._n_requests,
                "rows": self._n_rows,
                "batches": self._n_batches,
                "padding_rows": self._n_padding,
                "errors": self._n_errors,
                "buckets": {b: n for b, n in self._bucket_hits.items()},
                "request_sizes": dict(self._size_hist),
            }
        out["warmup_s"] = round(self.warmup_s, 4)
        for hist, stem in ((self._lat, "latency"),
                           (self._qlat, "queue"),
                           (self._dlat, "device")):
            for q in (50, 99):
                v = hist.percentile(q)
                out[f"{stem}_p{q}_ms"] = (round(v * 1e3, 3)
                                          if v == v else None)
        return out
