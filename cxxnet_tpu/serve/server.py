"""Continuous-batching inference server (docs/SERVING.md).

The predict/extract tasks are batch-at-a-time, train-shaped code: one
caller, one fixed batch, one padded dispatch. Production serving is
the opposite shape - many concurrent callers submitting a few rows
each - and the TF-paper framing (PAPERS.md, arXiv:1605.08695) treats
it as the same dataflow system with a different driver. This module is
that driver:

- a **shared request queue**: `submit()` is thread-safe and returns a
  future; requests larger than the biggest bucket split internally and
  re-join on `result()`;
- **continuous/dynamic batching into padded buckets**: dispatchers
  coalesce queued requests up to `max_batch` rows and run the smallest
  power-of-two bucket that covers them, padding the tail. Every bucket
  size is a distinct program shape of ONE jitted inference executable
  (trainer's `infer_fn`), so the bucket set compiles once;
- **warmed executables**: `warmup()` runs every bucket once at
  startup. Steady state then performs ZERO recompiles - provable via
  the same `_cache_size` technique the jaxpr audit uses
  (`executable_cache_size()` == `len(buckets)` and stays flat);
- **replica fan-out**: `replicas` dispatcher threads drain the shared
  queue; each dispatch is the SPMD executable over the full mesh (on
  `mesh = data:N` the bucket's rows spread over the data axis), and
  jax's async dispatch lets replicas pipeline host staging against
  device compute. `zero_stage = 3` params are consumed directly at
  their stored (sharded) layout - the executable's in_shardings are
  the trainer's `pstore`, so no host-side gather ever runs;
- an **admission/flush policy**: a dispatcher waits up to
  `max_wait_ms` for the bucket to fill, then flushes what it has
  (fill-or-timeout), so p99 latency stays bounded under low load.

Telemetry (docs/OBSERVABILITY.md): `serve.latency_s` histogram
(p50/p99 through the registry), the `serve.queue_s` / `serve.device_s`
per-request breakdown (request tracing: queue = submit -> dispatch,
incl. the fill-or-timeout coalesce wait; device = dispatch -> result
readback), the `serve.request_rows` Prometheus
histogram over the bucket ladder, `serve.queue_depth` gauge,
`serve.requests`/`serve.rows`/`serve.batches`/`serve.padding_rows`/
`serve.errors` counters. These accumulate unconditionally (they are
the product surface, queried via `Server.stats()`), like the fault
counters - no per-row device sync is added beyond the result readback
serving inherently requires. With the observability plane armed every
dispatch additionally lands in the flight recorder (executable
fingerprint + bucket + trace id - telemetry/flight.py), each warmed
bucket registers on `/executables`, and resolved requests emit `trace`
events that `tools/trace_export.py` renders to Perfetto-loadable
Chrome trace JSON.

The production front (this PR's layer, docs/SERVING.md "Serving over
HTTP" + "Hot-swap runbook"):

- **HTTP request path**: `Server(http_port=N)` (CLI `serve_port=`)
  attaches a `/predict` POST endpoint to the same stdlib listener
  that serves `/metrics`/`/healthz` - rows in, predictions out, trace
  ids minted at ingress so the queue-vs-device decomposition covers
  the network hop;
- **backpressure + load shedding**: a hard `queue_limit` (rows) above
  which `submit()` raises a typed `QueueFullError` and `/predict`
  returns 429 with a `Retry-After` derived from the queue depth and
  the measured drain rate; shedding flips `/healthz` to 503 through
  the health source map (`serve_shed`) until the queue drains below
  half the limit for a hysteresis window, so an LB can rotate the
  replica out and back in;
- **per-request deadlines**: `deadline_ms` (server default or per
  request) expires queued requests BEFORE dispatch - a dead request
  never wastes a bucket slot - surfacing as `DeadlineExpiredError`
  in-process and 504 over HTTP;
- **zero-downtime hot-swap**: `swap_to(path)` (or the `swap_watch=`
  polling thread) validates an atomic checksummed checkpoint (crc32
  trailer), stages the new params to device OUTSIDE any lock, and
  switches between batches under `_swap_lock`; in-flight dispatches
  already bound the old params and finish on the old weights, no
  request drops. A torn/corrupt file is rejected (`swap.rejected`
  event) and the old weights keep serving.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.telemetry.flight import fingerprint as exec_fingerprint
from cxxnet_tpu.utils import fault


class QueueFullError(RuntimeError):
    """submit() rejected: the queue is at `queue_limit` rows (load
    shedding, docs/SERVING.md). Carries the advice an HTTP 429 turns
    into a Retry-After header: `retry_after_s` (queue depth over the
    measured drain rate) and the `queue_depth` at rejection."""

    def __init__(self, msg: str, retry_after_s: float,
                 queue_depth: int) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth


class DeadlineExpiredError(RuntimeError):
    """The request's deadline passed while it was still queued; it was
    dropped before dispatch (a dead request must never spend a bucket
    slot). HTTP callers see 504."""


def bucket_sizes(max_batch: int, data_axis: int = 1) -> Tuple[int, ...]:
    """The padded-batch bucket set: powers of two up to `max_batch`
    that the mesh's data axis divides (a bucket's rows must split
    evenly over the axis), plus `max_batch` itself. At least one
    bucket must exist - a `max_batch` the data axis does not divide
    cannot be dispatched and is rejected here, at configure time."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    out = set()
    b = 1
    while b <= max_batch:
        if b % data_axis == 0:
            out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_buckets(ladder: Sequence[int], max_batch: int,
                   data_axis: int = 1) -> Tuple[int, ...]:
    """An EXPLICIT bucket ladder (the autotuner's telemetry-shaped
    rungs, or `serve_bucket_ladder =` - docs/GRAPH_PASSES.md) folded
    into a valid bucket set: rungs outside [1, max_batch] or not
    divisible by the mesh's data axis are dropped (the
    inapplicable-tuned-value rule - a cache shaped on one mesh must
    not break another), and `max_batch` itself always closes the
    ladder. The max_batch/data-axis contract is bucket_sizes'."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    axis = max(data_axis, 1)
    out = {int(b) for b in ladder
           if 1 <= int(b) <= max_batch and int(b) % axis == 0}
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_from_histogram(hist, max_batch: int, data_axis: int = 1,
                          rungs: int = 4) -> Tuple[int, ...]:
    """Shape a bucket ladder from an observed request-size histogram
    ({size: count}, the Server's `request_sizes` stat): one rung at
    each 1/rungs quantile of the size distribution, rounded UP to the
    data axis, closed by `max_batch`. Sizes the traffic actually
    sends get tight buckets (less padding); sizes it never sends get
    no bucket (fewer warmed executables) - the TVM move of shaping
    the search space from the workload instead of a fixed
    power-of-two set. Falls back to bucket_sizes on an empty
    histogram."""
    sizes = sorted((int(s), int(c)) for s, c in dict(hist).items()
                   if int(c) > 0 and int(s) >= 1)
    if not sizes:
        return bucket_sizes(max_batch, data_axis)
    axis = max(data_axis, 1)
    total = sum(c for _, c in sizes)
    ladder = []
    for r in range(1, max(rungs, 1) + 1):
        target = r * total / max(rungs, 1)
        acc = 0
        for s, c in sizes:
            acc += c
            if acc >= target:
                ladder.append(-(-s // axis) * axis)  # ceil to axis
                break
    return ladder_buckets(ladder, max_batch, data_axis)


def predictions_from_rows(rows: np.ndarray) -> np.ndarray:
    """The TransformPred rule (trainer.predict) applied to raw final-
    node rows: single-column output passes through as scalars, wider
    output argmaxes - so a serve result file is comparable line-for-
    line with a `task = pred` file."""
    rows = np.asarray(rows)
    flat = rows.reshape(rows.shape[0], -1)
    if flat.shape[1] == 1:
        return flat[:, 0]
    return np.argmax(flat, axis=1).astype(np.float32)


class _Future:
    """Minimal one-shot result future (no concurrent.futures executor
    to tie its lifetime to)."""

    __slots__ = ("_ev", "_value", "_error", "trace")

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        # the request trace id (minted at submit; the HTTP front
        # echoes it in the /predict response body)
        self.trace = ""

    def _set(self, value) -> None:
        self._value = value
        self._ev.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._value


class _JoinedFuture:
    """A request that split into several work items: result() is the
    row-concatenation of the parts, in submission order."""

    __slots__ = ("_parts",)

    def __init__(self, parts: List[_Future]) -> None:
        self._parts = parts

    @property
    def trace(self) -> str:
        return self._parts[0].trace if self._parts else ""

    def done(self) -> bool:
        return all(p.done() for p in self._parts)

    def result(self, timeout: Optional[float] = None):
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for p in self._parts:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            out.append(p.result(left))
        return np.concatenate(out, axis=0)


class _WorkItem:
    __slots__ = ("data", "extras", "n", "t_submit", "future",
                 "trace", "part", "nparts", "t_collect", "deadline")

    def __init__(self, data, extras, t_submit, trace="",
                 part=0, nparts=1, deadline=0.0) -> None:
        self.data = data
        self.extras = extras
        self.n = data.shape[0]
        self.t_submit = t_submit
        self.future = _Future()
        # absolute monotonic expiry (0 = none): checked at queue-pop
        # so an expired request drops BEFORE dispatch
        self.deadline = deadline
        # end-to-end request tracing (docs/OBSERVABILITY.md "Request
        # tracing"): the trace id minted at submit(), the part index
        # for oversize requests that split, and the coalesce time a
        # dispatcher stamps when it pops the item; the queue/device
        # latency cut itself is the DISPATCH stamp (_run_batch) -
        # the fill wait after the pop is still queue time
        self.trace = trace
        self.part = part
        self.nparts = nparts
        self.t_collect = 0.0


class Server:
    """Continuous-batching server over a trainer's inference
    executable. The trainer must hold a model (init_model or
    load_model); its mesh, dtype and device_augment spec all apply
    unchanged - serving is the same compiled forward predict runs,
    driven by a queue instead of an iterator.

    start() spawns the dispatcher replicas (warmup() first unless you
    want the first requests to pay the compiles); submit() from any
    thread; stop() drains the queue, joins the replicas and returns
    stats(). Usable as a context manager."""

    def __init__(self, trainer, max_batch: int = 0,
                 max_wait_ms: Optional[float] = None,
                 replicas: Optional[int] = None,
                 node: int = -1,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "0.0.0.0",
                 ladder: Optional[Sequence[int]] = None,
                 http_port: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 swap_watch: Optional[str] = None,
                 swap_poll_ms: Optional[float] = None) -> None:
        import jax
        if trainer.state is None:
            raise RuntimeError(
                "Server needs an initialized trainer (init_model or "
                "load_model first)")
        if jax.process_count() > 1:
            raise RuntimeError(
                "serving a multi-controller job is not supported; run "
                "the server on a single-process mesh")
        self.trainer = trainer
        self.max_batch = int(max_batch or trainer.serve_max_batch
                             or trainer.batch_size)
        self.max_wait_ms = float(
            trainer.serve_max_wait_ms if max_wait_ms is None
            else max_wait_ms)
        self.replicas = int(trainer.serve_replicas if replicas is None
                            else replicas)
        if self.replicas < 1:
            raise ValueError("serve_replicas must be >= 1")
        self.node = (node if node >= 0
                     else trainer.net_cfg.num_nodes - 1)
        dsize = trainer.mesh.shape.get("data", 1)
        # explicit ladder > trainer's (tuned or serve_bucket_ladder =)
        # ladder > the power-of-two default - the same
        # explicit-keys-win chain the scalar serve knobs ride
        lad = (ladder if ladder is not None
               else getattr(trainer, "serve_ladder", None))
        self.buckets = (ladder_buckets(lad, self.max_batch, dsize)
                        if lad else
                        bucket_sizes(self.max_batch, dsize))
        if getattr(trainer, "passes_need_calibration",
                   lambda: False)():
            # a calibrating pass (fold_conv_bn / quantize_int8)
            # without stats: the infer executable built below is the
            # un-rewritten FLOAT graph (safe, just unoptimized) and
            # stays so for this Server's lifetime - warmup on zeros
            # must never become the calibration batch (zero-input
            # moments and activation ranges would be garbage).
            # task=serve calibrates from the first pred batch before
            # building the Server (main.py); programmatic users call
            # trainer.calibrate_graph_passes (or predict once) first.
            telemetry.stderr(
                "serve: graph passes (fold_conv_bn/quantize_int8) "
                "have no calibration stats; serving the unoptimized "
                "float graph (calibrate before Server creation to "
                "fold/quantize)\n",
                event_kind="serve", op="fold_uncalibrated")
        self._fn = trainer._infer_fn(self.node)
        c, y, x = trainer.net_cfg.input_shape
        self._input_dims = (c, y, x)
        self._extra_dims = [
            tuple(trainer.net.node_shapes[1 + i][1:])
            for i in range(trainer.net_cfg.extra_data_num)]
        # attachable live-exposition server (docs/OBSERVABILITY.md):
        # metrics_port=N serves /metrics + /healthz + /varz for the
        # Server's lifetime (0 = ephemeral bind, read .metrics_server
        # .port). None = off; programmatic twins of the CLI key, which
        # arms the process-wide plane in main.run instead.
        # http_port=N (CLI serve_port=) attaches the SAME listener
        # plus the /predict request path - one socket, both surfaces;
        # specifying both ports with different values is an error.
        if http_port is None:
            cfg_port = int(getattr(trainer, "serve_port", 0) or 0)
            if cfg_port > 0:
                http_port = cfg_port
        if (http_port is not None and metrics_port is not None
                and int(http_port) != int(metrics_port)):
            raise ValueError(
                "serve_port and metrics_port attach ONE listener; "
                f"set them equal or drop one (got {http_port} vs "
                f"{metrics_port})")
        self.http_port = http_port
        self.metrics_port = (metrics_port if metrics_port is not None
                             else http_port)
        self.metrics_host = metrics_host
        self.metrics_server = None
        if self.metrics_port is not None:
            # the attached exposition endpoint is a flight-recorder
            # consumer (it serves the /varz tail and /executables) -
            # arm the recorder for this Server's lifetime, the same
            # rule arm_observability applies to the process-wide
            # plane. Armed HERE (not in start()) so warmup()'s cost
            # enrichment sees it: warmup conventionally runs before
            # start(). stop() re-derives from the remaining consumers.
            telemetry.get().flight.enabled = True
        self._cond = threading.Condition()
        # admission state: the queue, its row count and the drain flag
        # move together under the condition (checked statically -
        # docs/STATIC_ANALYSIS.md GL016)
        self._queue: collections.deque = collections.deque()
        # guarded-by: self._cond
        self._queued_rows = 0
        self._threads: List[threading.Thread] = []
        # guarded-by: self._cond
        self._draining = False
        self._started = False
        self.warmup_s = 0.0
        # backpressure (docs/SERVING.md "Serving over HTTP"): hard
        # queue bound in ROWS (0 = unlimited), the default request
        # deadline, and the shed->healthy hysteresis window
        self.queue_limit = int(
            trainer.serve_queue_limit if queue_limit is None
            else queue_limit)
        self.deadline_ms = float(
            trainer.serve_deadline_ms if deadline_ms is None
            else deadline_ms)
        self.shed_clear_ms = float(
            getattr(trainer, "serve_shed_clear_ms", 1000.0))
        # guarded-by: self._cond
        self._last_shed_t = 0.0
        # whether this Server currently holds the `serve_shed` source
        # unhealthy (503 on /healthz); cleared with hysteresis once
        # the queue drains below queue_limit/2 for shed_clear_ms
        # guarded-by: self._cond
        self._shed_health = False
        # checkpoint hot-swap (docs/SERVING.md "Hot-swap runbook"):
        # _swap_lock orders the params/fn switch against dispatch
        # snapshots; ONLY attribute reads/writes happen under it -
        # staging (device_put) and warmup stay outside (GL015)
        self._swap_lock = threading.Lock()
        self.swap_watch = (swap_watch if swap_watch is not None
                           else getattr(trainer, "swap_watch", "")) or ""
        self.swap_poll_ms = float(
            getattr(trainer, "swap_poll_ms", 200.0)
            if swap_poll_ms is None else swap_poll_ms)
        self._swap_thread: Optional[threading.Thread] = None
        # watcher shutdown signal (checked each poll tick)
        self._swap_stop = threading.Event()
        # last (mtime_ns, size) the watcher acted on - recorded even
        # for a REJECTED file so a torn checkpoint is skipped once,
        # not re-validated in a hot loop
        # guarded-by: self._swap_lock
        self._swap_seen: Optional[Tuple[int, int]] = None
        # product-surface accounting, independent of the process-wide
        # registry (a second Server in one process must not inherit
        # the first one's counts OR its latency window); the registry
        # mirrors everything for the metrics stream/report
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._n_requests = 0
        # guarded-by: self._lock
        self._n_rows = 0
        # guarded-by: self._lock
        self._n_batches = 0
        # guarded-by: self._lock
        self._n_padding = 0
        # guarded-by: self._lock
        self._n_errors = 0
        # guarded-by: self._lock
        self._n_shed = 0
        # guarded-by: self._lock
        self._n_shed_rows = 0
        # guarded-by: self._lock
        self._n_expired = 0
        # guarded-by: self._lock
        self._n_swaps = 0
        # guarded-by: self._lock
        self._n_swap_rejected = 0
        # measured drain rate (rows/s, EWMA over dispatched batches):
        # what Retry-After is derived from
        # guarded-by: self._lock
        self._drain_rate = 0.0
        # guarded-by: self._lock
        self._last_drain_t = 0.0
        # guarded-by: self._lock
        self._bucket_hits: Dict[int, int] = {b: 0 for b in self.buckets}
        # request-size histogram: the serve telemetry the autotuner's
        # ladder_from_histogram shapes the bucket ladder from
        # (docs/GRAPH_PASSES.md "per-layer autotuner"); counts per
        # submitted work-item row count
        # guarded-by: self._lock
        self._size_hist: Dict[int, int] = {}
        self._lat = telemetry.Histogram()
        # per-request queue-vs-device decomposition (request tracing):
        # queue = submit -> coalesce, device = coalesce -> result
        self._qlat = telemetry.Histogram()
        self._dlat = telemetry.Histogram()
        # request-size distribution as a proper Prometheus histogram
        # on /metrics (bounds = this Server's bucket ladder); the
        # dict-shaped stats()["request_sizes"] stays for the autotuner
        self._req_hist = telemetry.get().registry.bucket_histogram(
            "serve.request_rows", bounds=self.buckets)
        # request-trace ids minted at submit(); executable
        # fingerprints per warmed bucket (filled by warmup) feed the
        # flight recorder + /executables registry (telemetry/flight.py)
        self._trace_seq = itertools.count(1)
        self._exec_fp: Dict[int, str] = {}

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> float:
        """Compile + run every bucket executable once (zeros input) so
        steady-state serving never compiles. Returns the wall seconds
        spent; also recorded as `serve.warmup_s`."""
        import jax
        t0 = time.perf_counter()
        params = self.trainer.state["params"]
        tel = telemetry.get()
        epoch = getattr(self.trainer, "_fold_epoch", 0)
        for b in self.buckets:
            data = np.zeros((b,) + self._input_dims, np.float32)
            extras = [np.zeros((b,) + d, np.float32)
                      for d in self._extra_dims]
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            tb = time.perf_counter()
            jax.block_until_ready(self._fn(params, gdata, gextras))
            compile_s = time.perf_counter() - tb
            # executable registry (telemetry/flight.py): one entry per
            # warmed bucket program shape, stamped with its compile
            # wall-time (warmup's block IS the compile window). The
            # fingerprint is what flight entries and stall dumps name.
            fp = exec_fingerprint(
                "serve.infer", self.node, b, self._input_dims,
                epoch)
            self._exec_fp[b] = fp
            tel.executables.register(
                fp, name=f"serve.infer:b{b}", kind="serve",
                shape=str((b,) + self._input_dims),
                arg_bytes=int(data.nbytes
                              + sum(e.nbytes for e in extras)),
                device=jax.default_backend(), donated=0,
                compile_s=compile_s)
            if tel.flight.enabled:
                # armed plane: enrich with XLA cost analysis + output
                # footprint (one extra trace/lowering per bucket,
                # sanctioned here in the warmup window; the jit cache
                # the zero-recompile audit counts is untouched)
                tel.executables.enrich(fp, self._fn,
                                       (params, gdata, gextras))
        self.warmup_s = time.perf_counter() - t0
        telemetry.observe("serve.warmup_s", self.warmup_s)
        telemetry.event("serve", op="warmup", buckets=list(self.buckets),
                        secs=self.warmup_s)
        return self.warmup_s

    def executable_cache_size(self) -> Optional[int]:
        """Compiled-program count of the inference executable (the
        jaxpr audit's `_cache_size` technique): after warmup this
        equals len(buckets) and must stay flat under any steady-state
        request mix - the zero-recompile proof."""
        fn = getattr(self._fn, "_cache_size", None)
        return fn() if callable(fn) else None

    def start(self) -> "Server":
        if self._started:
            return self
        if self.metrics_port is not None and self.metrics_server is None:
            from cxxnet_tpu.telemetry.http import ObservabilityServer
            self.metrics_server = ObservabilityServer(
                telemetry.get(), int(self.metrics_port),
                host=self.metrics_host,
                predict_backend=(self if self.http_port is not None
                                 else None))
            self.metrics_server.start()
            telemetry.event("observability", op="http_start",
                            port=self.metrics_server.port,
                            host=self.metrics_host,
                            predict=self.http_port is not None)
        with self._cond:
            # published under the lock that guards it: a replica from
            # a previous start/stop cycle draining late must not read
            # a torn flag
            self._draining = False
        self._started = True
        for i in range(self.replicas):
            t = threading.Thread(target=self._replica_loop,
                                 name=f"serve-replica-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        if self.swap_watch and self._swap_thread is None:
            # checkpoint watcher: the file's CURRENT state counts as
            # already-served (the Server was presumably built from
            # it); only a subsequent publish triggers a swap
            with self._swap_lock:
                self._swap_seen = self._swap_stat()
            self._swap_stop.clear()
            self._swap_thread = threading.Thread(
                target=self._swap_watch_loop,
                name="serve-swap-watch", daemon=True)
            self._swap_thread.start()
        return self

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        """Stop the replicas - after draining the queue (default), or
        immediately failing queued requests (drain=False) - and return
        stats(). Idempotent."""
        if self._swap_thread is not None:
            self._swap_stop.set()
            self._swap_thread.join(timeout=10.0)
            self._swap_thread = None
        with self._cond:
            self._draining = True
            if not drain:
                while self._queue:
                    it = self._queue.popleft()
                    self._queued_rows -= it.n
                    it.future._set_error(
                        RuntimeError("server stopped before dispatch"))
            self._cond.notify_all()
            shed_held = self._shed_health
            self._shed_health = False
        if shed_held:
            # a stopped server is not "overloaded"; release the 503
            # so a restart doesn't inherit a stale verdict
            telemetry.get().health.clear("serve_shed")
        for t in self._threads:
            t.join(timeout=60.0)
        self._threads = []
        self._started = False
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self.metrics_port is not None:
            # this Server's endpoint was a flight consumer; re-derive
            # the recorder's armed state from whatever remains (sinks,
            # the process-wide plane, an explicit flight_recorder=1)
            telemetry.get()._refresh_flight()
        telemetry.set_gauge("serve.queue_depth", 0.0)
        stats = self.stats()
        telemetry.event("serve", op="stop", **{
            k: v for k, v in stats.items() if not isinstance(v, dict)})
        return stats

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- submission --------------------------------------------------------
    def submit(self, data: np.ndarray, extras: Sequence = (),
               deadline_ms: Optional[float] = None):
        """Enqueue one request: data is (n, c, y, x) rows or a single
        (c, y, x) instance; extras (if the net declares extra inputs)
        ride along row-aligned. Returns a future whose result() is the
        raw final-node rows, (n, width) - predictions_from_rows turns
        them into predict()-style labels. Thread-safe; requests wider
        than the largest bucket split transparently.

        `deadline_ms` overrides the server default (serve_deadline_ms;
        0 = none): a request still queued past its deadline is dropped
        BEFORE dispatch and its future raises DeadlineExpiredError.
        With `queue_limit` set, a submit that would push the queue
        past the limit raises QueueFullError instead of enqueueing
        (load shedding - the HTTP front maps it to 429+Retry-After)."""
        if not self._started:
            raise RuntimeError("Server not started (call start())")
        data = np.ascontiguousarray(data)
        if data.ndim == 3:
            data = data[None]
        if data.ndim != 4 or data.shape[1:] != self._input_dims:
            raise ValueError(
                f"serve request must be (n, {self._input_dims[0]}, "
                f"{self._input_dims[1]}, {self._input_dims[2]}) or a "
                f"single instance; got {data.shape}")
        if data.shape[0] < 1:
            raise ValueError("serve request needs at least one row")
        extras = [np.ascontiguousarray(e, dtype=np.float32)
                  for e in extras]
        if len(extras) != len(self._extra_dims):
            raise ValueError(
                f"net declares {len(self._extra_dims)} extra inputs "
                f"but the request carries {len(extras)}")
        for e in extras:
            if e.shape[0] != data.shape[0]:
                raise ValueError("extras must be row-aligned with data")
        t_submit = time.monotonic()
        # request trace id (docs/OBSERVABILITY.md "Request tracing"):
        # minted once per submit and shared by every split part, so an
        # oversize request renders as ONE span tree in the exported
        # Chrome trace; pid-scoped so multi-process traces merge
        trace = f"{os.getpid():x}-{next(self._trace_seq):06d}"
        eff_ms = (self.deadline_ms if deadline_ms is None
                  else float(deadline_ms))
        deadline = t_submit + eff_ms / 1e3 if eff_ms > 0 else 0.0
        nparts = -(-data.shape[0] // self.max_batch)
        items = []
        for part, lo in enumerate(
                range(0, data.shape[0], self.max_batch)):
            hi = lo + self.max_batch
            items.append(_WorkItem(
                data[lo:hi], [e[lo:hi] for e in extras], t_submit,
                trace=trace, part=part, nparts=nparts,
                deadline=deadline))
        items[0].future.trace = trace
        shed_depth = -1
        with self._cond:
            if self._draining:
                raise RuntimeError("server is stopping")
            if (self.queue_limit > 0 and
                    self._queued_rows + data.shape[0]
                    > self.queue_limit):
                # hard admission bound: reject, do NOT enqueue. The
                # shed verdict (503 on /healthz) holds until the
                # queue drains below half the limit for the
                # hysteresis window (_maybe_recover)
                shed_depth = self._queued_rows
                self._last_shed_t = t_submit
                flip = not self._shed_health
                self._shed_health = True
            else:
                for it in items:
                    self._queue.append(it)
                    self._queued_rows += it.n
                depth = self._queued_rows
                self._cond.notify_all()
        if shed_depth >= 0:
            retry_s = self._retry_after(shed_depth + data.shape[0])
            with self._lock:
                self._n_shed += 1
                self._n_shed_rows += data.shape[0]
            telemetry.inc("serve.shed_total")
            telemetry.inc("serve.shed_rows", data.shape[0])
            if flip:
                reason = (f"load shed: queue {shed_depth} rows + "
                          f"{data.shape[0]} > limit {self.queue_limit}")
                telemetry.get().health.set_unhealthy(
                    "serve_shed", reason)
                telemetry.event("serve", op="shed",
                                queue_depth=shed_depth,
                                limit=self.queue_limit)
            raise QueueFullError(
                f"serve queue full ({shed_depth} rows >= limit "
                f"{self.queue_limit}); retry in {retry_s:.2f}s",
                retry_after_s=retry_s, queue_depth=shed_depth)
        with self._lock:
            self._n_requests += 1
            self._n_rows += data.shape[0]
            for it in items:
                self._size_hist[it.n] = self._size_hist.get(it.n, 0) + 1
        for it in items:
            self._req_hist.observe(it.n)
        telemetry.inc("serve.requests")
        telemetry.inc("serve.rows", data.shape[0])
        telemetry.set_gauge("serve.queue_depth", depth)
        if len(items) == 1:
            return items[0].future
        return _JoinedFuture([it.future for it in items])

    # -- backpressure helpers ----------------------------------------------
    def _retry_after(self, backlog_rows: int) -> float:
        """Retry-After advice for a shed request: the time the current
        backlog takes to drain at the measured (EWMA) drain rate,
        clamped to [0.1s, 60s]. Before any batch has dispatched the
        rate is unknown and the floor applies."""
        with self._lock:
            rate = self._drain_rate
        if rate <= 0:
            return 1.0
        return min(60.0, max(0.1, backlog_rows / rate))

    def _maybe_recover(self) -> None:
        """Shed->healthy hysteresis: clear the `serve_shed` health
        verdict once the queue has drained below HALF the limit AND
        no shed happened for shed_clear_ms - a single drained batch
        amid a storm must not flap /healthz."""
        now = time.monotonic()
        cleared = False
        with self._cond:
            if (self._shed_health
                    and self._queued_rows * 2 < max(self.queue_limit, 1)
                    and (now - self._last_shed_t)
                    >= self.shed_clear_ms / 1e3):
                self._shed_health = False
                cleared = True
        if cleared:
            telemetry.get().health.clear("serve_shed")
            telemetry.event("serve", op="shed_recovered",
                            limit=self.queue_limit)

    def _fail_expired(self, it: _WorkItem, now: float) -> None:
        """Resolve a deadline-expired item (called OUTSIDE _cond: the
        future Event set + registry counters need no queue state)."""
        with self._lock:
            self._n_expired += 1
        telemetry.inc("serve.deadline_expired")
        waited_ms = (now - it.t_submit) * 1e3
        it.future._set_error(DeadlineExpiredError(
            f"request deadline expired after {waited_ms:.1f} ms in "
            "queue (dropped before dispatch)"))
        telemetry.event("serve", op="deadline_expired",
                        trace=it.trace, part=it.part, rows=it.n,
                        waited_ms=round(waited_ms, 3))

    # -- dispatchers -------------------------------------------------------
    def _collect(self) -> Optional[List[_WorkItem]]:
        """Admission policy: block for work, then coalesce queued
        items up to max_batch rows, waiting at most max_wait_ms past
        the FIRST item's submit time for the batch to fill
        (fill-or-timeout). Deadline-expired items are dropped here,
        before a bucket slot is spent on them. Returns None when
        stopping and drained; an empty list means "nothing live this
        round, loop again" (everything popped had expired)."""
        expired: List[_WorkItem] = []
        items = self._collect_locked(expired)
        if expired:
            now = time.monotonic()
            for it in expired:
                self._fail_expired(it, now)
        if items is not None:
            self._maybe_recover()
        return items

    def _collect_locked(
            self, expired: List[_WorkItem]
    ) -> Optional[List[_WorkItem]]:
        with self._cond:
            first = None
            while first is None:
                if not self._queue:
                    if self._draining:
                        return None
                    if expired:
                        # resolve the drops promptly instead of
                        # blocking here with their futures pending
                        break
                    if (self._shed_health and self._queued_rows * 2
                            < max(self.queue_limit, 1)
                            and time.monotonic() - self._last_shed_t
                            >= self.shed_clear_ms / 1e3):
                        # storm over, traffic gone: surface so the
                        # caller can clear the shed 503 (recovery
                        # must not wait for the next request)
                        break
                    self._cond.wait(0.05)
                    continue
                # pop the next un-expired item; expired ones
                # accumulate for post-lock resolution
                now = time.monotonic()
                while self._queue:
                    it = self._queue.popleft()
                    self._queued_rows -= it.n
                    if it.deadline and now > it.deadline:
                        expired.append(it)
                        continue
                    first = it
                    break
            if first is None:
                telemetry.set_gauge("serve.queue_depth",
                                    self._queued_rows)
                return []
            # coalesce stamp: end of this item's queue phase (request
            # tracing's queue-vs-device cut)
            first.t_collect = time.monotonic()
            items = [first]
            total = first.n
            deadline = first.t_submit + self.max_wait_ms / 1e3
            while total < self.max_batch:
                if self._queue:
                    head = self._queue[0]
                    if head.deadline and time.monotonic() > head.deadline:
                        self._queue.popleft()
                        self._queued_rows -= head.n
                        expired.append(head)
                        continue
                    if head.n <= self.max_batch - total:
                        it = self._queue.popleft()
                        self._queued_rows -= it.n
                        it.t_collect = time.monotonic()
                        items.append(it)
                        total += it.n
                        continue
                    break  # head doesn't fit: ship what we have
                wait = deadline - time.monotonic()
                if wait <= 0 or self._draining:
                    break
                self._cond.wait(min(wait, 0.05))
            telemetry.set_gauge("serve.queue_depth", self._queued_rows)
            return items

    def _run_batch(self, items: List[_WorkItem]) -> None:
        from cxxnet_tpu.parallel import distributed
        total = sum(it.n for it in items)
        bucket = next(b for b in self.buckets if b >= total)
        data = np.concatenate([it.data for it in items], axis=0)
        extras = [
            np.concatenate([it.extras[i] for it in items], axis=0)
            for i in range(len(self._extra_dims))]
        if bucket > total:
            pad = bucket - total
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], data.dtype)],
                axis=0)
            extras = [np.concatenate(
                [e, np.zeros((pad,) + e.shape[1:], e.dtype)], axis=0)
                for e in extras]
        tel = telemetry.get()
        fp = self._exec_fp.get(bucket, "")
        fl = None
        if tel.flight.enabled:
            # dispatch flight record: opened BEFORE staging (a hung
            # backend blocks inside device_put / the dispatch / the
            # readback below, leaving this entry in-flight with the
            # exact executable fingerprint + request trace on it)
            fl = tel.flight.start(
                "serve", fp=fp, bucket=bucket, nbytes=int(data.nbytes),
                trace=items[0].trace,
                fields={"rows": total, "requests": len(items)})
        t_dispatch = time.monotonic()
        try:
            # serve-side fault points (utils/fault.py, CXXNET_FAULT):
            # delay stalls the dispatch (deadline/backpressure tests),
            # error crashes it (the replica recovers, futures fail)
            fault.fault_point("serve_dispatch_delay")
            fault.fault_point("serve_dispatch_error")
            # hot-swap consistency: snapshot (fn, params) under the
            # swap lock so a batch binds ONE weight generation; the
            # dispatch itself runs outside the lock (GL015 - never
            # hold a lock across a jax boundary). An in-flight batch
            # that snapshotted before a swap finishes on old weights.
            with self._swap_lock:
                fn = self._fn
                params = self.trainer.state["params"]
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            out = fn(params, gdata, gextras)
            rows = distributed.fetch_local(out)
        except BaseException as e:
            # a FAILED dispatch must not read as a hung one: the
            # replica recovers and keeps serving, so close the flight
            # entry with the error instead of leaving it in-flight
            # forever (only a dispatch that never returns stays open)
            tel.flight.fail(fl, f"{type(e).__name__}: {e}")
            raise
        rows = rows.reshape(bucket, -1)
        t_done = time.monotonic()
        tel.flight.finish(fl)
        if fp:
            tel.executables.count_dispatch(fp, secs=t_done - t_dispatch)
        off = 0
        for it in items:
            it.future._set(rows[off:off + it.n])
            off += it.n
            self._lat.observe(t_done - it.t_submit)
            telemetry.observe("serve.latency_s", t_done - it.t_submit)
            # queue-vs-device breakdown per traced request part: the
            # cut is at DISPATCH, not at queue-pop - the fill-or-
            # timeout coalesce wait after the pop is host-side
            # admission latency and must not be billed to the device
            # (it would misdirect a p99 investigation toward the
            # accelerator); t_collect still rides the trace record so
            # the export can render the coalesce boundary
            queue_s = max(t_dispatch - it.t_submit, 0.0)
            device_s = max(t_done - t_dispatch, 0.0)
            self._qlat.observe(queue_s)
            self._dlat.observe(device_s)
            telemetry.observe("serve.queue_s", queue_s)
            telemetry.observe("serve.device_s", device_s)
            # one trace record per resolved part (no-op with no event
            # sink armed): the complete span set tools/trace_export.py
            # renders to Chrome trace-event JSON
            tel.event("trace", trace=it.trace, part=it.part,
                      parts=it.nparts, rows=it.n, bucket=bucket,
                      fp=fp, t_submit=round(it.t_submit, 6),
                      t_collect=round(it.t_collect, 6),
                      t_dispatch=round(t_dispatch, 6),
                      t_done=round(t_done, 6),
                      queue_ms=round(queue_s * 1e3, 3),
                      device_ms=round(device_s * 1e3, 3))
        with self._lock:
            self._n_batches += 1
            self._n_padding += bucket - total
            self._bucket_hits[bucket] += 1
            # drain-rate EWMA (rows/s across all replicas): Retry-After
            # advice for shed requests derives from it. Measured over
            # inter-completion gaps so replica overlap and admission
            # waits are priced in, not just device time.
            if self._last_drain_t > 0:
                gap = t_done - self._last_drain_t
                if gap > 1e-6:
                    inst = total / gap
                    self._drain_rate = (
                        inst if self._drain_rate <= 0
                        else 0.7 * self._drain_rate + 0.3 * inst)
            self._last_drain_t = t_done
        telemetry.inc("serve.batches")
        telemetry.inc("serve.padding_rows", bucket - total)
        # serving progress beacon: a wedged dispatch (hung backend)
        # stops marking and the watchdog dumps the stuck replica stack
        telemetry.beacon("serve.batch")

    def _replica_loop(self) -> None:
        while True:
            items = self._collect()
            if items is None:
                return
            if not items:
                # nothing live this round (expired drops resolved /
                # shed recovery surfaced) - nothing to dispatch
                continue
            try:
                self._run_batch(items)
            except BaseException as e:  # noqa: BLE001 - delivered via futures
                with self._lock:
                    self._n_errors += 1
                telemetry.inc("serve.errors")
                telemetry.stderr(
                    f"serve: dispatch failed: {type(e).__name__}: {e}\n",
                    event_kind="serve", op="error",
                    error=f"{type(e).__name__}: {e}")
                for it in items:
                    if not it.future.done():
                        it.future._set_error(e)

    # -- checkpoint hot-swap -----------------------------------------------
    def _swap_stat(self) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(self.swap_watch)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def _swap_watch_loop(self) -> None:
        """Poll the published-checkpoint path every swap_poll_ms and
        swap on any (mtime, size) change. The stat is recorded before
        the attempt, so a rejected (torn) file is skipped ONCE and
        not re-validated in a hot loop; publishing a fixed file
        changes the stat again and retries."""
        poll_s = max(self.swap_poll_ms, 10.0) / 1e3
        while not self._swap_stop.wait(poll_s):
            cur = self._swap_stat()
            with self._swap_lock:
                if cur is None or cur == self._swap_seen:
                    continue
                self._swap_seen = cur
            try:
                self.swap_to(self.swap_watch)
            except BaseException as e:  # noqa: BLE001 - keep serving
                telemetry.stderr(
                    f"serve: swap attempt failed: "
                    f"{type(e).__name__}: {e}\n",
                    event_kind="swap", op="error",
                    error=f"{type(e).__name__}: {e}")

    def _params_mismatch(self, cur, new) -> Optional[str]:
        """A swap must be weight-compatible with the warmed
        executables: identical param tree (layer/param keys) and leaf
        shapes. Returns the first mismatch as a reason string."""
        for lk in cur:
            if lk not in new:
                return f"checkpoint missing layer {lk!r}"
            for pn in cur[lk]:
                if pn not in new[lk]:
                    return f"checkpoint missing param {lk}/{pn}"
                want = tuple(cur[lk][pn].shape)
                got = tuple(np.shape(new[lk][pn]))
                if want != got:
                    return (f"shape mismatch at {lk}/{pn}: "
                            f"checkpoint {got} vs serving {want}")
        extra = [f"{lk}/{pn}" for lk in new for pn in new[lk]
                 if lk not in cur or pn not in cur[lk]]
        if extra:
            return f"checkpoint has unknown params: {extra[:3]}"
        return None

    def swap_to(self, path: str) -> bool:
        """Zero-downtime weight swap from an atomic checksummed
        checkpoint (docs/SERVING.md "Hot-swap runbook"): validate the
        crc32 trailer, load, verify the param tree matches, stage the
        new params to device (all outside any lock), then switch
        between batches under _swap_lock. In-flight batches bound the
        old params at dispatch and finish on the old weights; no
        request is dropped. Returns True on an applied swap; a
        torn/corrupt/mismatched checkpoint emits `swap` op=rejected
        and the old weights keep serving (False)."""
        from cxxnet_tpu.nnet import checkpoint
        from cxxnet_tpu.parallel import distributed
        t0 = time.perf_counter()
        blob = None
        reason = checkpoint.validate_file(path)
        if reason is None:
            try:
                with open(path, "rb") as fi:
                    blob = checkpoint.load_model(fi)
            except (OSError, ValueError) as e:
                reason = f"{type(e).__name__}: {e}"
        if reason is None:
            reason = self._params_mismatch(
                self.trainer.state["params"], blob["params"])
        if reason is not None:
            with self._lock:
                self._n_swap_rejected += 1
            telemetry.inc("serve.swap_rejected")
            telemetry.stderr(
                f"serve: checkpoint swap rejected ({path}): "
                f"{reason}\n",
                event_kind="swap", op="rejected", path=path,
                reason=reason)
            return False
        # stage the new weights at the stored sharded layout (the
        # same put_global_full landing set_weight uses) BEFORE taking
        # the swap lock - device_put is a dispatch boundary and must
        # never run under a lock (GL015 / the runtime lock audit)
        cur = self.trainer.state["params"]
        pstore = self.trainer._params_store_shard
        staged = {
            lk: {pn: distributed.put_global_full(
                np.ascontiguousarray(blob["params"][lk][pn]),
                pstore[lk][pn])
                for pn in cur[lk]}
            for lk in cur}
        with self._swap_lock:
            self.trainer.state["params"] = staged
            self.trainer.epoch = int(blob.get("epoch",
                                              self.trainer.epoch))
            old_fold = self.trainer._fold_epoch
            # frozen fold/quant calibration described the OLD weights:
            # retire it (epoch bump + stale-executable eviction, the
            # PR 10/12 mechanism). On the no-passes path this is a
            # no-op and params stay plain jit ARGUMENTS - the swap is
            # a zero-recompile, bitwise switch.
            self.trainer._retire_calibration_state()
            rewarmed = self.trainer._fold_epoch != old_fold
            if rewarmed:
                self._fn = self.trainer._infer_fn(self.node)
        if rewarmed:
            # new fold epoch = new executables: re-warm every bucket
            # so steady state stays recompile-free and /executables
            # lists the new fingerprints (epoch is part of them)
            self.warmup()
        with self._lock:
            self._n_swaps += 1
        telemetry.inc("serve.swaps")
        telemetry.event("swap", op="applied", path=path,
                        epoch=self.trainer.epoch, rewarmed=rewarmed,
                        secs=round(time.perf_counter() - t0, 4))
        return True

    # -- HTTP request path -------------------------------------------------
    def handle_predict(self, body: bytes):
        """The /predict POST backend (telemetry/http.py routes here
        when this Server attached with http_port/serve_port): JSON
        {"data": rows, "extras": [...], "deadline_ms": N, "raw": bool}
        in; {"predictions": [...], "rows": n, "trace": id} out. data
        is (n,c,y,x) nested, flat (n, c*y*x), or one instance. Maps
        QueueFullError -> 429 + Retry-After, deadline expiry/timeout
        -> 504, validation -> 400, dispatch failure -> 500. Returns
        (status, extra_headers, body_bytes)."""
        import json

        def err(code: int, msg: str, **extra):
            payload = {"error": msg}
            payload.update(extra)
            return code, {}, json.dumps(payload).encode()

        t0 = time.monotonic()
        try:
            req = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError):
            return err(400, "request body must be a JSON object")
        if not isinstance(req, dict) or "data" not in req:
            return err(400, 'request JSON needs a "data" field '
                            '(rows to predict)')
        try:
            data = np.asarray(req["data"], dtype=np.float32)
        except (ValueError, TypeError):
            return err(400, '"data" must be a numeric array')
        c, y, x = self._input_dims
        width = c * y * x
        if data.ndim == 1 and data.size == width:
            data = data.reshape(1, c, y, x)
        elif data.ndim == 2 and data.shape[-1] == width:
            data = data.reshape(-1, c, y, x)
        deadline_ms = req.get("deadline_ms")
        try:
            extras = [np.asarray(e, dtype=np.float32)
                      for e in req.get("extras", ())]
            fut = self.submit(data, extras, deadline_ms=deadline_ms)
        except QueueFullError as e:
            # ceil seconds for the header (int per RFC 9110), exact
            # advice in the body; [1, 60] keeps a confused client
            # from either hammering or giving up
            secs = max(1, min(60, int(-(-e.retry_after_s // 1))))
            return (429, {"Retry-After": str(secs)},
                    json.dumps({
                        "error": "queue full (load shed)",
                        "retry_after_s": round(e.retry_after_s, 3),
                        "queue_depth": e.queue_depth}).encode())
        except (ValueError, TypeError) as e:
            return err(400, str(e))
        except RuntimeError as e:
            return err(503, str(e))
        eff_ms = (self.deadline_ms if deadline_ms is None
                  else float(deadline_ms))
        timeout = eff_ms / 1e3 + 5.0 if eff_ms > 0 else 300.0
        try:
            rows = fut.result(timeout=timeout)
        except DeadlineExpiredError as e:
            return err(504, str(e), trace=fut.trace)
        except TimeoutError:
            return err(504, "timed out waiting for the result",
                       trace=fut.trace)
        except BaseException as e:  # noqa: BLE001 - dispatch error -> 500
            return err(500, f"{type(e).__name__}: {e}",
                       trace=fut.trace)
        rows = np.asarray(rows)
        out = {
            "predictions": [float(v)
                            for v in predictions_from_rows(rows)],
            "rows": int(rows.shape[0]),
            "trace": fut.trace,
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
        }
        if req.get("raw"):
            # raw final-node rows: what the bitwise swap proofs and
            # the smoke's cold-restart comparison consume
            out["outputs"] = rows.reshape(rows.shape[0], -1).tolist()
        return 200, {}, json.dumps(out).encode()

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Product-surface summary: request/row/batch/padding counts,
        per-bucket dispatch counts, and latency p50/p99 (ms) from the
        registry histogram."""
        with self._lock:
            out: Dict[str, Any] = {
                "requests": self._n_requests,
                "rows": self._n_rows,
                "batches": self._n_batches,
                "padding_rows": self._n_padding,
                "errors": self._n_errors,
                "shed_requests": self._n_shed,
                "shed_rows": self._n_shed_rows,
                "deadline_expired": self._n_expired,
                "swaps": self._n_swaps,
                "swap_rejected": self._n_swap_rejected,
                "drain_rows_per_s": round(self._drain_rate, 2),
                "buckets": {b: n for b, n in self._bucket_hits.items()},
                "request_sizes": dict(self._size_hist),
            }
        out["queue_limit"] = self.queue_limit
        out["warmup_s"] = round(self.warmup_s, 4)
        for hist, stem in ((self._lat, "latency"),
                           (self._qlat, "queue"),
                           (self._dlat, "device")):
            for q in (50, 99):
                v = hist.percentile(q)
                out[f"{stem}_p{q}_ms"] = (round(v * 1e3, 3)
                                          if v == v else None)
        return out
