"""Continuous-batching inference server (docs/SERVING.md).

The predict/extract tasks are batch-at-a-time, train-shaped code: one
caller, one fixed batch, one padded dispatch. Production serving is
the opposite shape - many concurrent callers submitting a few rows
each - and the TF-paper framing (PAPERS.md, arXiv:1605.08695) treats
it as the same dataflow system with a different driver. This module is
that driver:

- a **shared request queue**: `submit()` is thread-safe and returns a
  future; requests larger than the biggest bucket split internally and
  re-join on `result()`;
- **continuous/dynamic batching into padded buckets**: dispatchers
  coalesce queued requests up to `max_batch` rows and run the smallest
  power-of-two bucket that covers them, padding the tail. Every bucket
  size is a distinct program shape of ONE jitted inference executable
  (trainer's `infer_fn`), so the bucket set compiles once;
- **warmed executables**: `warmup()` runs every bucket once at
  startup. Steady state then performs ZERO recompiles - provable via
  the same `_cache_size` technique the jaxpr audit uses
  (`executable_cache_size()` == `len(buckets)` and stays flat);
- **replica fan-out**: `replicas` dispatcher threads drain the shared
  queue; each dispatch is the SPMD executable over the full mesh (on
  `mesh = data:N` the bucket's rows spread over the data axis), and
  jax's async dispatch lets replicas pipeline host staging against
  device compute. `zero_stage = 3` params are consumed directly at
  their stored (sharded) layout - the executable's in_shardings are
  the trainer's `pstore`, so no host-side gather ever runs;
- an **admission/flush policy**: a dispatcher waits up to
  `max_wait_ms` for the bucket to fill, then flushes what it has
  (fill-or-timeout), so p99 latency stays bounded under low load.

Telemetry (docs/OBSERVABILITY.md): `serve.latency_s` histogram
(p50/p99 through the registry), the `serve.queue_s` / `serve.device_s`
per-request breakdown (request tracing: queue = submit -> dispatch,
incl. the fill-or-timeout coalesce wait; device = dispatch -> result
readback), the `serve.request_rows` Prometheus
histogram over the bucket ladder, `serve.queue_depth` gauge,
`serve.requests`/`serve.rows`/`serve.batches`/`serve.padding_rows`/
`serve.errors` counters. These accumulate unconditionally (they are
the product surface, queried via `Server.stats()`), like the fault
counters - no per-row device sync is added beyond the result readback
serving inherently requires. With the observability plane armed every
dispatch additionally lands in the flight recorder (executable
fingerprint + bucket + trace id - telemetry/flight.py), each warmed
bucket registers on `/executables`, and resolved requests emit `trace`
events that `tools/trace_export.py` renders to Perfetto-loadable
Chrome trace JSON.

The production front (this PR's layer, docs/SERVING.md "Serving over
HTTP" + "Hot-swap runbook"):

- **HTTP request path**: `Server(http_port=N)` (CLI `serve_port=`)
  attaches a `/predict` POST endpoint to the same stdlib listener
  that serves `/metrics`/`/healthz` - rows in, predictions out, trace
  ids minted at ingress so the queue-vs-device decomposition covers
  the network hop;
- **backpressure + load shedding**: a hard `queue_limit` (rows) above
  which `submit()` raises a typed `QueueFullError` and `/predict`
  returns 429 with a `Retry-After` derived from the queue depth and
  the measured drain rate; shedding flips `/healthz` to 503 through
  the health source map (`serve_shed`) until the queue drains below
  half the limit for a hysteresis window, so an LB can rotate the
  replica out and back in;
- **per-request deadlines**: `deadline_ms` (server default or per
  request) expires queued requests BEFORE dispatch - a dead request
  never wastes a bucket slot - surfacing as `DeadlineExpiredError`
  in-process and 504 over HTTP;
- **zero-downtime hot-swap**: `swap_to(path)` (or the `swap_watch=`
  polling thread) validates an atomic checksummed checkpoint (crc32
  trailer), stages the new params to device OUTSIDE any lock, and
  switches between batches under `_swap_lock`; in-flight dispatches
  already bound the old params and finish on the old weights, no
  request drops. A torn/corrupt file is rejected (`swap.rejected`
  event) and the old weights keep serving;
- **canaried rollout with automatic rollback** (`swap_canary_frac=`,
  docs/SERVING.md "Canary runbook"): a validated new checkpoint is
  STAGED as a candidate params slot instead of promoted - a
  deterministic fraction of requests (hash of the trace id, so split
  parts stay coherent) binds the candidate while the rest keep the
  incumbent, both through the SAME warmed bucket executables (params
  are jit arguments; the canary is a second argument binding - zero
  recompiles, `executable_cache_size()` stays flat). A judge thread
  scores the candidate over `swap_canary_window` seconds
  (error/deadline rates vs incumbent + shadow pairs: the same live
  rows dispatched through both param sets, compared argmax/allclose)
  and either auto-promotes (`swap` op=promoted) or auto-rolls-back
  (`swap` op=rolled_back; the incumbent is bitwise-untouched and the
  watcher quarantines the file exactly like a torn checkpoint - the
  pre-attempt stat record means it is never retried until
  republished);
- **hardened ingress + graceful drain** (docs/SERVING.md "Connection
  limits & drain"): `serve_conn_timeout_ms`/`serve_max_conns`/
  `serve_max_body_bytes` plumb to the listener (telemetry/http.py) -
  per-connection read deadlines so a slow-loris client cannot pin a
  listener thread, an accept gate answering 503 + Retry-After past
  the connection cap (own `serve_conns` health source with the same
  hysteretic recovery as shedding), and a 413 for bloated bodies
  before a byte of them is read. `drain()` (SIGTERM in `task=serve`)
  stops admission, flips /healthz to a draining verdict, resolves
  everything queued with zero drops, then stops.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cxxnet_tpu import telemetry
from cxxnet_tpu.telemetry.flight import fingerprint as exec_fingerprint
from cxxnet_tpu.utils import fault

# Retry-After advice when the drain-rate EWMA has no samples yet (a
# cold or just-restarted Server has dispatched nothing): the
# documented default the 429 header carries instead of an estimate
# derived from uninitialized state (docs/SERVING.md)
RETRY_AFTER_COLD_S = 1.0


def _trace_side(trace: str, frac: float) -> int:
    """Deterministic canary routing (docs/SERVING.md "Canary
    runbook"): hash of the request trace id against the traffic
    fraction - 1 = candidate, 0 = incumbent. Keyed on the trace so
    every split part of an oversize request lands on the same weight
    generation, and a retried trace routes the same way."""
    return 1 if zlib.crc32(trace.encode()) % 10000 < frac * 10000 else 0


class QueueFullError(RuntimeError):
    """submit() rejected: the queue is at `queue_limit` rows (load
    shedding, docs/SERVING.md). Carries the advice an HTTP 429 turns
    into a Retry-After header: `retry_after_s` (queue depth over the
    measured drain rate) and the `queue_depth` at rejection."""

    def __init__(self, msg: str, retry_after_s: float,
                 queue_depth: int) -> None:
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth


class DeadlineExpiredError(RuntimeError):
    """The request's deadline passed while it was still queued; it was
    dropped before dispatch (a dead request must never spend a bucket
    slot). HTTP callers see 504."""


def bucket_sizes(max_batch: int, data_axis: int = 1) -> Tuple[int, ...]:
    """The padded-batch bucket set: powers of two up to `max_batch`
    that the mesh's data axis divides (a bucket's rows must split
    evenly over the axis), plus `max_batch` itself. At least one
    bucket must exist - a `max_batch` the data axis does not divide
    cannot be dispatched and is rejected here, at configure time."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    out = set()
    b = 1
    while b <= max_batch:
        if b % data_axis == 0:
            out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_buckets(ladder: Sequence[int], max_batch: int,
                   data_axis: int = 1) -> Tuple[int, ...]:
    """An EXPLICIT bucket ladder (the autotuner's telemetry-shaped
    rungs, or `serve_bucket_ladder =` - docs/GRAPH_PASSES.md) folded
    into a valid bucket set: rungs outside [1, max_batch] or not
    divisible by the mesh's data axis are dropped (the
    inapplicable-tuned-value rule - a cache shaped on one mesh must
    not break another), and `max_batch` itself always closes the
    ladder. The max_batch/data-axis contract is bucket_sizes'."""
    if max_batch < 1:
        raise ValueError("serve_max_batch must be >= 1")
    if max_batch % max(data_axis, 1):
        raise ValueError(
            f"serve_max_batch={max_batch} must be a multiple of the "
            f"mesh's data-axis size ({data_axis}) - every bucket "
            "dispatches over that axis")
    axis = max(data_axis, 1)
    out = {int(b) for b in ladder
           if 1 <= int(b) <= max_batch and int(b) % axis == 0}
    out.add(max_batch)
    return tuple(sorted(out))


def ladder_from_histogram(hist, max_batch: int, data_axis: int = 1,
                          rungs: int = 4) -> Tuple[int, ...]:
    """Shape a bucket ladder from an observed request-size histogram
    ({size: count}, the Server's `request_sizes` stat): one rung at
    each 1/rungs quantile of the size distribution, rounded UP to the
    data axis, closed by `max_batch`. Sizes the traffic actually
    sends get tight buckets (less padding); sizes it never sends get
    no bucket (fewer warmed executables) - the TVM move of shaping
    the search space from the workload instead of a fixed
    power-of-two set. Falls back to bucket_sizes on an empty
    histogram."""
    sizes = sorted((int(s), int(c)) for s, c in dict(hist).items()
                   if int(c) > 0 and int(s) >= 1)
    if not sizes:
        return bucket_sizes(max_batch, data_axis)
    axis = max(data_axis, 1)
    total = sum(c for _, c in sizes)
    ladder = []
    for r in range(1, max(rungs, 1) + 1):
        target = r * total / max(rungs, 1)
        acc = 0
        for s, c in sizes:
            acc += c
            if acc >= target:
                ladder.append(-(-s // axis) * axis)  # ceil to axis
                break
    return ladder_buckets(ladder, max_batch, data_axis)


def predictions_from_rows(rows: np.ndarray) -> np.ndarray:
    """The TransformPred rule (trainer.predict) applied to raw final-
    node rows: single-column output passes through as scalars, wider
    output argmaxes - so a serve result file is comparable line-for-
    line with a `task = pred` file."""
    rows = np.asarray(rows)
    flat = rows.reshape(rows.shape[0], -1)
    if flat.shape[1] == 1:
        return flat[:, 0]
    return np.argmax(flat, axis=1).astype(np.float32)


class _Future:
    """Minimal one-shot result future (no concurrent.futures executor
    to tie its lifetime to)."""

    __slots__ = ("_ev", "_value", "_error", "trace")

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        # the request trace id (minted at submit; the HTTP front
        # echoes it in the /predict response body)
        self.trace = ""

    def _set(self, value) -> None:
        self._value = value
        self._ev.set()

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._value


class _JoinedFuture:
    """A request that split into several work items: result() is the
    row-concatenation of the parts, in submission order."""

    __slots__ = ("_parts",)

    def __init__(self, parts: List[_Future]) -> None:
        self._parts = parts

    @property
    def trace(self) -> str:
        return self._parts[0].trace if self._parts else ""

    def done(self) -> bool:
        return all(p.done() for p in self._parts)

    def result(self, timeout: Optional[float] = None):
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for p in self._parts:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            out.append(p.result(left))
        return np.concatenate(out, axis=0)


class _Canary:
    """A staged candidate weight generation under judgment
    (docs/SERVING.md "Canary runbook"). Every mutable field moves
    under the owning Server's `_swap_lock`; the judge thread snapshots
    under the lock and dispatches shadow pairs OUTSIDE it (GL015)."""

    __slots__ = ("params", "path", "epoch", "frac", "t0", "n_req",
                 "n_err", "n_exp", "shadow", "shadow_done",
                 "provenance")

    def __init__(self, params, path: str, epoch: int,
                 frac: float) -> None:
        self.params = params
        self.path = path
        self.epoch = epoch
        self.frac = frac
        self.t0 = time.monotonic()
        # per-side accounting over the judging window, indexed
        # [incumbent, candidate]: dispatched requests, dispatch
        # errors, deadline expiries - the judge's rate comparison
        self.n_req = [0, 0]
        self.n_err = [0, 0]
        self.n_exp = [0, 0]
        # sampled live request rows pending a shadow comparison
        # ((data, extras) copies; capped small - a sample, not a tap)
        self.shadow: List[Tuple[np.ndarray, List[np.ndarray]]] = []
        self.shadow_done = 0
        # publish_model's sidecar metadata (src path etc.), riding
        # the promoted/rolled_back events for provenance
        self.provenance: Dict[str, Any] = {}


class _WorkItem:
    __slots__ = ("data", "extras", "n", "t_submit", "future",
                 "trace", "part", "nparts", "t_collect", "deadline",
                 "side")

    def __init__(self, data, extras, t_submit, trace="",
                 part=0, nparts=1, deadline=0.0) -> None:
        self.data = data
        self.extras = extras
        self.n = data.shape[0]
        self.t_submit = t_submit
        self.future = _Future()
        # absolute monotonic expiry (0 = none): checked at queue-pop
        # so an expired request drops BEFORE dispatch
        self.deadline = deadline
        # end-to-end request tracing (docs/OBSERVABILITY.md "Request
        # tracing"): the trace id minted at submit(), the part index
        # for oversize requests that split, and the coalesce time a
        # dispatcher stamps when it pops the item; the queue/device
        # latency cut itself is the DISPATCH stamp (_run_batch) -
        # the fill wait after the pop is still queue time
        self.trace = trace
        self.part = part
        self.nparts = nparts
        self.t_collect = 0.0
        # canary routing side (0 = incumbent, 1 = candidate), stamped
        # at queue-pop from the trace hash while a canary is active;
        # a batch only ever coalesces items of one side
        self.side = 0


class Server:
    """Continuous-batching server over a trainer's inference
    executable. The trainer must hold a model (init_model or
    load_model); its mesh, dtype and device_augment spec all apply
    unchanged - serving is the same compiled forward predict runs,
    driven by a queue instead of an iterator.

    start() spawns the dispatcher replicas (warmup() first unless you
    want the first requests to pay the compiles); submit() from any
    thread; stop() drains the queue, joins the replicas and returns
    stats(). Usable as a context manager."""

    def __init__(self, trainer, max_batch: int = 0,
                 max_wait_ms: Optional[float] = None,
                 replicas: Optional[int] = None,
                 node: int = -1,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "0.0.0.0",
                 ladder: Optional[Sequence[int]] = None,
                 http_port: Optional[int] = None,
                 queue_limit: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 swap_watch: Optional[str] = None,
                 swap_poll_ms: Optional[float] = None,
                 canary_frac: Optional[float] = None,
                 canary_window: Optional[float] = None,
                 conn_timeout_ms: Optional[float] = None,
                 max_conns: Optional[int] = None,
                 max_body_bytes: Optional[int] = None) -> None:
        import jax
        if trainer.state is None:
            raise RuntimeError(
                "Server needs an initialized trainer (init_model or "
                "load_model first)")
        if jax.process_count() > 1:
            raise RuntimeError(
                "serving a multi-controller job is not supported; run "
                "the server on a single-process mesh")
        self.trainer = trainer
        self.max_batch = int(max_batch or trainer.serve_max_batch
                             or trainer.batch_size)
        self.max_wait_ms = float(
            trainer.serve_max_wait_ms if max_wait_ms is None
            else max_wait_ms)
        self.replicas = int(trainer.serve_replicas if replicas is None
                            else replicas)
        if self.replicas < 1:
            raise ValueError("serve_replicas must be >= 1")
        self.node = (node if node >= 0
                     else trainer.net_cfg.num_nodes - 1)
        dsize = trainer.mesh.shape.get("data", 1)
        # explicit ladder > trainer's (tuned or serve_bucket_ladder =)
        # ladder > the power-of-two default - the same
        # explicit-keys-win chain the scalar serve knobs ride
        lad = (ladder if ladder is not None
               else getattr(trainer, "serve_ladder", None))
        self.buckets = (ladder_buckets(lad, self.max_batch, dsize)
                        if lad else
                        bucket_sizes(self.max_batch, dsize))
        if getattr(trainer, "passes_need_calibration",
                   lambda: False)():
            # a calibrating pass (fold_conv_bn / quantize_int8)
            # without stats: the infer executable built below is the
            # un-rewritten FLOAT graph (safe, just unoptimized) and
            # stays so for this Server's lifetime - warmup on zeros
            # must never become the calibration batch (zero-input
            # moments and activation ranges would be garbage).
            # task=serve calibrates from the first pred batch before
            # building the Server (main.py); programmatic users call
            # trainer.calibrate_graph_passes (or predict once) first.
            telemetry.stderr(
                "serve: graph passes (fold_conv_bn/quantize_int8) "
                "have no calibration stats; serving the unoptimized "
                "float graph (calibrate before Server creation to "
                "fold/quantize)\n",
                event_kind="serve", op="fold_uncalibrated")
        self._fn = trainer._infer_fn(self.node)
        c, y, x = trainer.net_cfg.input_shape
        self._input_dims = (c, y, x)
        self._extra_dims = [
            tuple(trainer.net.node_shapes[1 + i][1:])
            for i in range(trainer.net_cfg.extra_data_num)]
        # attachable live-exposition server (docs/OBSERVABILITY.md):
        # metrics_port=N serves /metrics + /healthz + /varz for the
        # Server's lifetime (0 = ephemeral bind, read .metrics_server
        # .port). None = off; programmatic twins of the CLI key, which
        # arms the process-wide plane in main.run instead.
        # http_port=N (CLI serve_port=) attaches the SAME listener
        # plus the /predict request path - one socket, both surfaces;
        # specifying both ports with different values is an error.
        if http_port is None:
            cfg_port = int(getattr(trainer, "serve_port", 0) or 0)
            if cfg_port > 0:
                http_port = cfg_port
        if (http_port is not None and metrics_port is not None
                and int(http_port) != int(metrics_port)):
            raise ValueError(
                "serve_port and metrics_port attach ONE listener; "
                f"set them equal or drop one (got {http_port} vs "
                f"{metrics_port})")
        self.http_port = http_port
        self.metrics_port = (metrics_port if metrics_port is not None
                             else http_port)
        self.metrics_host = metrics_host
        self.metrics_server = None
        if self.metrics_port is not None:
            # the attached exposition endpoint is a flight-recorder
            # consumer (it serves the /varz tail and /executables) -
            # arm the recorder for this Server's lifetime, the same
            # rule arm_observability applies to the process-wide
            # plane. Armed HERE (not in start()) so warmup()'s cost
            # enrichment sees it: warmup conventionally runs before
            # start(). stop() re-derives from the remaining consumers.
            telemetry.get().flight.enabled = True
        self._cond = threading.Condition()
        # admission state: the queue, its row count and the drain flag
        # move together under the condition (checked statically -
        # docs/STATIC_ANALYSIS.md GL016)
        self._queue: collections.deque = collections.deque()
        # guarded-by: self._cond
        self._queued_rows = 0
        self._threads: List[threading.Thread] = []
        # guarded-by: self._cond
        self._draining = False
        self._started = False
        self.warmup_s = 0.0
        # backpressure (docs/SERVING.md "Serving over HTTP"): hard
        # queue bound in ROWS (0 = unlimited), the default request
        # deadline, and the shed->healthy hysteresis window
        self.queue_limit = int(
            trainer.serve_queue_limit if queue_limit is None
            else queue_limit)
        self.deadline_ms = float(
            trainer.serve_deadline_ms if deadline_ms is None
            else deadline_ms)
        self.shed_clear_ms = float(
            getattr(trainer, "serve_shed_clear_ms", 1000.0))
        # guarded-by: self._cond
        self._last_shed_t = 0.0
        # whether this Server currently holds the `serve_shed` source
        # unhealthy (503 on /healthz); cleared with hysteresis once
        # the queue drains below queue_limit/2 for shed_clear_ms
        # guarded-by: self._cond
        self._shed_health = False
        # checkpoint hot-swap (docs/SERVING.md "Hot-swap runbook"):
        # _swap_lock orders the params/fn switch against dispatch
        # snapshots; ONLY attribute reads/writes happen under it -
        # staging (device_put) and warmup stay outside (GL015)
        self._swap_lock = threading.Lock()
        self.swap_watch = (swap_watch if swap_watch is not None
                           else getattr(trainer, "swap_watch", "")) or ""
        self.swap_poll_ms = float(
            getattr(trainer, "swap_poll_ms", 200.0)
            if swap_poll_ms is None else swap_poll_ms)
        self._swap_thread: Optional[threading.Thread] = None
        # watcher shutdown signal (checked each poll tick)
        self._swap_stop = threading.Event()
        # canaried rollout (docs/SERVING.md "Canary runbook"): with
        # canary_frac in (0, 1] a validated checkpoint stages as a
        # CANDIDATE slot instead of promoting, judged for
        # canary_window seconds. 0 = off: swap_to flips immediately,
        # no judge thread ever spawns (unarmed byte-parity)
        self.canary_frac = float(
            getattr(trainer, "swap_canary_frac", 0.0)
            if canary_frac is None else canary_frac)
        if not 0.0 <= self.canary_frac <= 1.0:
            raise ValueError("swap_canary_frac must be in [0, 1]")
        self.canary_window = float(
            getattr(trainer, "swap_canary_window", 10.0)
            if canary_window is None else canary_window)
        if self.canary_window <= 0:
            raise ValueError("swap_canary_window must be > 0")
        # the candidate under judgment (None = no canary in flight)
        # guarded-by: self._swap_lock
        self._canary: Optional[_Canary] = None
        self._canary_thread: Optional[threading.Thread] = None
        # judge shutdown signal: set by stop(), read each judge tick
        self._canary_stop = threading.Event()
        # connection-level ingress limits (enforced by the listener -
        # telemetry/http.py; configured here so the serve_* fallback
        # chain stays uniform). All 0 = off, the plain PR-16 listener.
        self.conn_timeout_ms = float(
            getattr(trainer, "serve_conn_timeout_ms", 0.0)
            if conn_timeout_ms is None else conn_timeout_ms)
        self.max_conns = int(
            getattr(trainer, "serve_max_conns", 0)
            if max_conns is None else max_conns)
        self.max_body_bytes = int(
            getattr(trainer, "serve_max_body_bytes", 0)
            if max_body_bytes is None else max_body_bytes)
        # last (mtime_ns, size) the watcher acted on - recorded even
        # for a REJECTED file so a torn checkpoint is skipped once,
        # not re-validated in a hot loop
        # guarded-by: self._swap_lock
        self._swap_seen: Optional[Tuple[int, int]] = None
        # product-surface accounting, independent of the process-wide
        # registry (a second Server in one process must not inherit
        # the first one's counts OR its latency window); the registry
        # mirrors everything for the metrics stream/report
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._n_requests = 0
        # guarded-by: self._lock
        self._n_rows = 0
        # guarded-by: self._lock
        self._n_batches = 0
        # guarded-by: self._lock
        self._n_padding = 0
        # guarded-by: self._lock
        self._n_errors = 0
        # guarded-by: self._lock
        self._n_shed = 0
        # guarded-by: self._lock
        self._n_shed_rows = 0
        # guarded-by: self._lock
        self._n_expired = 0
        # guarded-by: self._lock
        self._n_swaps = 0
        # guarded-by: self._lock
        self._n_swap_rejected = 0
        # guarded-by: self._lock
        self._n_canary_req = 0
        # guarded-by: self._lock
        self._n_canary_promoted = 0
        # guarded-by: self._lock
        self._n_canary_rolled_back = 0
        # measured drain rate (rows/s, EWMA over dispatched batches):
        # what Retry-After is derived from
        # guarded-by: self._lock
        self._drain_rate = 0.0
        # guarded-by: self._lock
        self._last_drain_t = 0.0
        # guarded-by: self._lock
        self._bucket_hits: Dict[int, int] = {b: 0 for b in self.buckets}
        # request-size histogram: the serve telemetry the autotuner's
        # ladder_from_histogram shapes the bucket ladder from
        # (docs/GRAPH_PASSES.md "per-layer autotuner"); counts per
        # submitted work-item row count
        # guarded-by: self._lock
        self._size_hist: Dict[int, int] = {}
        self._lat = telemetry.Histogram()
        # per-request queue-vs-device decomposition (request tracing):
        # queue = submit -> coalesce, device = coalesce -> result
        self._qlat = telemetry.Histogram()
        self._dlat = telemetry.Histogram()
        # request-size distribution as a proper Prometheus histogram
        # on /metrics (bounds = this Server's bucket ladder); the
        # dict-shaped stats()["request_sizes"] stays for the autotuner
        self._req_hist = telemetry.get().registry.bucket_histogram(
            "serve.request_rows", bounds=self.buckets)
        # request-trace ids minted at submit(); executable
        # fingerprints per warmed bucket (filled by warmup) feed the
        # flight recorder + /executables registry (telemetry/flight.py)
        self._trace_seq = itertools.count(1)
        self._exec_fp: Dict[int, str] = {}

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> float:
        """Compile + run every bucket executable once (zeros input) so
        steady-state serving never compiles. Returns the wall seconds
        spent; also recorded as `serve.warmup_s`."""
        import jax
        t0 = time.perf_counter()
        params = self.trainer.state["params"]
        tel = telemetry.get()
        epoch = getattr(self.trainer, "_fold_epoch", 0)
        for b in self.buckets:
            data = np.zeros((b,) + self._input_dims, np.float32)
            extras = [np.zeros((b,) + d, np.float32)
                      for d in self._extra_dims]
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            tb = time.perf_counter()
            jax.block_until_ready(self._fn(params, gdata, gextras))
            compile_s = time.perf_counter() - tb
            # executable registry (telemetry/flight.py): one entry per
            # warmed bucket program shape, stamped with its compile
            # wall-time (warmup's block IS the compile window). The
            # fingerprint is what flight entries and stall dumps name.
            fp = exec_fingerprint(
                "serve.infer", self.node, b, self._input_dims,
                epoch)
            self._exec_fp[b] = fp
            tel.executables.register(
                fp, name=f"serve.infer:b{b}", kind="serve",
                shape=str((b,) + self._input_dims),
                arg_bytes=int(data.nbytes
                              + sum(e.nbytes for e in extras)),
                device=jax.default_backend(), donated=0,
                compile_s=compile_s)
            if tel.flight.enabled:
                # armed plane: enrich with XLA cost analysis + output
                # footprint (one extra trace/lowering per bucket,
                # sanctioned here in the warmup window; the jit cache
                # the zero-recompile audit counts is untouched)
                tel.executables.enrich(fp, self._fn,
                                       (params, gdata, gextras))
        self.warmup_s = time.perf_counter() - t0
        telemetry.observe("serve.warmup_s", self.warmup_s)
        telemetry.event("serve", op="warmup", buckets=list(self.buckets),
                        secs=self.warmup_s)
        return self.warmup_s

    def executable_cache_size(self) -> Optional[int]:
        """Compiled-program count of the inference executable (the
        jaxpr audit's `_cache_size` technique): after warmup this
        equals len(buckets) and must stay flat under any steady-state
        request mix - the zero-recompile proof."""
        fn = getattr(self._fn, "_cache_size", None)
        return fn() if callable(fn) else None

    def start(self) -> "Server":
        if self._started:
            return self
        if self.metrics_port is not None and self.metrics_server is None:
            from cxxnet_tpu.telemetry.http import ObservabilityServer
            self.metrics_server = ObservabilityServer(
                telemetry.get(), int(self.metrics_port),
                host=self.metrics_host,
                predict_backend=(self if self.http_port is not None
                                 else None),
                conn_timeout_ms=self.conn_timeout_ms,
                max_conns=self.max_conns,
                max_body_bytes=self.max_body_bytes,
                conn_clear_ms=self.shed_clear_ms)
            self.metrics_server.start()
            telemetry.event("observability", op="http_start",
                            port=self.metrics_server.port,
                            host=self.metrics_host,
                            predict=self.http_port is not None)
        with self._cond:
            # published under the lock that guards it: a replica from
            # a previous start/stop cycle draining late must not read
            # a torn flag
            self._draining = False
        with self._lock:
            # a restarted Server serves a fresh traffic mix: the
            # previous run's drain-rate EWMA is stale advice, so
            # Retry-After reverts to the documented cold default
            # until a batch dispatches (RETRY_AFTER_COLD_S)
            self._drain_rate = 0.0
            self._last_drain_t = 0.0
        self._started = True
        for i in range(self.replicas):
            t = threading.Thread(target=self._replica_loop,
                                 name=f"serve-replica-{i}", daemon=True)
            self._threads.append(t)
            t.start()
        if self.swap_watch and self._swap_thread is None:
            # checkpoint watcher: the file's CURRENT state counts as
            # already-served (the Server was presumably built from
            # it); only a subsequent publish triggers a swap
            with self._swap_lock:
                self._swap_seen = self._swap_stat()
            self._swap_stop.clear()
            self._swap_thread = threading.Thread(
                target=self._swap_watch_loop,
                name="serve-swap-watch", daemon=True)
            self._swap_thread.start()
        return self

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        """Stop the replicas - after draining the queue (default), or
        immediately failing queued requests (drain=False) - and return
        stats(). Idempotent."""
        if self._swap_thread is not None:
            self._swap_stop.set()
            self._swap_thread.join(timeout=10.0)
            self._swap_thread = None
        if self._canary_thread is not None:
            # an undecided canary fails SAFE at shutdown: the judge
            # sees the stop signal and rolls back to the incumbent
            # (promotion needs a full window's evidence)
            self._canary_stop.set()
            self._canary_thread.join(timeout=15.0)
            self._canary_thread = None
        with self._cond:
            self._draining = True
            if not drain:
                while self._queue:
                    it = self._queue.popleft()
                    self._queued_rows -= it.n
                    it.future._set_error(
                        RuntimeError("server stopped before dispatch"))
            self._cond.notify_all()
            shed_held = self._shed_health
            self._shed_health = False
        if shed_held:
            # a stopped server is not "overloaded"; release the 503
            # so a restart doesn't inherit a stale verdict
            telemetry.get().health.clear("serve_shed")
        for t in self._threads:
            t.join(timeout=60.0)
        self._threads = []
        self._started = False
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self.metrics_port is not None:
            # this Server's endpoint was a flight consumer; re-derive
            # the recorder's armed state from whatever remains (sinks,
            # the process-wide plane, an explicit flight_recorder=1)
            telemetry.get()._refresh_flight()
        telemetry.set_gauge("serve.queue_depth", 0.0)
        stats = self.stats()
        telemetry.event("serve", op="stop", **{
            k: v for k, v in stats.items() if not isinstance(v, dict)})
        return stats

    def drain(self) -> Dict[str, Any]:
        """Graceful shutdown (docs/SERVING.md "Connection limits &
        drain"; `task=serve` runs this on SIGTERM): stop admitting -
        new submits raise and /predict answers 503 - flip /healthz to
        a `serve_drain` 503 so the LB rotates this replica out,
        resolve EVERYTHING already queued (zero drops: the replicas
        keep dispatching until the queue is empty), then stop.
        Returns the final stats()."""
        with self._cond:
            depth = self._queued_rows
            self._draining = True
            self._cond.notify_all()
        telemetry.get().health.set_unhealthy(
            "serve_drain", "draining: shutdown in progress")
        telemetry.event("serve", op="drain_start", queue_rows=depth)
        try:
            stats = self.stop(drain=True)
        finally:
            # the listener is closed by stop(); clear the verdict so
            # a long-lived process (or a restarted Server) does not
            # inherit a stale draining 503
            telemetry.get().health.clear("serve_drain")
        telemetry.event("serve", op="drain_done", queue_rows=depth,
                        errors=stats.get("errors"))
        return stats

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- submission --------------------------------------------------------
    def submit(self, data: np.ndarray, extras: Sequence = (),
               deadline_ms: Optional[float] = None):
        """Enqueue one request: data is (n, c, y, x) rows or a single
        (c, y, x) instance; extras (if the net declares extra inputs)
        ride along row-aligned. Returns a future whose result() is the
        raw final-node rows, (n, width) - predictions_from_rows turns
        them into predict()-style labels. Thread-safe; requests wider
        than the largest bucket split transparently.

        `deadline_ms` overrides the server default (serve_deadline_ms;
        0 = none): a request still queued past its deadline is dropped
        BEFORE dispatch and its future raises DeadlineExpiredError.
        With `queue_limit` set, a submit that would push the queue
        past the limit raises QueueFullError instead of enqueueing
        (load shedding - the HTTP front maps it to 429+Retry-After)."""
        if not self._started:
            raise RuntimeError("Server not started (call start())")
        data = np.ascontiguousarray(data)
        if data.ndim == 3:
            data = data[None]
        if data.ndim != 4 or data.shape[1:] != self._input_dims:
            raise ValueError(
                f"serve request must be (n, {self._input_dims[0]}, "
                f"{self._input_dims[1]}, {self._input_dims[2]}) or a "
                f"single instance; got {data.shape}")
        if data.shape[0] < 1:
            raise ValueError("serve request needs at least one row")
        extras = [np.ascontiguousarray(e, dtype=np.float32)
                  for e in extras]
        if len(extras) != len(self._extra_dims):
            raise ValueError(
                f"net declares {len(self._extra_dims)} extra inputs "
                f"but the request carries {len(extras)}")
        for e in extras:
            if e.shape[0] != data.shape[0]:
                raise ValueError("extras must be row-aligned with data")
        t_submit = time.monotonic()
        # request trace id (docs/OBSERVABILITY.md "Request tracing"):
        # minted once per submit and shared by every split part, so an
        # oversize request renders as ONE span tree in the exported
        # Chrome trace; pid-scoped so multi-process traces merge
        trace = f"{os.getpid():x}-{next(self._trace_seq):06d}"
        eff_ms = (self.deadline_ms if deadline_ms is None
                  else float(deadline_ms))
        deadline = t_submit + eff_ms / 1e3 if eff_ms > 0 else 0.0
        nparts = -(-data.shape[0] // self.max_batch)
        items = []
        for part, lo in enumerate(
                range(0, data.shape[0], self.max_batch)):
            hi = lo + self.max_batch
            items.append(_WorkItem(
                data[lo:hi], [e[lo:hi] for e in extras], t_submit,
                trace=trace, part=part, nparts=nparts,
                deadline=deadline))
        items[0].future.trace = trace
        shed_depth = -1
        with self._cond:
            if self._draining:
                raise RuntimeError("server is stopping")
            if (self.queue_limit > 0 and
                    self._queued_rows + data.shape[0]
                    > self.queue_limit):
                # hard admission bound: reject, do NOT enqueue. The
                # shed verdict (503 on /healthz) holds until the
                # queue drains below half the limit for the
                # hysteresis window (_maybe_recover)
                shed_depth = self._queued_rows
                self._last_shed_t = t_submit
                flip = not self._shed_health
                self._shed_health = True
            else:
                for it in items:
                    self._queue.append(it)
                    self._queued_rows += it.n
                depth = self._queued_rows
                self._cond.notify_all()
        if shed_depth >= 0:
            retry_s = self._retry_after(shed_depth + data.shape[0])
            with self._lock:
                self._n_shed += 1
                self._n_shed_rows += data.shape[0]
            telemetry.inc("serve.shed_total")
            telemetry.inc("serve.shed_rows", data.shape[0])
            if flip:
                reason = (f"load shed: queue {shed_depth} rows + "
                          f"{data.shape[0]} > limit {self.queue_limit}")
                telemetry.get().health.set_unhealthy(
                    "serve_shed", reason)
                telemetry.event("serve", op="shed",
                                queue_depth=shed_depth,
                                limit=self.queue_limit)
            raise QueueFullError(
                f"serve queue full ({shed_depth} rows >= limit "
                f"{self.queue_limit}); retry in {retry_s:.2f}s",
                retry_after_s=retry_s, queue_depth=shed_depth)
        with self._lock:
            self._n_requests += 1
            self._n_rows += data.shape[0]
            for it in items:
                self._size_hist[it.n] = self._size_hist.get(it.n, 0) + 1
        for it in items:
            self._req_hist.observe(it.n)
        telemetry.inc("serve.requests")
        telemetry.inc("serve.rows", data.shape[0])
        telemetry.set_gauge("serve.queue_depth", depth)
        if len(items) == 1:
            return items[0].future
        return _JoinedFuture([it.future for it in items])

    # -- backpressure helpers ----------------------------------------------
    def _retry_after(self, backlog_rows: int) -> float:
        """Retry-After advice for a shed request: the time the current
        backlog takes to drain at the measured (EWMA) drain rate,
        clamped to [0.1s, 60s]. With no sample yet - a cold Server, or
        one just restarted (start() resets the EWMA) - the rate is
        unknown and the documented RETRY_AFTER_COLD_S default applies;
        a non-finite estimate falls back the same way rather than
        leaking garbage into the header."""
        with self._lock:
            rate = self._drain_rate
        if not (rate > 0.0) or not np.isfinite(rate):
            return RETRY_AFTER_COLD_S
        adv = backlog_rows / rate
        if not np.isfinite(adv):
            return RETRY_AFTER_COLD_S
        return min(60.0, max(0.1, adv))

    def _maybe_recover(self) -> None:
        """Shed->healthy hysteresis: clear the `serve_shed` health
        verdict once the queue has drained below HALF the limit AND
        no shed happened for shed_clear_ms - a single drained batch
        amid a storm must not flap /healthz."""
        now = time.monotonic()
        cleared = False
        with self._cond:
            if (self._shed_health
                    and self._queued_rows * 2 < max(self.queue_limit, 1)
                    and (now - self._last_shed_t)
                    >= self.shed_clear_ms / 1e3):
                self._shed_health = False
                cleared = True
        if cleared:
            telemetry.get().health.clear("serve_shed")
            telemetry.event("serve", op="shed_recovered",
                            limit=self.queue_limit)

    def _fail_expired(self, it: _WorkItem, now: float) -> None:
        """Resolve a deadline-expired item (called OUTSIDE _cond: the
        future Event set + registry counters need no queue state)."""
        with self._lock:
            self._n_expired += 1
        if self.canary_frac > 0:
            # judge evidence: attribute the expiry to the weight
            # generation that would have served this trace
            with self._swap_lock:
                can = self._canary
                if can is not None:
                    can.n_exp[_trace_side(it.trace, can.frac)] += 1
        telemetry.inc("serve.deadline_expired")
        waited_ms = (now - it.t_submit) * 1e3
        it.future._set_error(DeadlineExpiredError(
            f"request deadline expired after {waited_ms:.1f} ms in "
            "queue (dropped before dispatch)"))
        telemetry.event("serve", op="deadline_expired",
                        trace=it.trace, part=it.part, rows=it.n,
                        waited_ms=round(waited_ms, 3))

    # -- dispatchers -------------------------------------------------------
    def _collect(self) -> Optional[List[_WorkItem]]:
        """Admission policy: block for work, then coalesce queued
        items up to max_batch rows, waiting at most max_wait_ms past
        the FIRST item's submit time for the batch to fill
        (fill-or-timeout). Deadline-expired items are dropped here,
        before a bucket slot is spent on them. Returns None when
        stopping and drained; an empty list means "nothing live this
        round, loop again" (everything popped had expired)."""
        expired: List[_WorkItem] = []
        frac = 0.0
        if self.canary_frac > 0:
            # snapshot the active canary's traffic split BEFORE taking
            # _cond (no nested locks on the admission path); a canary
            # resolving mid-collect is benign - the batch's side tag
            # just routes to the incumbent at dispatch
            with self._swap_lock:
                if self._canary is not None:
                    frac = self._canary.frac
        items = self._collect_locked(expired, frac)
        if expired:
            now = time.monotonic()
            for it in expired:
                self._fail_expired(it, now)
        if items is not None:
            self._maybe_recover()
        return items

    def _collect_locked(
            self, expired: List[_WorkItem], frac: float = 0.0
    ) -> Optional[List[_WorkItem]]:
        with self._cond:
            first = None
            while first is None:
                if not self._queue:
                    if self._draining:
                        return None
                    if expired:
                        # resolve the drops promptly instead of
                        # blocking here with their futures pending
                        break
                    if (self._shed_health and self._queued_rows * 2
                            < max(self.queue_limit, 1)
                            and time.monotonic() - self._last_shed_t
                            >= self.shed_clear_ms / 1e3):
                        # storm over, traffic gone: surface so the
                        # caller can clear the shed 503 (recovery
                        # must not wait for the next request)
                        break
                    self._cond.wait(0.05)
                    continue
                # pop the next un-expired item; expired ones
                # accumulate for post-lock resolution
                now = time.monotonic()
                while self._queue:
                    it = self._queue.popleft()
                    self._queued_rows -= it.n
                    if it.deadline and now > it.deadline:
                        expired.append(it)
                        continue
                    first = it
                    break
            if first is None:
                telemetry.set_gauge("serve.queue_depth",
                                    self._queued_rows)
                return []
            # coalesce stamp: end of this item's queue phase (request
            # tracing's queue-vs-device cut)
            first.t_collect = time.monotonic()
            if frac > 0.0:
                first.side = _trace_side(first.trace, frac)
            items = [first]
            total = first.n
            deadline = first.t_submit + self.max_wait_ms / 1e3
            while total < self.max_batch:
                if self._queue:
                    head = self._queue[0]
                    if head.deadline and time.monotonic() > head.deadline:
                        self._queue.popleft()
                        self._queued_rows -= head.n
                        expired.append(head)
                        continue
                    if frac > 0.0:
                        head.side = _trace_side(head.trace, frac)
                        if head.side != first.side:
                            # a batch binds ONE weight generation:
                            # ship what we have, the head opens the
                            # other side's batch next round
                            break
                    if head.n <= self.max_batch - total:
                        it = self._queue.popleft()
                        self._queued_rows -= it.n
                        it.t_collect = time.monotonic()
                        items.append(it)
                        total += it.n
                        continue
                    break  # head doesn't fit: ship what we have
                wait = deadline - time.monotonic()
                if wait <= 0 or self._draining:
                    break
                self._cond.wait(min(wait, 0.05))
            telemetry.set_gauge("serve.queue_depth", self._queued_rows)
            return items

    def _run_batch(self, items: List[_WorkItem]) -> None:
        from cxxnet_tpu.parallel import distributed
        total = sum(it.n for it in items)
        bucket = next(b for b in self.buckets if b >= total)
        data = np.concatenate([it.data for it in items], axis=0)
        extras = [
            np.concatenate([it.extras[i] for it in items], axis=0)
            for i in range(len(self._extra_dims))]
        if bucket > total:
            pad = bucket - total
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], data.dtype)],
                axis=0)
            extras = [np.concatenate(
                [e, np.zeros((pad,) + e.shape[1:], e.dtype)], axis=0)
                for e in extras]
        tel = telemetry.get()
        fp = self._exec_fp.get(bucket, "")
        fl = None
        if tel.flight.enabled:
            # dispatch flight record: opened BEFORE staging (a hung
            # backend blocks inside device_put / the dispatch / the
            # readback below, leaving this entry in-flight with the
            # exact executable fingerprint + request trace on it)
            fl = tel.flight.start(
                "serve", fp=fp, bucket=bucket, nbytes=int(data.nbytes),
                trace=items[0].trace,
                fields={"rows": total, "requests": len(items)})
        t_dispatch = time.monotonic()
        try:
            # serve-side fault points (utils/fault.py, CXXNET_FAULT):
            # delay stalls the dispatch (deadline/backpressure tests),
            # error crashes it (the replica recovers, futures fail)
            fault.fault_point("serve_dispatch_delay")
            fault.fault_point("serve_dispatch_error")
            # hot-swap consistency: snapshot (fn, params) under the
            # swap lock so a batch binds ONE weight generation; the
            # dispatch itself runs outside the lock (GL015 - never
            # hold a lock across a jax boundary). An in-flight batch
            # that snapshotted before a swap finishes on old weights.
            # A canary batch (side=1) binds the staged candidate
            # params instead - same fn, same warmed executables, the
            # candidate is just a second argument binding.
            side = items[0].side
            routed = 0
            with self._swap_lock:
                fn = self._fn
                can = self._canary
                if can is not None and side == 1:
                    params = can.params
                    routed = len(items)
                else:
                    side = 0
                    params = self.trainer.state["params"]
                if can is not None:
                    can.n_req[side] += len(items)
                    if side == 0 and len(can.shadow) < 4:
                        # sample incumbent rows for the judge's shadow
                        # comparison (same rows through BOTH param
                        # sets, compared argmax/allclose)
                        can.shadow.append(
                            (items[0].data.copy(),
                             [e.copy() for e in items[0].extras]))
            if routed:
                with self._lock:
                    self._n_canary_req += routed
                telemetry.inc("serve.canary_requests", routed)
            gdata, gextras = self.trainer.stage_infer_rows(data, extras)
            out = fn(params, gdata, gextras)
            rows = distributed.fetch_local(out)
        except BaseException as e:
            # a FAILED dispatch must not read as a hung one: the
            # replica recovers and keeps serving, so close the flight
            # entry with the error instead of leaving it in-flight
            # forever (only a dispatch that never returns stays open)
            tel.flight.fail(fl, f"{type(e).__name__}: {e}")
            raise
        rows = rows.reshape(bucket, -1)
        t_done = time.monotonic()
        tel.flight.finish(fl)
        if fp:
            tel.executables.count_dispatch(fp, secs=t_done - t_dispatch)
        off = 0
        for it in items:
            it.future._set(rows[off:off + it.n])
            off += it.n
            self._lat.observe(t_done - it.t_submit)
            telemetry.observe("serve.latency_s", t_done - it.t_submit)
            # queue-vs-device breakdown per traced request part: the
            # cut is at DISPATCH, not at queue-pop - the fill-or-
            # timeout coalesce wait after the pop is host-side
            # admission latency and must not be billed to the device
            # (it would misdirect a p99 investigation toward the
            # accelerator); t_collect still rides the trace record so
            # the export can render the coalesce boundary
            queue_s = max(t_dispatch - it.t_submit, 0.0)
            device_s = max(t_done - t_dispatch, 0.0)
            self._qlat.observe(queue_s)
            self._dlat.observe(device_s)
            telemetry.observe("serve.queue_s", queue_s)
            telemetry.observe("serve.device_s", device_s)
            # one trace record per resolved part (no-op with no event
            # sink armed): the complete span set tools/trace_export.py
            # renders to Chrome trace-event JSON
            tel.event("trace", trace=it.trace, part=it.part,
                      parts=it.nparts, rows=it.n, bucket=bucket,
                      fp=fp, t_submit=round(it.t_submit, 6),
                      t_collect=round(it.t_collect, 6),
                      t_dispatch=round(t_dispatch, 6),
                      t_done=round(t_done, 6),
                      queue_ms=round(queue_s * 1e3, 3),
                      device_ms=round(device_s * 1e3, 3))
        with self._lock:
            self._n_batches += 1
            self._n_padding += bucket - total
            self._bucket_hits[bucket] += 1
            # drain-rate EWMA (rows/s across all replicas): Retry-After
            # advice for shed requests derives from it. Measured over
            # inter-completion gaps so replica overlap and admission
            # waits are priced in, not just device time.
            if self._last_drain_t > 0:
                gap = t_done - self._last_drain_t
                if gap > 1e-6:
                    inst = total / gap
                    self._drain_rate = (
                        inst if self._drain_rate <= 0
                        else 0.7 * self._drain_rate + 0.3 * inst)
            self._last_drain_t = t_done
        telemetry.inc("serve.batches")
        telemetry.inc("serve.padding_rows", bucket - total)
        # serving progress beacon: a wedged dispatch (hung backend)
        # stops marking and the watchdog dumps the stuck replica stack
        telemetry.beacon("serve.batch")

    def _replica_loop(self) -> None:
        while True:
            items = self._collect()
            if items is None:
                return
            if not items:
                # nothing live this round (expired drops resolved /
                # shed recovery surfaced) - nothing to dispatch
                continue
            try:
                self._run_batch(items)
            except BaseException as e:  # noqa: BLE001 - delivered via futures
                with self._lock:
                    self._n_errors += 1
                if self.canary_frac > 0:
                    # judge evidence: bill the failed dispatch to the
                    # weight generation the batch was bound to
                    with self._swap_lock:
                        can = self._canary
                        if can is not None:
                            can.n_err[items[0].side] += 1
                telemetry.inc("serve.errors")
                telemetry.stderr(
                    f"serve: dispatch failed: {type(e).__name__}: {e}\n",
                    event_kind="serve", op="error",
                    error=f"{type(e).__name__}: {e}")
                for it in items:
                    if not it.future.done():
                        it.future._set_error(e)

    # -- checkpoint hot-swap -----------------------------------------------
    def _swap_stat(self) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(self.swap_watch)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def _swap_watch_loop(self) -> None:
        """Poll the published-checkpoint path every swap_poll_ms and
        swap on any (mtime, size) change. The stat is recorded before
        the attempt, so a rejected (torn) file is skipped ONCE and
        not re-validated in a hot loop; publishing a fixed file
        changes the stat again and retries."""
        poll_s = max(self.swap_poll_ms, 10.0) / 1e3
        while not self._swap_stop.wait(poll_s):
            cur = self._swap_stat()
            with self._swap_lock:
                if cur is None or cur == self._swap_seen:
                    continue
                self._swap_seen = cur
            try:
                self.swap_to(self.swap_watch)
            except BaseException as e:  # noqa: BLE001 - keep serving
                telemetry.stderr(
                    f"serve: swap attempt failed: "
                    f"{type(e).__name__}: {e}\n",
                    event_kind="swap", op="error",
                    error=f"{type(e).__name__}: {e}")

    def _params_mismatch(self, cur, new) -> Optional[str]:
        """A swap must be weight-compatible with the warmed
        executables: identical param tree (layer/param keys) and leaf
        shapes. Returns the first mismatch as a reason string."""
        for lk in cur:
            if lk not in new:
                return f"checkpoint missing layer {lk!r}"
            for pn in cur[lk]:
                if pn not in new[lk]:
                    return f"checkpoint missing param {lk}/{pn}"
                want = tuple(cur[lk][pn].shape)
                got = tuple(np.shape(new[lk][pn]))
                if want != got:
                    return (f"shape mismatch at {lk}/{pn}: "
                            f"checkpoint {got} vs serving {want}")
        extra = [f"{lk}/{pn}" for lk in new for pn in new[lk]
                 if lk not in cur or pn not in cur[lk]]
        if extra:
            return f"checkpoint has unknown params: {extra[:3]}"
        return None

    def swap_to(self, path: str) -> bool:
        """Zero-downtime weight swap from an atomic checksummed
        checkpoint (docs/SERVING.md "Hot-swap runbook"): validate the
        crc32 trailer, load, verify the param tree matches, stage the
        new params to device (all outside any lock), then switch
        between batches under _swap_lock. In-flight batches bound the
        old params at dispatch and finish on the old weights; no
        request is dropped. Returns True on an applied swap; a
        torn/corrupt/mismatched checkpoint emits `swap` op=rejected
        and the old weights keep serving (False)."""
        from cxxnet_tpu.nnet import checkpoint
        t0 = time.perf_counter()
        blob = None
        reason = checkpoint.validate_file(path)
        if reason is None:
            try:
                with open(path, "rb") as fi:
                    blob = checkpoint.load_model(fi)
            except (OSError, ValueError) as e:
                reason = f"{type(e).__name__}: {e}"
        if reason is None:
            reason = self._params_mismatch(
                self.trainer.state["params"], blob["params"])
        if reason is not None:
            with self._lock:
                self._n_swap_rejected += 1
            telemetry.inc("serve.swap_rejected")
            telemetry.stderr(
                f"serve: checkpoint swap rejected ({path}): "
                f"{reason}\n",
                event_kind="swap", op="rejected", path=path,
                reason=reason)
            return False
        if self.canary_frac > 0:
            calibrated = (self.trainer._fold_stats is not None
                          or self.trainer._quant_stats is not None)
            if calibrated:
                # frozen fold/quant calibration means applying this
                # checkpoint rewarms new executables - incumbent and
                # candidate could not share warmed buckets, so the
                # traffic split is impossible. Fall through to the
                # direct (non-canaried) swap and say so.
                telemetry.stderr(
                    f"serve: canary bypassed for {path}: calibrated "
                    f"passes force a rewarm, applying directly\n",
                    event_kind="swap", op="canary_bypassed", path=path)
            else:
                with self._swap_lock:
                    busy = self._canary is not None
                if busy:
                    with self._lock:
                        self._n_swap_rejected += 1
                    telemetry.inc("serve.swap_rejected")
                    telemetry.stderr(
                        f"serve: checkpoint swap rejected ({path}): "
                        f"canary already in progress\n",
                        event_kind="swap", op="rejected", path=path,
                        reason="canary already in progress")
                    return False
                staged = self._stage_params(blob)
                return self._start_canary(
                    staged, path,
                    int(blob.get("epoch", self.trainer.epoch)))
        # stage the new weights at the stored sharded layout BEFORE
        # taking the swap lock - device_put is a dispatch boundary and
        # must never run under a lock (GL015 / the runtime lock audit)
        staged = self._stage_params(blob)
        with self._swap_lock:
            self.trainer.state["params"] = staged
            self.trainer.epoch = int(blob.get("epoch",
                                              self.trainer.epoch))
            old_fold = self.trainer._fold_epoch
            # frozen fold/quant calibration described the OLD weights:
            # retire it (epoch bump + stale-executable eviction, the
            # PR 10/12 mechanism). On the no-passes path this is a
            # no-op and params stay plain jit ARGUMENTS - the swap is
            # a zero-recompile, bitwise switch.
            self.trainer._retire_calibration_state()
            rewarmed = self.trainer._fold_epoch != old_fold
            if rewarmed:
                self._fn = self.trainer._infer_fn(self.node)
        if rewarmed:
            # new fold epoch = new executables: re-warm every bucket
            # so steady state stays recompile-free and /executables
            # lists the new fingerprints (epoch is part of them)
            self.warmup()
        with self._lock:
            self._n_swaps += 1
        telemetry.inc("serve.swaps")
        telemetry.event("swap", op="applied", path=path,
                        epoch=self.trainer.epoch, rewarmed=rewarmed,
                        secs=round(time.perf_counter() - t0, 4))
        return True

    def _stage_params(self, blob: Dict[str, Any]) -> Dict[str, Any]:
        """Stage a validated checkpoint's params to device at the
        stored sharded layout (the same put_global_full landing
        set_weight uses). Runs OUTSIDE any lock - device_put is a
        dispatch boundary and must never run under a lock (GL015 /
        the runtime lock audit)."""
        from cxxnet_tpu.parallel import distributed
        cur = self.trainer.state["params"]
        pstore = self.trainer._params_store_shard
        return {
            lk: {pn: distributed.put_global_full(
                np.ascontiguousarray(blob["params"][lk][pn]),
                pstore[lk][pn])
                for pn in cur[lk]}
            for lk in cur}

    # -- canaried rollout --------------------------------------------------
    def _start_canary(self, staged, path: str, epoch: int) -> bool:
        """Install a validated, device-staged candidate as the canary
        (docs/SERVING.md "Canary runbook"): a swap_canary_frac slice
        of traffic (deterministic on the trace id, so oversize-split
        parts stay coherent) binds the candidate params at dispatch
        while the rest keeps the incumbent - through the SAME warmed
        bucket executables, zero recompiles. A judge thread scores
        the candidate over swap_canary_window seconds and either
        promotes it (swap op=promoted) or rolls it back
        (op=rolled_back, incumbent bitwise-untouched)."""
        from cxxnet_tpu.nnet import checkpoint
        can = _Canary(staged, path, epoch, self.canary_frac)
        can.provenance = checkpoint.read_publish_meta(path) or {}
        with self._swap_lock:
            if self._canary is not None:
                # raced with another swap_to: first canary wins, this
                # candidate is dropped (the watcher already recorded
                # the file's stat, so it is quarantined like a reject)
                return False
            self._canary = can
        # one judge per canary: the previous judge (if any) exited
        # when its canary resolved, so join is immediate
        if self._canary_thread is not None:
            self._canary_thread.join(timeout=15.0)
        self._canary_stop.clear()
        self._canary_thread = threading.Thread(
            target=self._canary_judge_loop, args=(can,),
            name="serve-canary-judge", daemon=True)
        self._canary_thread.start()
        telemetry.event(
            "swap", op="canary_started", path=path, epoch=epoch,
            frac=can.frac, window_s=self.canary_window,
            src=str(can.provenance.get("src", "")))
        return True

    def _canary_judge_loop(self, can: "_Canary") -> None:
        """Judge thread: periodically score the canary against the
        incumbent until the window closes, then promote or roll back.
        ANY judge failure rolls back - a broken judge must fail safe
        to the incumbent (the canary_judge_error fault point proves
        it)."""
        try:
            fault.fault_point("canary_judge_error")
            deadline = can.t0 + self.canary_window
            while True:
                wait_s = min(0.05, max(0.0, deadline - time.monotonic()))
                if self._canary_stop.wait(wait_s):
                    # server stopping before the window closed: the
                    # candidate was never promoted, drop it
                    self._canary_rollback(
                        can, "server stopping before verdict")
                    return
                verdict = self._canary_check(can)
                if verdict is not None:
                    self._canary_rollback(can, verdict)
                    return
                if time.monotonic() >= deadline:
                    break
            verdict = self._canary_check(can, final=True)
            if verdict is not None:
                self._canary_rollback(can, verdict)
            else:
                self._canary_promote(can)
        except BaseException as e:  # noqa: BLE001 - fail safe to incumbent
            self._canary_rollback(
                can, f"judge error: {type(e).__name__}: {e}")

    def _canary_check(self, can: "_Canary",
                      final: bool = False) -> Optional[str]:
        """One judge round. Returns a rollback reason, or None when
        the canary still looks healthy. Evidence: (a) shadow pairs -
        the same sampled rows dispatched through BOTH param sets and
        compared (candidate non-finite where the incumbent is finite,
        or argmax agreement below 0.5, is a fail); (b) error/deadline
        rates - candidate
        worse than incumbent with at least one bad event is a fail.
        On the final round with zero organic evidence, a synthetic
        zeros batch checks the candidate at least produces finite
        output."""
        with self._swap_lock:
            if self._canary is not can:
                return None
            fn = self._fn
            inc_params = self.trainer.state["params"]
            cand_params = can.params
            sample = can.shadow.pop() if can.shadow else None
            shadow_done = can.shadow_done
            n_req = list(can.n_req)
            bad = [can.n_err[0] + can.n_exp[0],
                   can.n_err[1] + can.n_exp[1]]
        if sample is not None:
            reason = self._shadow_divergence(
                fn, inc_params, cand_params, sample[0], sample[1])
            with self._swap_lock:
                can.shadow_done += 1
            if reason is not None:
                return reason
        elif final and shadow_done == 0:
            # no organic traffic reached the incumbent during the
            # window: synthesize a zeros batch so the candidate is at
            # least proven finite before promotion (argmax agreement
            # on synthetic rows is meaningless, so skip it)
            c, y, x = self._input_dims
            data = np.zeros((1, c, y, x), np.float32)
            extras = [np.zeros((1, d), np.float32)
                      for d in self._extra_dims]
            reason = self._shadow_divergence(
                fn, inc_params, cand_params, data, extras,
                check_agree=False)
            if reason is not None:
                return reason
        if bad[1] > 0:
            rate = [bad[s] / max(n_req[s], 1) for s in (0, 1)]
            if rate[1] > rate[0]:
                return (f"candidate error/deadline rate "
                        f"{rate[1]:.4f} > incumbent {rate[0]:.4f} "
                        f"({bad[1]}/{n_req[1]} vs "
                        f"{bad[0]}/{n_req[0]})")
        return None

    def _shadow_divergence(self, fn, inc_params, cand_params, data,
                           extras, check_agree: bool = True
                           ) -> Optional[str]:
        """Dispatch the same rows through incumbent and candidate
        params (same warmed bucket executables - the rows are padded
        to a covering bucket, so the executable cache stays flat) and
        compare. Returns a rollback reason or None."""
        from cxxnet_tpu.parallel import distributed
        n = int(data.shape[0])
        bucket = next((b for b in self.buckets if b >= n),
                      self.buckets[-1])
        if n > bucket:
            data, extras = data[:bucket], [e[:bucket] for e in extras]
            n = bucket
        if bucket > n:
            pad = bucket - n
            data = np.concatenate(
                [data, np.zeros((pad,) + data.shape[1:], data.dtype)],
                axis=0)
            extras = [np.concatenate(
                [e, np.zeros((pad,) + e.shape[1:], e.dtype)], axis=0)
                for e in extras]
        gdata, gextras = self.trainer.stage_infer_rows(data, extras)
        out_inc = distributed.fetch_local(
            fn(inc_params, gdata, gextras)).reshape(bucket, -1)[:n]
        out_cand = distributed.fetch_local(
            fn(cand_params, gdata, gextras)).reshape(bucket, -1)[:n]
        if fault.fault_point("canary_divergence") == "corrupt":
            # sabotage: poison the candidate's answers so the
            # divergence check trips (rollback-path drills)
            out_cand = out_cand + np.nan
        # the judge scores RELATIVE health: a candidate is only
        # penalized for non-finite outputs at positions where the
        # incumbent was finite (an incumbent that already emits NaN -
        # e.g. a diverged trainer - must not veto its own checkpoint)
        cand_bad = ~np.isfinite(out_cand)
        if bool(np.any(cand_bad & np.isfinite(out_inc))):
            return ("candidate produced non-finite outputs where "
                    "the incumbent was finite")
        agree = None
        if check_agree:
            agree = float(np.mean(
                predictions_from_rows(out_cand)
                == predictions_from_rows(out_inc)))
        telemetry.event(
            "swap", op="canary_shadow", rows=n,
            agree=(None if agree is None else round(agree, 4)),
            allclose=bool(np.allclose(out_cand, out_inc,
                                      rtol=1e-3, atol=1e-5)))
        if agree is not None and agree < 0.5:
            return (f"candidate argmax agreement {agree:.2f} < 0.5 "
                    f"on {n} shadow rows")
        return None

    def _canary_promote(self, can: "_Canary") -> None:
        """The window closed clean: the candidate becomes the
        incumbent between batches (same flip as a direct swap -
        in-flight batches bound their params at dispatch)."""
        with self._swap_lock:
            if self._canary is not can:
                return
            self.trainer.state["params"] = can.params
            self.trainer.epoch = can.epoch
            self._canary = None
        with self._lock:
            self._n_swaps += 1
            self._n_canary_promoted += 1
        telemetry.inc("serve.swaps")
        telemetry.inc("serve.canary_promoted")
        telemetry.event(
            "swap", op="promoted", path=can.path, epoch=can.epoch,
            canary_requests=can.n_req[1], shadow_pairs=can.shadow_done,
            window_s=self.canary_window,
            src=str(can.provenance.get("src", "")))

    def _canary_rollback(self, can: "_Canary", reason: str) -> None:
        """Drop the candidate; the incumbent was never touched, so
        rollback is just detaching the canary slot. The watcher
        recorded the file's stat before the attempt, so the bad
        checkpoint is quarantined (skipped once) exactly like a torn
        file - republishing retries."""
        with self._swap_lock:
            if self._canary is not can:
                return
            self._canary = None
        with self._lock:
            self._n_canary_rolled_back += 1
        telemetry.inc("serve.canary_rolled_back")
        telemetry.stderr(
            f"serve: canary rolled back ({can.path}): {reason}\n",
            event_kind="swap", op="rolled_back", path=can.path,
            reason=reason, canary_requests=can.n_req[1],
            shadow_pairs=can.shadow_done,
            src=str(can.provenance.get("src", "")))

    # -- HTTP request path -------------------------------------------------
    def handle_predict(self, body: bytes):
        """The /predict POST backend (telemetry/http.py routes here
        when this Server attached with http_port/serve_port): JSON
        {"data": rows, "extras": [...], "deadline_ms": N, "raw": bool}
        in; {"predictions": [...], "rows": n, "trace": id} out. data
        is (n,c,y,x) nested, flat (n, c*y*x), or one instance. Maps
        QueueFullError -> 429 + Retry-After, deadline expiry/timeout
        -> 504, validation -> 400, dispatch failure -> 500. Returns
        (status, extra_headers, body_bytes)."""
        import json

        def err(code: int, msg: str, **extra):
            payload = {"error": msg}
            payload.update(extra)
            return code, {}, json.dumps(payload).encode()

        t0 = time.monotonic()
        try:
            req = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError):
            return err(400, "request body must be a JSON object")
        if not isinstance(req, dict) or "data" not in req:
            return err(400, 'request JSON needs a "data" field '
                            '(rows to predict)')
        try:
            data = np.asarray(req["data"], dtype=np.float32)
        except (ValueError, TypeError):
            return err(400, '"data" must be a numeric array')
        c, y, x = self._input_dims
        width = c * y * x
        if data.ndim == 1 and data.size == width:
            data = data.reshape(1, c, y, x)
        elif data.ndim == 2 and data.shape[-1] == width:
            data = data.reshape(-1, c, y, x)
        deadline_ms = req.get("deadline_ms")
        try:
            extras = [np.asarray(e, dtype=np.float32)
                      for e in req.get("extras", ())]
            fut = self.submit(data, extras, deadline_ms=deadline_ms)
        except QueueFullError as e:
            # ceil seconds for the header (int per RFC 9110), exact
            # advice in the body; [1, 60] keeps a confused client
            # from either hammering or giving up
            secs = max(1, min(60, int(-(-e.retry_after_s // 1))))
            return (429, {"Retry-After": str(secs)},
                    json.dumps({
                        "error": "queue full (load shed)",
                        "retry_after_s": round(e.retry_after_s, 3),
                        "queue_depth": e.queue_depth}).encode())
        except (ValueError, TypeError) as e:
            return err(400, str(e))
        except RuntimeError as e:
            return err(503, str(e))
        eff_ms = (self.deadline_ms if deadline_ms is None
                  else float(deadline_ms))
        timeout = eff_ms / 1e3 + 5.0 if eff_ms > 0 else 300.0
        try:
            rows = fut.result(timeout=timeout)
        except DeadlineExpiredError as e:
            return err(504, str(e), trace=fut.trace)
        except TimeoutError:
            return err(504, "timed out waiting for the result",
                       trace=fut.trace)
        except BaseException as e:  # noqa: BLE001 - dispatch error -> 500
            return err(500, f"{type(e).__name__}: {e}",
                       trace=fut.trace)
        rows = np.asarray(rows)
        out = {
            "predictions": [float(v)
                            for v in predictions_from_rows(rows)],
            "rows": int(rows.shape[0]),
            "trace": fut.trace,
            "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
        }
        if req.get("raw"):
            # raw final-node rows: what the bitwise swap proofs and
            # the smoke's cold-restart comparison consume
            out["outputs"] = rows.reshape(rows.shape[0], -1).tolist()
        return 200, {}, json.dumps(out).encode()

    # -- reporting ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Product-surface summary: request/row/batch/padding counts,
        per-bucket dispatch counts, and latency p50/p99 (ms) from the
        registry histogram."""
        with self._lock:
            out: Dict[str, Any] = {
                "requests": self._n_requests,
                "rows": self._n_rows,
                "batches": self._n_batches,
                "padding_rows": self._n_padding,
                "errors": self._n_errors,
                "shed_requests": self._n_shed,
                "shed_rows": self._n_shed_rows,
                "deadline_expired": self._n_expired,
                "swaps": self._n_swaps,
                "swap_rejected": self._n_swap_rejected,
                "canary_requests": self._n_canary_req,
                "canary_promoted": self._n_canary_promoted,
                "canary_rolled_back": self._n_canary_rolled_back,
                "drain_rows_per_s": round(self._drain_rate, 2),
                "buckets": {b: n for b, n in self._bucket_hits.items()},
                "request_sizes": dict(self._size_hist),
            }
        with self._swap_lock:
            out["canary_active"] = self._canary is not None
        if self.metrics_server is not None:
            ingress = getattr(self.metrics_server, "ingress_stats",
                              None)
            if ingress is not None:
                out.update(ingress())
        out["queue_limit"] = self.queue_limit
        out["warmup_s"] = round(self.warmup_s, 4)
        for hist, stem in ((self._lat, "latency"),
                           (self._qlat, "queue"),
                           (self._dlat, "device")):
            for q in (50, 99):
                v = hist.percentile(q)
                out[f"{stem}_p{q}_ms"] = (round(v * 1e3, 3)
                                          if v == v else None)
        return out
