"""Continuous-batching inference serving layer (docs/SERVING.md)."""

from cxxnet_tpu.serve.server import (
    DeadlineExpiredError, QueueFullError, Server, bucket_sizes,
    ladder_buckets, ladder_from_histogram, predictions_from_rows)

__all__ = ["Server", "bucket_sizes", "ladder_buckets",
           "ladder_from_histogram", "predictions_from_rows",
           "QueueFullError", "DeadlineExpiredError"]
