"""Optional plugin layers (the role of src/plugin/ in the reference:
external-framework adapters, off the hot path, enabled on demand)."""
