"""torch adapter plugin: drop a torch.nn.Module into a netconfig DAG.

Role parity with the reference's caffe adapter
(src/plugin/caffe_adapter-inl.hpp:27-231): wrap a layer from an external
framework as a first-class DAG layer - inputs/outputs mirrored across the
boundary, external params exposed to our updaters/checkpoints, gradients
flowing through. Where the reference copies node data into caffe Blobs,
here the torch module runs on host CPU under `jax.pure_callback`, with a
`jax.custom_vjp` whose backward calls torch.autograd - so it composes
with jit/grad like any pure-JAX layer (at host-callback speed; this is an
escape hatch, not a hot path, exactly like the reference gates its
adapter off by default - global.h:8-10).

Config (quotes keep the tokenizer from splitting on spaces):
    layer[a->b] = torch:mylayer
      torch_module = "nn.Conv2d(3, 8, 3, padding=1)"

The expression is evaluated with `torch` and `torch.nn as nn` in scope.
Params are discovered from the module (named_parameters) and live in the
regular params pytree (trained by OUR updaters; copied into the module
around every callback).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from cxxnet_tpu.layers.base import Layer, Params, Shape, register_layer


def _sanitize(name: str) -> str:
    return name.replace(".", "_")


@register_layer
class TorchAdapterLayer(Layer):
    """`torch`: wraps a torch.nn.Module built from the config string."""

    type_name = "torch"

    def __init__(self, name: str = ""):
        super().__init__(name)
        self.module_expr = ""
        self._module = None
        self._param_names: List[str] = []

    def set_param(self, name: str, val: str) -> None:
        super().set_param(name, val)
        if name == "torch_module":
            self.module_expr = val

    # -- module construction ------------------------------------------------
    def _build_module(self):
        if self._module is not None:
            return
        if not self.module_expr:
            raise ValueError(
                "torch adapter: must set torch_module = <expression>")
        try:
            import torch
            from torch import nn
        except ImportError as e:  # pragma: no cover - torch is baked in
            raise RuntimeError(
                "torch adapter requires torch installed") from e
        self._module = eval(self.module_expr,  # noqa: S307 - config-owned
                            {"torch": torch, "nn": nn})
        self._module = self._module.float().cpu()
        self._param_names = [n for n, _ in
                             self._module.named_parameters()]
        bufs = [n for n, _ in self._module.named_buffers()]
        if bufs:
            import warnings
            warnings.warn(
                "torch adapter: module has stateful buffers "
                f"{bufs}; they are neither trained nor checkpointed "
                "(running stats will stay at their init values)",
                stacklevel=2)

    def _torch(self):
        import torch
        return torch

    def _load_params(self, params: Dict[str, np.ndarray]) -> None:
        torch = self._torch()
        with torch.no_grad():
            for n, p in self._module.named_parameters():
                p.copy_(torch.from_numpy(
                    np.asarray(params[_sanitize(n)], np.float32)))

    # -- Layer protocol -----------------------------------------------------
    def infer_shapes(self, in_shapes: List[Shape]) -> List[Shape]:
        self.check_one_to_one(in_shapes)
        self._build_module()
        torch = self._torch()
        with torch.no_grad():
            out = self._module(torch.zeros(*in_shapes[0]))
        if out.dim() != 4:
            raise ValueError(
                "torch adapter: module must map NCHW -> NCHW, got "
                f"{tuple(out.shape)}")
        self._out_shape_tail = tuple(out.shape)[1:]
        return [tuple(out.shape)]

    def init_params(self, key: jax.Array, in_shapes: List[Shape]) -> Params:
        self._build_module()
        # torch's own initialization is the layer's init (the reference
        # keeps caffe's blob init too)
        return {
            _sanitize(n): jnp.asarray(
                p.detach().cpu().numpy().astype(np.float32))
            for n, p in self._module.named_parameters()}

    def param_tags(self) -> Dict[str, str]:
        self._build_module()
        tags = {}
        for n, p in self._module.named_parameters():
            tags[_sanitize(n)] = "bias" if p.dim() == 1 else "wmat"
        return tags

    def apply(self, params: Params, inputs: List[jax.Array], *,
              train: bool, rng: Optional[jax.Array] = None,
              ) -> List[jax.Array]:
        self._build_module()
        x = inputs[0]
        names = [_sanitize(n) for n in self._param_names]
        ptuple = tuple(params[n] for n in names)
        out_shape = (x.shape[0],) + self._out_shape_tail
        layer = self
        # one torch-RNG seed shared by forward and backward, so a
        # stochastic module (Dropout) draws the SAME mask in both - the
        # backward re-runs the forward under torch.autograd
        if rng is not None:
            seed = jax.random.randint(rng, (), 0, np.int32(2**31 - 1))
        else:
            seed = jnp.zeros((), jnp.int32)

        def host_fwd(pvals, xv, sv):
            torch = layer._torch()
            layer._load_params(dict(zip(names, pvals)))
            layer._module.train(train)  # honor Dropout etc. semantics
            torch.manual_seed(int(np.asarray(sv)))
            with torch.no_grad():
                out = layer._module(
                    torch.from_numpy(np.asarray(xv, np.float32)))
            return out.numpy().astype(np.float32)

        def host_bwd(pvals, xv, gv, sv):
            torch = layer._torch()
            layer._load_params(dict(zip(names, pvals)))
            layer._module.train(train)
            torch.manual_seed(int(np.asarray(sv)))
            xt = torch.from_numpy(np.asarray(xv, np.float32))
            xt.requires_grad_(True)
            out = layer._module(xt)
            tparams = [p for _, p in layer._module.named_parameters()]
            grads = torch.autograd.grad(
                out, [xt] + tparams,
                grad_outputs=torch.from_numpy(
                    np.asarray(gv, np.float32)),
                allow_unused=True)
            res = []
            for g, ref in zip(grads, [xt] + tparams):
                res.append(np.zeros(tuple(ref.shape), np.float32)
                           if g is None else
                           g.detach().numpy().astype(np.float32))
            return tuple(res)

        @jax.custom_vjp
        def f(ptuple, x, seed):
            return jax.pure_callback(
                host_fwd,
                jax.ShapeDtypeStruct(out_shape, jnp.float32),
                ptuple, x.astype(jnp.float32), seed)

        def f_fwd(ptuple, x, seed):
            return f(ptuple, x, seed), (ptuple, x, seed)

        def f_bwd(res, g):
            ptuple, x, seed = res
            outs = jax.pure_callback(
                host_bwd,
                tuple([jax.ShapeDtypeStruct(x.shape, jnp.float32)]
                      + [jax.ShapeDtypeStruct(p.shape, jnp.float32)
                         for p in ptuple]),
                ptuple, x.astype(jnp.float32), g.astype(jnp.float32),
                seed)
            return (tuple(outs[1:]), outs[0].astype(x.dtype),
                    jnp.zeros_like(seed))

        f.defvjp(f_fwd, f_bwd)
        return [f(ptuple, x, seed).astype(x.dtype)]
