"""Process-wide metrics registry: counters, gauges, histograms.

The reference framework's only runtime signal is a wall-clock round
time printed to stdout (cxxnet_main.cpp:376-387); nothing can count
retries, watch queue depths, or alert on checkpoint latency. This
module is the accounting half of the telemetry subsystem
(docs/OBSERVABILITY.md): cheap thread-safe instruments that work
whether or not any sink is configured. Rare-event sites (fault.retry,
checkpoint.*) accumulate unconditionally; per-step/per-batch hot paths
(train.*, io.prefetch.*) gate their instrumentation on a sink being
armed, because honest step timing costs a device sync the disabled
path must not pay. Snapshots are plain dicts, serialized into the
metrics JSONL by the sink layer.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Deque, Dict, List, Optional, Union

# histograms keep a bounded window of recent observations for
# percentiles (count/sum/min/max stay exact over the full stream); a
# training run observes one value per step, so 8192 covers hours of
# rounds without unbounded growth
HISTOGRAM_WINDOW = 8192


class Counter:
    """Monotonic counter (events, retries, batches)."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def snapshot(self) -> int:
        return self.value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, loss)."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> float:
        return self.value


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolation percentile (numpy's default method),
    without the numpy import on the telemetry hot path."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class Histogram:
    """Streaming distribution: exact count/sum/min/max plus a bounded
    window of recent observations for p50/p99."""

    __slots__ = ("_lock", "count", "sum", "min", "max", "_window")

    def __init__(self, window: int = HISTOGRAM_WINDOW) -> None:
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._window: Deque[float] = collections.deque(maxlen=window)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self._window.append(v)

    def percentile(self, q: float) -> float:
        with self._lock:
            vals = sorted(self._window)
        if not vals:
            return float("nan")
        return _percentile(vals, q)

    @property
    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else float("nan")

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._window)
            count, total = self.count, self.sum
            vmin, vmax = self.min, self.max
        out = {
            "count": count,
            "sum": total,
            "mean": total / count if count else None,
            "min": vmin if count else None,
            "max": vmax if count else None,
        }
        if vals:
            out["p50"] = _percentile(vals, 50)
            out["p99"] = _percentile(vals, 99)
        else:
            out["p50"] = out["p99"] = None
        return out


class BucketHistogram:
    """Fixed-bound cumulative-bucket histogram - the Prometheus
    ``histogram`` type (``_bucket{le=...}`` series), unlike Histogram
    above which exports as a quantile summary. Used where the value
    domain is known at creation (the Server's request-size
    distribution over its bucket ladder) so a scrape gets the real
    shape, not two quantiles."""

    __slots__ = ("_lock", "bounds", "count", "sum", "_counts")

    def __init__(self, bounds) -> None:
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError("BucketHistogram needs >= 1 bound")
        self.count = 0
        self.sum = 0.0
        # per-bound NON-cumulative counts + one overflow slot;
        # snapshot() accumulates (the export wants cumulative le=)
        self._counts = [0] * (len(self.bounds) + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
        buckets: Dict[str, int] = {}
        acc = 0
        for b, c in zip(self.bounds, counts):
            acc += c
            key = str(int(b)) if b == int(b) else repr(b)
            buckets[key] = acc
        buckets["+Inf"] = count
        return {"count": count, "sum": total, "buckets": buckets}


Instrument = Union[Counter, Gauge, Histogram, BucketHistogram]


class MetricsRegistry:
    """Name -> instrument map. Creation is idempotent per (name, kind);
    asking for an existing name with a different kind is a programming
    error and fails loudly (a silent re-type would corrupt the stream
    consumers parse)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._instruments: Dict[str, Instrument] = {}

    def _get(self, name: str, cls) -> Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls()
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def bucket_histogram(self, name: str, bounds=()) -> BucketHistogram:
        """Idempotent per name like the other kinds; the FIRST
        creation's bounds win (a second Server re-requesting the
        instrument must not silently re-bucket the series mid-scrape)."""
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = BucketHistogram(bounds)
                self._instruments[name] = inst
            elif not isinstance(inst, BucketHistogram):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not BucketHistogram")
            return inst

    def get(self, name: str) -> Optional[Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def instruments(self) -> Dict[str, Instrument]:
        """Copied name -> instrument map (the Prometheus exposition
        needs instrument KINDS, which snapshot() erases - a counter
        and an integer-valued gauge snapshot identically)."""
        with self._lock:
            return dict(self._instruments)

    def reset(self) -> None:
        with self._lock:
            self._instruments = {}

    def snapshot(self) -> Dict[str, object]:
        """One JSON-ready dict of every instrument's current value
        (counters/gauges scalar, histograms a stats sub-dict), sorted
        by name so diffs of consecutive records are readable."""
        with self._lock:
            items = sorted(self._instruments.items())
        return {name: inst.snapshot() for name, inst in items}
