"""Dispatch flight recorder + executable introspection registry.

Every bench round since 2026-07-30 lost its chip numbers to a TPU
backend hang, and the watchdog's thread-stack dump (watchdog.py) can
say *that* the process is stuck but not *which executable, which
bucket, which request* was in flight when it stuck. This module is the
missing black box, the TF-paper move (arXiv:1605.08695 §5) of making
the dataflow system explain itself at the artifact level:

- the **flight recorder**: a lock-light ring buffer of recent jitted
  dispatches (train / eval / infer / serve). Each entry records the
  executable fingerprint, program bucket (batch rows), argument bytes,
  device, thread, optional request trace id, and monotonic start/end.
  An entry whose end is still unset IS the in-flight dispatch - a hung
  backend blocks inside the dispatch call, so the watchdog stall dump
  and ``/varz`` tail finally *name* the wedged executable. Recording
  is a slot store + two clock reads, no device sync, and is armed only
  with the observability plane (sinks / ``metrics_port`` / watchdog /
  ``flight_recorder = 1``) - the unarmed path costs one attribute
  check, preserving the pinned CLI byte-parity contract.

- the **executable registry**: one entry per compiled program shape,
  keyed by the same fingerprint the flight entries carry - registered
  (cheaply, once per shape) at the existing per-node jit-cache sites
  (trainer train/eval/infer executables, the Server's warmed bucket
  set). Entries accumulate dispatch counts and, where the site
  naturally blocks (Server.warmup), compile wall-time; arming the
  plane additionally enriches serve entries with XLA cost analysis
  (flops / bytes accessed) and the output/donation footprint via the
  jit AOT path. Exposed live as the ``/executables`` HTTP endpoint and
  per-executable Prometheus series (http.py), and asserted non-empty
  by the jaxpr audit.

Ring and registry writes are GIL-atomic slot/dict stores behind one
short lock each; no lock is ever held across a jax dispatch (the
runtime lock audit's serve-storm scenario exercises exactly this).
Request tracing (trace ids minted at ``Server.submit``) rides the same
ring - ``tools/trace_export.py`` renders the event-stream twin of
these records to Chrome trace-event JSON for Perfetto
(docs/OBSERVABILITY.md "Request tracing").
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from typing import Any, Dict, List, Optional

# dispatches kept in the ring: enough to cover every in-flight replica
# plus a meaningful "what ran last" window without unbounded growth
FLIGHT_RING = 256
# entries included in a tail unless the caller asks otherwise
TAIL_DEFAULT = 16


def fingerprint(*parts) -> str:
    """Stable short id of one compiled program shape: hash of the
    site name + the shape/dtype/epoch parts the site keys its jit
    cache by. 12 hex chars - long enough to never collide across the
    handful of executables one process compiles, short enough to read
    in a stall dump."""
    h = hashlib.sha1("|".join(str(p) for p in parts).encode())
    return h.hexdigest()[:12]


class Flight:
    """One recorded dispatch. Mutable so finish() is a single slot
    store; snapshot() turns it into a plain dict."""

    __slots__ = ("seq", "kind", "fp", "bucket", "nbytes", "device",
                 "trace", "tid", "t0", "t1", "ts0", "fields")

    def __init__(self, seq: int, kind: str, fp: str, bucket: int,
                 nbytes: int, device: str, trace: Optional[str],
                 fields: Optional[Dict[str, Any]]) -> None:
        self.seq = seq
        self.kind = kind
        self.fp = fp
        self.bucket = bucket
        self.nbytes = nbytes
        self.device = device
        self.trace = trace
        self.tid = threading.current_thread().name
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        # graftlint: disable=GL004 wall TIMESTAMP by design - flight tails merge with the ts-stamped JSONL streams
        self.ts0 = time.time()
        self.fields = fields

    def as_dict(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now is None else now
        t1 = self.t1
        out: Dict[str, Any] = {
            "seq": self.seq, "kind": self.kind, "fp": self.fp,
            "bucket": self.bucket, "bytes": self.nbytes,
            "device": self.device, "thread": self.tid,
            "ts": round(self.ts0, 6),
            "secs": (round(t1 - self.t0, 6) if t1 is not None
                     else None),
            "in_flight": t1 is None,
        }
        if t1 is None:
            out["age_s"] = round(now - self.t0, 6)
        if self.trace is not None:
            out["trace"] = self.trace
        if self.fields:
            out.update(self.fields)
        return out


class FlightRecorder:
    """Lock-light dispatch ring. Sequence allocation is one
    ``next(itertools.count)`` (GIL-atomic) and the entry lands with a
    single list-slot store, so concurrent serve replicas never
    serialize on a recorder lock; a reader may see a slot torn by a
    wrap-around race, which forensics tolerates by construction (the
    snapshot orders by seq and drops None)."""

    def __init__(self, size: int = FLIGHT_RING) -> None:
        self.size = int(size)
        self._ring: List[Optional[Flight]] = [None] * self.size
        self._seq = itertools.count()
        # open (un-finished) dispatches, keyed by seq: the ring evicts
        # by age, but a WEDGED dispatch is exactly the entry that must
        # survive any number of later dispatches (a partial hang - one
        # serve replica stuck while the others keep the ring churning)
        # - so in-flight entries are held here until finish()/fail().
        # Bounded by size as a leak backstop (a site that loses its
        # handle without finishing must not grow this forever).
        self._open: Dict[int, Flight] = {}
        # armed with the observability plane (telemetry._refresh_flight)
        # or explicitly (flight_recorder = 1); unarmed recording costs
        # one attribute check at each dispatch site
        self.enabled = False
        self._explicit = False

    def arm(self, explicit: bool = True) -> None:
        self._explicit = bool(explicit)
        if explicit:
            self.enabled = True

    @property
    def explicit(self) -> bool:
        return self._explicit

    # -- recording ---------------------------------------------------------
    def start(self, kind: str, fp: str = "", bucket: int = 0,
              nbytes: int = 0, device: str = "",
              trace: Optional[str] = None,
              fields: Optional[Dict[str, Any]] = None
              ) -> Optional[Flight]:
        """Open one dispatch record; returns None when disarmed (the
        zero-overhead path - callers guard on .enabled before building
        arguments). The entry stays marked in-flight until finish()."""
        if not self.enabled:
            return None
        fl = Flight(next(self._seq), kind, fp, int(bucket),
                    int(nbytes), device, trace, fields)
        self._ring[fl.seq % self.size] = fl
        self._open[fl.seq] = fl
        if len(self._open) > self.size:
            # leak backstop: a site that lost its handle can never
            # grow the open table past one ring's worth
            self._open.pop(min(self._open), None)
        return fl

    def finish(self, fl: Optional[Flight]) -> None:
        if fl is not None:
            fl.t1 = time.monotonic()
            self._open.pop(fl.seq, None)

    def fail(self, fl: Optional[Flight], error: str) -> None:
        """Close a dispatch that RAISED: it must not read as a hung
        one (the caller survived and continues), so the entry finishes
        carrying the error - only a dispatch that never returns stays
        in-flight."""
        if fl is None:
            return
        if fl.fields is None:
            fl.fields = {}
        fl.fields["error"] = error
        self.finish(fl)

    # -- reading -----------------------------------------------------------
    def _entries(self) -> List[Flight]:
        # ring entries + any open dispatch the ring already evicted
        # (a long-wedged entry outlives arbitrarily many later
        # dispatches - see _open above); dedupe by seq
        got = {fl.seq: fl for fl in self._ring if fl is not None}
        got.update(dict(self._open))
        return [got[s] for s in sorted(got)]

    def snapshot(self) -> List[Dict[str, Any]]:
        """Every live ring entry, oldest-first."""
        now = time.monotonic()
        return [fl.as_dict(now) for fl in self._entries()]

    def tail(self, n: int = TAIL_DEFAULT) -> List[Dict[str, Any]]:
        """The newest n entries, oldest-first (newest LAST - the
        watchdog/varz convention recent_spans uses) - plus ANY older
        in-flight entry: the wedged dispatch is the one record a
        bounded window must never scroll away."""
        now = time.monotonic()
        entries = self._entries()
        window = entries[-n:] if n > 0 else []
        older = entries[:-n] if n > 0 else entries
        keep = [fl for fl in older if fl.t1 is None]
        return [fl.as_dict(now) for fl in keep + window]

    def in_flight(self) -> List[Dict[str, Any]]:
        """Dispatches started but not finished - during a hang these
        name the wedged executable(s). Read from the open table, so a
        wedged entry survives any amount of ring churn."""
        now = time.monotonic()
        # snapshot the dict once: a dispatch thread finish()-popping
        # between a key scan and a per-key lookup must not KeyError a
        # concurrent scrape
        open_now = dict(self._open)
        return [fl.as_dict(now)
                for _, fl in sorted(open_now.items())
                if fl.t1 is None]

    def format_tail(self, n: int = TAIL_DEFAULT,
                    rows: Optional[List[Dict[str, Any]]] = None) -> str:
        """Human-readable tail block for the watchdog stall dump;
        pass `rows` (a tail() result) to render an already-taken
        snapshot instead of taking a second one."""
        if rows is None:
            rows = self.tail(n)
        if not rows:
            return "  (no dispatches recorded)\n"
        out = []
        for r in rows:
            if r["in_flight"]:
                lead = f"  IN-FLIGHT {r['age_s']:9.3f}s"
            else:
                lead = f"  done      {r['secs']:9.4f}s"
            out.append(
                f"{lead} {r['kind']}"
                f" fp={r['fp'] or '-'} bucket={r['bucket']}"
                f" bytes={r['bytes']}"
                + (f" trace={r['trace']}" if "trace" in r else "")
                + f" thread={r['thread']}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        self._ring = [None] * self.size
        self._open = {}
        self._seq = itertools.count()
        self.enabled = False
        self._explicit = False


class ExecutableRegistry:
    """fingerprint -> executable facts. Registration happens once per
    compiled program shape at the jit-cache sites (cheap enough to run
    unconditionally - the jaxpr audit asserts the registry is never
    empty after real dispatches); per-dispatch counting is one dict
    hit + increment under a short lock never held across a dispatch."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._entries: Dict[str, Dict[str, Any]] = {}

    def register(self, fp: str, name: str, kind: str,
                 shape: str = "", arg_bytes: int = 0,
                 device: str = "", donated: int = 0,
                 compile_s: Optional[float] = None) -> None:
        """Idempotent per fingerprint; the first registration wins
        (re-deriving the same program shape must not reset counts)."""
        with self._lock:
            if fp in self._entries:
                e = self._entries[fp]
                if compile_s is not None and e.get("compile_s") is None:
                    e["compile_s"] = round(compile_s, 6)
                return
            self._entries[fp] = {
                "fingerprint": fp, "name": name, "kind": kind,
                "shape": shape, "arg_bytes": int(arg_bytes),
                "device": device, "donated": int(donated),
                "compile_s": (round(compile_s, 6)
                              if compile_s is not None else None),
                "flops": None, "cost_bytes": None, "out_bytes": None,
                "dispatches": 0, "dispatch_s": 0.0,
                "last_used_ts": None,
            }

    def count_dispatch(self, fp: str,
                       secs: Optional[float] = None) -> None:
        with self._lock:
            e = self._entries.get(fp)
            if e is None:
                return
            e["dispatches"] += 1
            if secs is not None:
                e["dispatch_s"] = round(e["dispatch_s"] + secs, 6)
            # graftlint: disable=GL004 wall TIMESTAMP by design - last_used_ts merges with the ts-stamped streams
            e["last_used_ts"] = round(time.time(), 3)

    def enrich(self, fp: str, jitfn, args) -> None:
        """Attach the XLA cost analysis (flops / bytes accessed) and
        output footprint via the jit AOT path: one extra trace +
        lowering OUTSIDE the jit cache (the cache the zero-recompile
        audits count is untouched; ``Lowered.cost_analysis()`` needs
        no XLA compile), so it runs only where a trace window is
        sanctioned - Server.warmup with the plane armed, and the jaxpr
        audit. Best-effort: cost analysis availability varies by
        backend and a forensics feature must never take serving
        down."""
        try:
            lowered = jitfn.lower(*args)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            out_bytes = None
            try:
                import numpy as np
                sizes = []

                def _sz(x):
                    sizes.append(int(np.prod(x.shape))
                                 * np.dtype(x.dtype).itemsize)
                import jax
                jax.tree.map(_sz, lowered.out_info)
                out_bytes = sum(sizes)
            except Exception:  # noqa: BLE001 - footprint optional
                out_bytes = None
            with self._lock:
                e = self._entries.get(fp)
                if e is None:
                    return
                if ca:
                    fl = ca.get("flops")
                    by = ca.get("bytes accessed")
                    e["flops"] = float(fl) if fl is not None else None
                    e["cost_bytes"] = (float(by) if by is not None
                                       else None)
                if out_bytes is not None:
                    e["out_bytes"] = out_bytes
        except Exception:  # noqa: BLE001 - introspection never kills serving
            pass

    def seen(self, fp: str) -> bool:
        with self._lock:
            return fp in self._entries

    def snapshot(self) -> List[Dict[str, Any]]:
        """Sorted (by name, then fingerprint) entry copies - the
        ``/executables`` body and the Prometheus series source."""
        with self._lock:
            got = [dict(e) for e in self._entries.values()]
        got.sort(key=lambda e: (e["name"], e["fingerprint"]))
        return got

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset(self) -> None:
        with self._lock:
            self._entries = {}
