"""Declarative alert engine over the metrics registry.

PR 2 made faults countable; nothing ACTED on the counts - a
nan-rollback storm or a serve queue backlog scrolled past on stderr
(the ROADMAP pod item's "alert hooks on the fault counters" open end).
This module evaluates rules loaded from ``alert_rules=rules.json``
against the live registry on a background thread. Three condition
types:

- **threshold**: an instrument's current value compared against a
  bound, sustained for ``for_secs`` (``serve.queue_depth > 100 for
  10s``; histograms pick a ``stat`` - p50/p99/mean/count/sum);
- **rate**: a counter's increments per minute over a sliding window
  (``fault.nan_rollback > 3/min``);
- **absence**: a progress beacon (watchdog.py's table) that has gone
  silent for ``for_secs`` (``no train.step for 120s``). Before the
  beacon's first sighting the grace is ``startup_grace_secs``
  (default 60) - compile time must not page anyone.

A FIRING rule: emits an ``alert`` event (state=firing), bumps
``alert.fired``, flips `/healthz` to 503 (health source
``alert:<name>``), and optionally launches the ``alert_cmd=`` shell
hook with ALERT_NAME/ALERT_STATE/ALERT_MESSAGE in its environment
(fire-and-forget; a broken hook is noted once, never fatal). When the
condition has been false for ``clear_secs`` (hysteresis, default 0 =
immediately) the rule RESOLVES: state=resolved event, health cleared -
`/healthz` returns to 200 iff no other source is unhealthy.

Rule files are validated eagerly at load: an unknown type or key is a
config error at startup, not a rule that silently never fires (the
same stance as the config schema gate, docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import collections
import json
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional

from cxxnet_tpu.telemetry.registry import Counter, Gauge, Histogram

STARTUP_GRACE_SECS = 60.0

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

# allowed keys per rule type: a typo'd key ("for_sec") must be a load
# error, not a rule that silently uses the default forever
_COMMON_KEYS = {"name", "type", "for_secs", "clear_secs"}
_RULE_KEYS = {
    "threshold": _COMMON_KEYS | {"metric", "op", "value", "stat"},
    "rate": _COMMON_KEYS | {"metric", "max_per_min", "window_secs"},
    "absence": _COMMON_KEYS | {"beacon", "startup_grace_secs"},
}
_HIST_STATS = ("p50", "p99", "mean", "count", "sum", "min", "max")
# numeric rule fields, coerced to float at load so a string "256" (a
# hand-written JSON slip) is a startup error, not a TypeError the
# evaluation loop would swallow forever
_NUMERIC_KEYS = ("value", "max_per_min", "window_secs", "for_secs",
                 "clear_secs", "startup_grace_secs")


def _validate_rule(rule: Dict, idx: int) -> Dict:
    if not isinstance(rule, dict):
        raise ValueError(f"alert rule #{idx} is not an object: {rule!r}")
    rtype = rule.get("type")
    if rtype not in _RULE_KEYS:
        raise ValueError(
            f"alert rule #{idx}: unknown type {rtype!r} "
            f"(want one of {sorted(_RULE_KEYS)})")
    bad = set(rule) - _RULE_KEYS[rtype]
    if bad:
        raise ValueError(
            f"alert rule #{idx} ({rtype}): unknown key(s) "
            f"{sorted(bad)} - allowed: {sorted(_RULE_KEYS[rtype])}")
    rule = dict(rule)
    rule.setdefault("name", f"rule{idx}")
    if rtype == "threshold":
        for k in ("metric", "op", "value"):
            if k not in rule:
                raise ValueError(
                    f"alert rule {rule['name']!r}: threshold needs "
                    f"'{k}'")
        if rule["op"] not in _OPS:
            raise ValueError(
                f"alert rule {rule['name']!r}: op {rule['op']!r} not "
                f"in {sorted(_OPS)}")
        stat = rule.setdefault("stat", "p99")
        if stat not in _HIST_STATS:
            raise ValueError(
                f"alert rule {rule['name']!r}: stat {stat!r} not in "
                f"{_HIST_STATS}")
    elif rtype == "rate":
        if "metric" not in rule or "max_per_min" not in rule:
            raise ValueError(
                f"alert rule {rule['name']!r}: rate needs 'metric' "
                "and 'max_per_min'")
        rule.setdefault("window_secs", 60.0)
    else:  # absence
        if "beacon" not in rule or "for_secs" not in rule:
            raise ValueError(
                f"alert rule {rule['name']!r}: absence needs 'beacon' "
                "and 'for_secs'")
        rule.setdefault("startup_grace_secs", STARTUP_GRACE_SECS)
    rule.setdefault("for_secs", 0.0)
    rule.setdefault("clear_secs", 0.0)
    for k in _NUMERIC_KEYS:
        if k not in rule:
            continue
        v = rule[k]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(
                f"alert rule {rule['name']!r}: '{k}' must be a "
                f"number, got {v!r}")
        rule[k] = float(v)
    return rule


def load_rules(path: str) -> List[Dict]:
    """Parse + validate a rules file: a JSON list of rule objects, or
    ``{"rules": [...]}``."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        doc = doc.get("rules", doc)
    if not isinstance(doc, list):
        raise ValueError(
            f"alert rules file {path}: want a JSON list of rules "
            f"(or {{'rules': [...]}}), got {type(doc).__name__}")
    rules = [_validate_rule(r, i) for i, r in enumerate(doc)]
    names = [r["name"] for r in rules]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(
            f"alert rules file {path}: duplicate rule name(s) "
            f"{sorted(dupes)}")
    return rules


class _RuleState:
    __slots__ = ("rule", "firing", "pending_since", "clear_since",
                 "samples", "fired_count", "broken")

    def __init__(self, rule: Dict) -> None:
        self.rule = rule
        self.firing = False
        self.pending_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        # rate rules: sliding window of (t, counter value)
        self.samples: collections.deque = collections.deque()
        self.fired_count = 0
        self.broken = False  # eval blew up (noted once)


class AlertEngine:
    """Evaluates rules on a daemon thread; ``check_now(now)`` is the
    deterministic entry point tests drive with a fake clock."""

    def __init__(self, tel, rules: List[Dict], alert_cmd: str = "",
                 poll_secs: Optional[float] = None) -> None:
        self.tel = tel
        self.alert_cmd = alert_cmd
        # normalize/validate here too (idempotent after load_rules):
        # programmatic rule lists get the same eager rejection and
        # defaulting the file loader applies
        rules = [_validate_rule(r, i) for i, r in enumerate(rules)]
        self.states = [_RuleState(r) for r in rules]
        if poll_secs is None:
            spans = [float(r.get("for_secs") or 0) for r in rules] + \
                    [float(r.get("window_secs") or 0) for r in rules]
            tight = min([s for s in spans if s > 0], default=4.0)
            poll_secs = min(max(tight / 4.0, 0.05), 1.0)
        self.poll_secs = float(poll_secs)
        self._armed_at = time.monotonic()
        self._hook_broken = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "AlertEngine":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-alerts", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for rs in self.states:
            if rs.firing:
                # same contract as the watchdog: a dying engine must
                # not leave a permanent 503 behind
                rs.firing = False
                self.tel.health.clear(f"alert:{rs.rule['name']}")

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_secs):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 - alerting never kills training
                pass

    # -- evaluation --------------------------------------------------------
    def check_now(self, now: Optional[float] = None) -> List[str]:
        """Evaluate every rule; returns the names currently firing.
        Rules are isolated: one rule blowing up (noted once on
        stderr) must not stop the rules after it from being
        evaluated."""
        now = time.monotonic() if now is None else now
        for rs in self.states:
            try:
                cond, msg = self._condition(rs, now)
                self._advance(rs, cond, msg, now)
            except Exception as e:  # noqa: BLE001 - per-rule isolation
                if not rs.broken:
                    rs.broken = True
                    self.tel.stderr(
                        f"alerts: rule {rs.rule['name']!r} failed to "
                        f"evaluate: {type(e).__name__}: {e}\n",
                        event_kind="alert", name=rs.rule["name"],
                        state="eval_error",
                        error=f"{type(e).__name__}: {e}")
        return [rs.rule["name"] for rs in self.states if rs.firing]

    def _value(self, metric: str, stat: str):
        inst = self.tel.registry.get(metric)
        if inst is None:
            return None
        if isinstance(inst, (Counter, Gauge)):
            return inst.value
        if isinstance(inst, Histogram):
            snap = inst.snapshot()
            return snap.get(stat)
        return None

    def _condition(self, rs: _RuleState, now: float):
        r = rs.rule
        if r["type"] == "threshold":
            v = self._value(r["metric"], r["stat"])
            if v is None:
                return False, ""
            hit = _OPS[r["op"]](v, r["value"])
            return hit, (f"{r['metric']} = {v:g} {r['op']} "
                         f"{r['value']:g}" if hit else "")
        if r["type"] == "rate":
            v = self._value(r["metric"], "count")
            if v is None:
                v = 0
            win = float(r["window_secs"])
            rs.samples.append((now, float(v)))
            # keep one sample older than the window as the baseline
            while (len(rs.samples) > 2
                   and now - rs.samples[1][0] >= win):
                rs.samples.popleft()
            t0, v0 = rs.samples[0]
            span = now - t0
            if span <= 0 or len(rs.samples) < 2:
                return False, ""
            per_min = (float(v) - v0) / span * 60.0
            hit = per_min > float(r["max_per_min"])
            return hit, (f"{r['metric']} at {per_min:.2f}/min > "
                         f"{r['max_per_min']:g}/min" if hit else "")
        # absence
        beacons = self.tel.beacons()
        b = beacons.get(r["beacon"])
        if b is None:
            age = now - self._armed_at
            grace = max(float(r["startup_grace_secs"]),
                        float(r["for_secs"]))
            hit = age >= grace
            return hit, (f"beacon {r['beacon']!r} never seen in "
                         f"{age:.1f}s" if hit else "")
        age = now - b[1]
        hit = age >= float(r["for_secs"])
        return hit, (f"no {r['beacon']!r} progress for {age:.1f}s"
                     if hit else "")

    def _advance(self, rs: _RuleState, cond: bool, msg: str,
                 now: float) -> None:
        r = rs.rule
        if cond:
            rs.clear_since = None
            if rs.firing:
                return
            if rs.pending_since is None:
                rs.pending_since = now
            # absence embeds its duration in the condition (for_secs
            # IS the beacon-age threshold); threshold and rate sustain
            # the condition for_secs before firing
            wait = (0.0 if r["type"] == "absence"
                    else float(r["for_secs"]))
            if now - rs.pending_since >= wait:
                self._fire(rs, msg, now)
        else:
            rs.pending_since = None
            if not rs.firing:
                return
            if rs.clear_since is None:
                rs.clear_since = now
            if now - rs.clear_since >= float(r["clear_secs"]):
                self._resolve(rs, now)

    # -- transitions -------------------------------------------------------
    def _fire(self, rs: _RuleState, msg: str, now: float) -> None:
        rs.firing = True
        rs.fired_count += 1
        name = rs.rule["name"]
        self.tel.inc("alert.fired")
        self.tel.event("alert", name=name, state="firing",
                       rule_type=rs.rule["type"], message=msg)
        self.tel.health.set_unhealthy(f"alert:{name}", msg)
        self._run_hook(name, "firing", msg)

    def _resolve(self, rs: _RuleState, now: float) -> None:
        rs.firing = False
        rs.clear_since = None
        name = rs.rule["name"]
        self.tel.inc("alert.resolved")
        self.tel.event("alert", name=name, state="resolved",
                       rule_type=rs.rule["type"])
        self.tel.health.clear(f"alert:{name}")
        self._run_hook(name, "resolved", "")

    def _run_hook(self, name: str, state: str, msg: str) -> None:
        if not self.alert_cmd:
            return
        env = dict(os.environ, ALERT_NAME=name, ALERT_STATE=state,
                   ALERT_MESSAGE=msg)
        try:
            subprocess.Popen(  # noqa: S602 - operator-supplied hook
                self.alert_cmd, shell=True, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except OSError as e:
            if not self._hook_broken:
                self._hook_broken = True
                self.tel.stderr(
                    f"alerts: alert_cmd failed to launch: {e}\n",
                    event_kind="alert", name=name, state="hook_error",
                    error=str(e))
