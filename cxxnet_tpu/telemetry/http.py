"""HTTP exposition: `/metrics` (Prometheus), `/healthz`, `/varz`.

The registry and JSONL streams (registry.py / sink.py) are complete
but *offline* - nothing could watch a live run without tailing files.
This module is the live side: a stdlib-only background HTTP server
(the repo's first real network transport - a stepping stone for the
serving-transport roadmap item) exposing

- ``/metrics``: Prometheus text exposition (version 0.0.4) of the
  full registry - counters as ``cxxnet_<name>_total``, gauges as
  ``cxxnet_<name>``, histograms as summaries with ``quantile="0.5"``
  / ``quantile="0.99"`` series plus ``_sum``/``_count`` (the same
  count/sum/p50/p99 the JSONL snapshots carry). Dots become
  underscores; a process-tag info metric (``cxxnet_process_info``)
  carries the {host, pid, proc, device} tags as escaped labels so a
  multi-host scrape stays attributable.
- ``/healthz``: 200 while the process is healthy, 503 with the
  reasons JSON once the watchdog or an alert rule flags it
  (health.py); scrape-friendly liveness for load balancers and the
  obs-smoke CI job.
- ``/varz``: one JSON object, byte-compatible with a metrics-stream
  record (``{ts, host, pid, proc, ..., kind: "varz", metrics: {...}}``)
  so ``tools/agg.py`` can scrape live processes and file tails with
  the same parser; with the flight recorder armed the record
  additionally carries a ``flight`` tail (recent + in-flight
  dispatches - docs/OBSERVABILITY.md "Flight recorder").
- ``/executables``: the executable introspection plane (flight.py
  registry): one JSON entry per compiled program shape - fingerprint,
  site name/kind, compile wall-time, XLA cost-analysis flops/bytes,
  output/donation footprint and dispatch counts - plus the currently
  in-flight dispatches. The same facts export as labeled Prometheus
  series (``cxxnet_executable_*{fingerprint=...}``) on ``/metrics``.

With a serving backend attached (``Server(http_port=...)`` / the CLI
``serve_port=`` key) the same listener additionally routes ``POST
/predict`` - the serving request path (docs/SERVING.md "Serving over
HTTP"); the protocol mapping (429 + Retry-After on shed, 504 on
deadline expiry) lives on the Server, this module is transport only.

Connection-level ingress hardening (docs/SERVING.md "Connection
limits & drain") arms with ``serve_conn_timeout_ms`` /
``serve_max_conns`` / ``serve_max_body_bytes``: per-connection
header/body read deadlines (a slow-loris client is cut, not
serviced), a max-body gate (413 before the body is read), and an
accept gate answering an immediate raw 503 + Retry-After when
``max_conns`` handler threads are live - with its own ``serve_conns``
health source and the same hysteretic recovery as load shedding.
With the keys unset the plain ``ThreadingHTTPServer`` path is used
unchanged (byte parity).

Armed only by ``metrics_port=`` / ``serve_port=`` (or
``Server(metrics_port=...)``); with the keys unset this module is
never imported - the CLI byte-parity contract costs nothing.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from cxxnet_tpu.telemetry.registry import (
    BucketHistogram, Counter, Gauge, Histogram)
from cxxnet_tpu.telemetry.sink import _sanitize
from cxxnet_tpu.utils import fault

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Prometheus metric-name alphabet; everything else becomes "_"
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "cxxnet_"


def prom_name(name: str) -> str:
    """Registry name -> Prometheus name: dotted-lowercase grammar
    (GL008) maps onto the prom alphabet by replacing dots; anything
    foreign is flattened to underscores and a leading digit is
    shielded (prom names must not start with one)."""
    out = _BAD_CHARS.sub("_", name.replace(".", "_"))
    if out and out[0].isdigit():
        out = "_" + out
    return _PREFIX + out


def prom_label_escape(v: object) -> str:
    """Label-value escaping per the text exposition spec: backslash,
    double quote and newline."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v) -> str:
    """One sample value: prom accepts NaN/+Inf/-Inf tokens (which the
    JSONL sinks must NOT emit - different consumers, different
    specs)."""
    if v is None:
        return "NaN"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(tel) -> str:
    """The full registry as Prometheus text exposition, sorted by
    name so consecutive scrapes diff cleanly."""
    lines: List[str] = []
    tags = tel.tags()
    labels = ",".join(f'{k}="{prom_label_escape(v)}"'
                      for k, v in sorted(tags.items()))
    lines.append("# TYPE cxxnet_process_info gauge")
    lines.append("cxxnet_process_info{%s} 1" % labels)
    for name, inst in sorted(tel.registry.instruments().items()):
        pname = prom_name(name)
        if isinstance(inst, Counter):
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {_fmt_value(inst.value)}")
        elif isinstance(inst, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt_value(inst.value)}")
        elif isinstance(inst, BucketHistogram):
            snap = inst.snapshot()
            lines.append(f"# TYPE {pname} histogram")
            for le, cum in snap["buckets"].items():
                lines.append(f'{pname}_bucket{{le="{le}"}} '
                             f"{_fmt_value(cum)}")
            lines.append(f"{pname}_sum {_fmt_value(snap['sum'])}")
            lines.append(f"{pname}_count {_fmt_value(snap['count'])}")
        elif isinstance(inst, Histogram):
            snap = inst.snapshot()
            lines.append(f"# TYPE {pname} summary")
            lines.append(f'{pname}{{quantile="0.5"}} '
                         f'{_fmt_value(snap["p50"])}')
            lines.append(f'{pname}{{quantile="0.99"}} '
                         f'{_fmt_value(snap["p99"])}')
            lines.append(f"{pname}_sum {_fmt_value(snap['sum'])}")
            lines.append(f"{pname}_count {_fmt_value(snap['count'])}")
    lines.extend(_render_executables(tel))
    return "\n".join(lines) + "\n"


def _render_executables(tel) -> List[str]:
    """Per-executable introspection series (flight.py registry) plus
    the flight-recorder liveness gauges. Labeled by fingerprint so a
    multi-bucket serving process exports one series per warmed
    program shape - the Grafana twin of `/executables`."""
    execs = tel.executables.snapshot()
    lines: List[str] = []
    if execs:
        lines.append("# TYPE cxxnet_executable_dispatches_total counter")
        for e in execs:
            lab = (f'fingerprint="{prom_label_escape(e["fingerprint"])}"'
                   f',name="{prom_label_escape(e["name"])}"'
                   f',kind="{prom_label_escape(e["kind"])}"')
            lines.append("cxxnet_executable_dispatches_total{%s} %s"
                         % (lab, _fmt_value(e["dispatches"])))
        for field, pname in (("compile_s",
                              "cxxnet_executable_compile_seconds"),
                             ("flops", "cxxnet_executable_flops"),
                             ("cost_bytes",
                              "cxxnet_executable_cost_bytes")):
            rows = [e for e in execs if e.get(field) is not None]
            if not rows:
                continue
            lines.append(f"# TYPE {pname} gauge")
            for e in rows:
                lab = (f'fingerprint='
                       f'"{prom_label_escape(e["fingerprint"])}"'
                       f',name="{prom_label_escape(e["name"])}"')
                lines.append("%s{%s} %s"
                             % (pname, lab, _fmt_value(e[field])))
    if tel.flight.enabled:
        lines.append("# TYPE cxxnet_flight_inflight gauge")
        lines.append("cxxnet_flight_inflight "
                     + _fmt_value(len(tel.flight.in_flight())))
    return lines


# one exposition line: comment, or `name[{labels}] value` where value
# is a float or a NaN/+Inf/-Inf token (promtool's line grammar, the
# check the obs-smoke job and the tests run over real scrapes)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" (NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?)$")
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def validate_exposition(text: str) -> List[str]:
    """Promtool-style line check of a `/metrics` body; returns the
    list of malformed lines (empty = valid)."""
    bad = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _COMMENT_RE.match(line):
                bad.append(line)
        elif not _SAMPLE_RE.match(line):
            bad.append(line)
    return bad


class IngressLimits:
    """Connection-level ingress protection shared by the accept gate
    and the request handlers. One instance per ObservabilityServer;
    built only when at least one of the serve_conn_timeout_ms /
    serve_max_conns / serve_max_body_bytes keys is armed, so the
    unarmed listener carries zero extra state."""

    def __init__(self, tel, max_conns: int = 0,
                 conn_timeout_ms: float = 0.0,
                 max_body_bytes: int = 0, clear_ms: float = 1000.0):
        self._tel = tel
        self.max_conns = int(max_conns or 0)
        t = float(conn_timeout_ms or 0.0)
        self.conn_timeout_s = t / 1e3 if t > 0 else 0.0
        self.max_body_bytes = int(max_body_bytes or 0)
        self.clear_s = max(float(clear_ms or 0.0), 0.0) / 1e3
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._active = 0
        # guarded-by: self._lock
        self._n_rejected = 0
        # guarded-by: self._lock
        self._n_timeouts = 0
        # guarded-by: self._lock
        self._n_oversized = 0
        # guarded-by: self._lock
        self._last_reject_t = 0.0
        # guarded-by: self._lock
        self._gate_health = False

    def try_enter(self) -> bool:
        """Accept gate: called on the accept path before a handler
        thread is spawned. False = saturated; the caller answers an
        immediate 503 + Retry-After and closes the socket."""
        flip = False
        rejected = 0
        with self._lock:
            if 0 < self.max_conns <= self._active:
                self._n_rejected += 1
                self._last_reject_t = time.monotonic()
                if not self._gate_health:
                    self._gate_health = True
                    flip = True
                rejected = self._n_rejected
                ok = False
            else:
                self._active += 1
                ok = True
        if not ok:
            # telemetry strictly OUTSIDE the lock (the repo's lock
            # idiom: no I/O or cross-lock calls while held)
            self._tel.inc("serve.conn_rejected")
            if flip:
                self._tel.health.set_unhealthy(
                    "serve_conns",
                    f"connection limit saturated "
                    f"(serve_max_conns={self.max_conns})")
                self._tel.event("serve", op="conn_saturated",
                                max_conns=self.max_conns,
                                rejected=rejected)
        return ok

    def leave(self) -> None:
        with self._lock:
            self._active -= 1
        self._maybe_recover()

    def _maybe_recover(self) -> None:
        """Hysteretic gate recovery (the serve_shed pattern): clear
        the serve_conns health verdict only once occupancy fell below
        HALF the limit AND clear_ms passed since the last rejection -
        a gate oscillating at the limit must not flap /healthz."""
        clear = False
        with self._lock:
            if (self._gate_health
                    and self._active * 2 < max(self.max_conns, 1)
                    and (time.monotonic() - self._last_reject_t
                         >= self.clear_s)):
                self._gate_health = False
                clear = True
        if clear:
            self._tel.health.clear("serve_conns")
            self._tel.event("serve", op="conn_recovered",
                            max_conns=self.max_conns)

    def note_timeout(self, phase: str) -> None:
        """A connection was cut at the read deadline (phase: headers
        held open vs body dribbled - the two slow-loris shapes)."""
        with self._lock:
            self._n_timeouts += 1
        self._tel.inc("serve.conn_timeouts")
        self._tel.event("serve", op="conn_timeout", phase=phase,
                        timeout_ms=round(self.conn_timeout_s * 1e3, 1))

    def note_oversized(self, n: int) -> None:
        with self._lock:
            self._n_oversized += 1
        self._tel.inc("serve.conn_oversized")
        self._tel.event("serve", op="conn_oversized", bytes=int(n),
                        max_body_bytes=self.max_body_bytes)

    def release_health(self) -> None:
        """Listener closing: a dead socket is not 'saturated'."""
        with self._lock:
            held = self._gate_health
            self._gate_health = False
        if held:
            self._tel.health.clear("serve_conns")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "conn_active": self._active,
                "conn_rejected": self._n_rejected,
                "conn_timeouts": self._n_timeouts,
                "conn_oversized": self._n_oversized,
            }


class _IngressServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with the accept gate: when max_conns
    handler threads are live, a new connection gets a raw 503 +
    Retry-After ON THE ACCEPT PATH - no handler thread is spawned
    for it, so a connection flood cannot grow the thread pool past
    the limit."""

    daemon_threads = True

    def __init__(self, addr, handler, limits: IngressLimits):
        self._limits = limits
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        if not self._limits.try_enter():
            body = b'{"error": "connection limit reached"}'
            try:
                # bounded write: the reject path must never block on
                # a client that won't read
                request.settimeout(1.0)
                request.sendall(
                    b"HTTP/1.0 503 Service Unavailable\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Retry-After: 1\r\n"
                    b"Content-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)
            except OSError:
                pass  # client gone; the rejection still counted
            self.shutdown_request(request)
            return
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._limits.leave()


def _make_handler(tel, predict_backend=None, limits=None):
    conn_timeout = (limits.conn_timeout_s
                    if limits is not None and limits.conn_timeout_s > 0
                    else None)

    class _Handler(BaseHTTPRequestHandler):
        # one scrape per GET; no keep-alive state worth protocol 1.1
        protocol_version = "HTTP/1.0"
        # StreamRequestHandler.setup() applies this to the accepted
        # socket: EVERY blocking read (header line, body chunk) gets
        # the per-connection deadline, so a client holding its
        # headers open is cut at serve_conn_timeout_ms (None = the
        # unarmed, wait-forever stdlib default)
        timeout = conn_timeout

        def _send(self, code: int, body: bytes, ctype: str,
                  headers=None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self) -> None:  # noqa: N802 - stdlib handler API
            # the serving request path (docs/SERVING.md "Serving over
            # HTTP"): present only when a Server attached with
            # serve_port/http_port; all protocol mapping (429 +
            # Retry-After, 504 deadline, 400/500) lives in
            # Server.handle_predict - this handler is pure transport
            path = self.path.split("?", 1)[0]
            try:
                if path != "/predict" or predict_backend is None:
                    self._send(404, b"not found\n", "text/plain")
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    n = 0
                if (limits is not None
                        and 0 < limits.max_body_bytes < n):
                    # rejected BEFORE the body is read: a bloated
                    # client pays for its own upload, not us
                    limits.note_oversized(n)
                    self.close_connection = True
                    self._send(413, json.dumps({
                        "error": "request body too large",
                        "bytes": n,
                        "max_body_bytes": limits.max_body_bytes,
                    }).encode(), "application/json")
                    return
                if limits is None:
                    body = self.rfile.read(n) if n > 0 else b""
                else:
                    body = self._read_body(n)
                    if body is None:
                        return  # cut at the deadline; 408 sent
                code, headers, out = predict_backend.handle_predict(
                    body)
                self._send(code, out, "application/json",
                           headers=headers)
            except (BrokenPipeError, ConnectionResetError):
                pass  # caller went away mid-write; nothing to save

        def _read_body(self, n: int) -> Optional[bytes]:
            """Read the request body against the per-connection
            deadline: chunked, so a slow-loris client dribbling
            bytes cannot extend its stay - the ABSOLUTE deadline
            (set when the body read starts) cuts it regardless of
            per-read progress. Returns None when the connection was
            cut (408 already sent, socket closing)."""
            if n <= 0:
                return b""
            deadline = (time.monotonic() + limits.conn_timeout_s
                        if limits.conn_timeout_s > 0 else None)
            chunks: List[bytes] = []
            got = 0
            try:
                while got < n:
                    # serve_slow_client fault point (CXXNET_FAULT):
                    # delay mode stalls this loop exactly like a
                    # dribbling client, so the deadline cut is
                    # testable without a real slow socket
                    fault.fault_point("serve_slow_client")
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        raise TimeoutError("body read deadline")
                    chunk = self.rfile.read(min(n - got, 65536))
                    if not chunk:
                        break  # short body; json decode will 400 it
                    chunks.append(chunk)
                    got += len(chunk)
            except (TimeoutError, OSError):
                limits.note_timeout("body")
                self.close_connection = True
                try:
                    self._send(408, json.dumps({
                        "error": "request body read timed out",
                        "timeout_ms": round(
                            limits.conn_timeout_s * 1e3, 1),
                    }).encode(), "application/json")
                except OSError:
                    pass  # client gone; the cut still counted
                return None
            return b"".join(chunks)

        def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._send(200, render_prometheus(tel).encode(),
                               PROM_CONTENT_TYPE)
                elif path == "/varz":
                    rec = tel.snapshot_record(kind="varz")
                    if tel.flight.enabled:
                        # flight-recorder tail rides the varz record
                        # (extra key; the metrics-stream schema's
                        # parsers read known keys): a remote operator
                        # sees the in-flight dispatch of a hung host
                        # without shell access to it
                        rec["flight"] = tel.flight.tail(32)
                    self._send(200, json.dumps(
                        _sanitize(rec), separators=(",", ":"),
                        default=str).encode(), "application/json")
                elif path == "/executables":
                    rec = tel._record("executables", {
                        "executables": tel.executables.snapshot(),
                        "in_flight": tel.flight.in_flight()})
                    self._send(200, json.dumps(
                        _sanitize(rec), separators=(",", ":"),
                        default=str).encode(), "application/json")
                elif path in ("/healthz", "/health"):
                    ok, reasons = tel.health.status()
                    body = json.dumps(
                        {"ok": ok, "reasons": reasons}).encode()
                    self._send(200 if ok else 503, body,
                               "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper went away mid-write; nothing to save

        def log_message(self, *args) -> None:
            # BaseHTTPRequestHandler logs every request to stderr by
            # default - scrape traffic must never touch the CLI's
            # stderr (byte-parity applies to the ARMED run's normal
            # lines too; scrapes are not run output)
            pass

        def log_error(self, fmt, *args) -> None:
            # the parent's handle_one_request absorbs a HEADER-phase
            # socket timeout (the classic slow-loris: connect, never
            # finish the request line) and reports it only here
            # ("Request timed out: ..."), so this override is where
            # that cut becomes a counted serve.conn_timeouts event
            if limits is not None and "timed out" in str(fmt):
                limits.note_timeout("headers")

    return _Handler


class ObservabilityServer:
    """Background exposition server. Binds at construction (so the
    resolved port - meaningful with port=0 ephemeral binds in tests -
    is immediately readable), serves on a daemon thread after
    ``start()``, and ``close()`` shuts the socket down and joins."""

    def __init__(self, tel, port: int = 0, host: str = "0.0.0.0",
                 predict_backend=None, conn_timeout_ms: float = 0.0,
                 max_conns: int = 0, max_body_bytes: int = 0,
                 conn_clear_ms: float = 1000.0):
        limits = None
        if ((conn_timeout_ms or 0) > 0 or (max_conns or 0) > 0
                or (max_body_bytes or 0) > 0):
            limits = IngressLimits(
                tel, max_conns=max_conns,
                conn_timeout_ms=conn_timeout_ms,
                max_body_bytes=max_body_bytes,
                clear_ms=conn_clear_ms)
        self._limits = limits
        handler = _make_handler(tel, predict_backend=predict_backend,
                                limits=limits)
        if limits is not None:
            self._srv = _IngressServer((host, int(port)), handler,
                                       limits)
        else:
            # unarmed parity: the exact pre-hardening server class
            self._srv = ThreadingHTTPServer((host, int(port)), handler)
            self._srv.daemon_threads = True
        self.port: int = self._srv.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObservabilityServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._srv.serve_forever,
                name="telemetry-http", daemon=True)
            self._thread.start()
        return self

    def ingress_stats(self) -> Dict[str, int]:
        """Connection-gate counters (empty dict when the ingress
        limits are unarmed); merged into Server.stats()."""
        return self._limits.stats() if self._limits is not None else {}

    def close(self) -> None:
        if self._thread is not None:
            self._srv.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._srv.server_close()
        if self._limits is not None:
            self._limits.release_health()
