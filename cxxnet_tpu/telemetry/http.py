"""HTTP exposition: `/metrics` (Prometheus), `/healthz`, `/varz`.

The registry and JSONL streams (registry.py / sink.py) are complete
but *offline* - nothing could watch a live run without tailing files.
This module is the live side: a stdlib-only background HTTP server
(the repo's first real network transport - a stepping stone for the
serving-transport roadmap item) exposing

- ``/metrics``: Prometheus text exposition (version 0.0.4) of the
  full registry - counters as ``cxxnet_<name>_total``, gauges as
  ``cxxnet_<name>``, histograms as summaries with ``quantile="0.5"``
  / ``quantile="0.99"`` series plus ``_sum``/``_count`` (the same
  count/sum/p50/p99 the JSONL snapshots carry). Dots become
  underscores; a process-tag info metric (``cxxnet_process_info``)
  carries the {host, pid, proc, device} tags as escaped labels so a
  multi-host scrape stays attributable.
- ``/healthz``: 200 while the process is healthy, 503 with the
  reasons JSON once the watchdog or an alert rule flags it
  (health.py); scrape-friendly liveness for load balancers and the
  obs-smoke CI job.
- ``/varz``: one JSON object, byte-compatible with a metrics-stream
  record (``{ts, host, pid, proc, ..., kind: "varz", metrics: {...}}``)
  so ``tools/agg.py`` can scrape live processes and file tails with
  the same parser; with the flight recorder armed the record
  additionally carries a ``flight`` tail (recent + in-flight
  dispatches - docs/OBSERVABILITY.md "Flight recorder").
- ``/executables``: the executable introspection plane (flight.py
  registry): one JSON entry per compiled program shape - fingerprint,
  site name/kind, compile wall-time, XLA cost-analysis flops/bytes,
  output/donation footprint and dispatch counts - plus the currently
  in-flight dispatches. The same facts export as labeled Prometheus
  series (``cxxnet_executable_*{fingerprint=...}``) on ``/metrics``.

With a serving backend attached (``Server(http_port=...)`` / the CLI
``serve_port=`` key) the same listener additionally routes ``POST
/predict`` - the serving request path (docs/SERVING.md "Serving over
HTTP"); the protocol mapping (429 + Retry-After on shed, 504 on
deadline expiry) lives on the Server, this module is transport only.

Armed only by ``metrics_port=`` / ``serve_port=`` (or
``Server(metrics_port=...)``); with the keys unset this module is
never imported - the CLI byte-parity contract costs nothing.
"""

from __future__ import annotations

import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from cxxnet_tpu.telemetry.registry import (
    BucketHistogram, Counter, Gauge, Histogram)
from cxxnet_tpu.telemetry.sink import _sanitize

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Prometheus metric-name alphabet; everything else becomes "_"
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "cxxnet_"


def prom_name(name: str) -> str:
    """Registry name -> Prometheus name: dotted-lowercase grammar
    (GL008) maps onto the prom alphabet by replacing dots; anything
    foreign is flattened to underscores and a leading digit is
    shielded (prom names must not start with one)."""
    out = _BAD_CHARS.sub("_", name.replace(".", "_"))
    if out and out[0].isdigit():
        out = "_" + out
    return _PREFIX + out


def prom_label_escape(v: object) -> str:
    """Label-value escaping per the text exposition spec: backslash,
    double quote and newline."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v) -> str:
    """One sample value: prom accepts NaN/+Inf/-Inf tokens (which the
    JSONL sinks must NOT emit - different consumers, different
    specs)."""
    if v is None:
        return "NaN"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(tel) -> str:
    """The full registry as Prometheus text exposition, sorted by
    name so consecutive scrapes diff cleanly."""
    lines: List[str] = []
    tags = tel.tags()
    labels = ",".join(f'{k}="{prom_label_escape(v)}"'
                      for k, v in sorted(tags.items()))
    lines.append("# TYPE cxxnet_process_info gauge")
    lines.append("cxxnet_process_info{%s} 1" % labels)
    for name, inst in sorted(tel.registry.instruments().items()):
        pname = prom_name(name)
        if isinstance(inst, Counter):
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {_fmt_value(inst.value)}")
        elif isinstance(inst, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt_value(inst.value)}")
        elif isinstance(inst, BucketHistogram):
            snap = inst.snapshot()
            lines.append(f"# TYPE {pname} histogram")
            for le, cum in snap["buckets"].items():
                lines.append(f'{pname}_bucket{{le="{le}"}} '
                             f"{_fmt_value(cum)}")
            lines.append(f"{pname}_sum {_fmt_value(snap['sum'])}")
            lines.append(f"{pname}_count {_fmt_value(snap['count'])}")
        elif isinstance(inst, Histogram):
            snap = inst.snapshot()
            lines.append(f"# TYPE {pname} summary")
            lines.append(f'{pname}{{quantile="0.5"}} '
                         f'{_fmt_value(snap["p50"])}')
            lines.append(f'{pname}{{quantile="0.99"}} '
                         f'{_fmt_value(snap["p99"])}')
            lines.append(f"{pname}_sum {_fmt_value(snap['sum'])}")
            lines.append(f"{pname}_count {_fmt_value(snap['count'])}")
    lines.extend(_render_executables(tel))
    return "\n".join(lines) + "\n"


def _render_executables(tel) -> List[str]:
    """Per-executable introspection series (flight.py registry) plus
    the flight-recorder liveness gauges. Labeled by fingerprint so a
    multi-bucket serving process exports one series per warmed
    program shape - the Grafana twin of `/executables`."""
    execs = tel.executables.snapshot()
    lines: List[str] = []
    if execs:
        lines.append("# TYPE cxxnet_executable_dispatches_total counter")
        for e in execs:
            lab = (f'fingerprint="{prom_label_escape(e["fingerprint"])}"'
                   f',name="{prom_label_escape(e["name"])}"'
                   f',kind="{prom_label_escape(e["kind"])}"')
            lines.append("cxxnet_executable_dispatches_total{%s} %s"
                         % (lab, _fmt_value(e["dispatches"])))
        for field, pname in (("compile_s",
                              "cxxnet_executable_compile_seconds"),
                             ("flops", "cxxnet_executable_flops"),
                             ("cost_bytes",
                              "cxxnet_executable_cost_bytes")):
            rows = [e for e in execs if e.get(field) is not None]
            if not rows:
                continue
            lines.append(f"# TYPE {pname} gauge")
            for e in rows:
                lab = (f'fingerprint='
                       f'"{prom_label_escape(e["fingerprint"])}"'
                       f',name="{prom_label_escape(e["name"])}"')
                lines.append("%s{%s} %s"
                             % (pname, lab, _fmt_value(e[field])))
    if tel.flight.enabled:
        lines.append("# TYPE cxxnet_flight_inflight gauge")
        lines.append("cxxnet_flight_inflight "
                     + _fmt_value(len(tel.flight.in_flight())))
    return lines


# one exposition line: comment, or `name[{labels}] value` where value
# is a float or a NaN/+Inf/-Inf token (promtool's line grammar, the
# check the obs-smoke job and the tests run over real scrapes)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" (NaN|[+-]Inf|[+-]?[0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?)$")
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def validate_exposition(text: str) -> List[str]:
    """Promtool-style line check of a `/metrics` body; returns the
    list of malformed lines (empty = valid)."""
    bad = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _COMMENT_RE.match(line):
                bad.append(line)
        elif not _SAMPLE_RE.match(line):
            bad.append(line)
    return bad


def _make_handler(tel, predict_backend=None):
    class _Handler(BaseHTTPRequestHandler):
        # one scrape per GET; no keep-alive state worth protocol 1.1
        protocol_version = "HTTP/1.0"

        def _send(self, code: int, body: bytes, ctype: str,
                  headers=None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self) -> None:  # noqa: N802 - stdlib handler API
            # the serving request path (docs/SERVING.md "Serving over
            # HTTP"): present only when a Server attached with
            # serve_port/http_port; all protocol mapping (429 +
            # Retry-After, 504 deadline, 400/500) lives in
            # Server.handle_predict - this handler is pure transport
            path = self.path.split("?", 1)[0]
            try:
                if path != "/predict" or predict_backend is None:
                    self._send(404, b"not found\n", "text/plain")
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    n = 0
                body = self.rfile.read(n) if n > 0 else b""
                code, headers, out = predict_backend.handle_predict(
                    body)
                self._send(code, out, "application/json",
                           headers=headers)
            except (BrokenPipeError, ConnectionResetError):
                pass  # caller went away mid-write; nothing to save

        def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    self._send(200, render_prometheus(tel).encode(),
                               PROM_CONTENT_TYPE)
                elif path == "/varz":
                    rec = tel.snapshot_record(kind="varz")
                    if tel.flight.enabled:
                        # flight-recorder tail rides the varz record
                        # (extra key; the metrics-stream schema's
                        # parsers read known keys): a remote operator
                        # sees the in-flight dispatch of a hung host
                        # without shell access to it
                        rec["flight"] = tel.flight.tail(32)
                    self._send(200, json.dumps(
                        _sanitize(rec), separators=(",", ":"),
                        default=str).encode(), "application/json")
                elif path == "/executables":
                    rec = tel._record("executables", {
                        "executables": tel.executables.snapshot(),
                        "in_flight": tel.flight.in_flight()})
                    self._send(200, json.dumps(
                        _sanitize(rec), separators=(",", ":"),
                        default=str).encode(), "application/json")
                elif path in ("/healthz", "/health"):
                    ok, reasons = tel.health.status()
                    body = json.dumps(
                        {"ok": ok, "reasons": reasons}).encode()
                    self._send(200 if ok else 503, body,
                               "application/json")
                else:
                    self._send(404, b"not found\n", "text/plain")
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper went away mid-write; nothing to save

        def log_message(self, *args) -> None:
            # BaseHTTPRequestHandler logs every request to stderr by
            # default - scrape traffic must never touch the CLI's
            # stderr (byte-parity applies to the ARMED run's normal
            # lines too; scrapes are not run output)
            pass

    return _Handler


class ObservabilityServer:
    """Background exposition server. Binds at construction (so the
    resolved port - meaningful with port=0 ephemeral binds in tests -
    is immediately readable), serves on a daemon thread after
    ``start()``, and ``close()`` shuts the socket down and joins."""

    def __init__(self, tel, port: int = 0, host: str = "0.0.0.0",
                 predict_backend=None):
        self._srv = ThreadingHTTPServer(
            (host, int(port)),
            _make_handler(tel, predict_backend=predict_backend))
        self._srv.daemon_threads = True
        self.port: int = self._srv.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObservabilityServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._srv.serve_forever,
                name="telemetry-http", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._srv.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._srv.server_close()
