"""Structured sinks for telemetry events and metric snapshots.

One record per line. ``json`` format emits canonical JSONL (the
machine-readable stream docs/OBSERVABILITY.md specifies; multi-process
runs tag every record with host/pid/proc so streams merge with a plain
``sort -k ts``); ``text`` format renders the same record as a
``ts kind k=v ...`` line for eyeballing. Writes are line-atomic under a
lock and the file is opened append-mode, so a resumed run extends the
same stream instead of truncating the preempted run's history.
"""

from __future__ import annotations

import json
import math
import sys
import threading
from typing import Dict, Optional


def _json_default(o):
    """Serialize numpy scalars/arrays and anything else foreign: try
    the numeric value first, fall back to repr text (a telemetry write
    must never raise into the training loop). Non-finite numerics
    become null - see _sanitize."""
    try:
        v = float(o)
    except (TypeError, ValueError):
        return str(o)
    return v if math.isfinite(v) else None


def _sanitize(o):
    """Replace non-finite floats with null, recursively. json.dumps
    would emit bare NaN/Infinity tokens (invalid per RFC 8259, rejected
    by jq/JS) - and the NaN paths are exactly the fault events
    telemetry exists to record (a diverging run's loss gauge goes NaN
    and would poison every later snapshot)."""
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, dict):
        return {k: _sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_sanitize(v) for v in o]
    return o


def format_record(record: Dict[str, object], fmt: str = "json") -> str:
    if fmt == "json":
        return json.dumps(_sanitize(record), separators=(",", ":"),
                          default=_json_default)
    # text: ts + kind first, remaining fields as k=v
    parts = []
    ts = record.get("ts")
    if ts is not None:
        parts.append(f"{ts:.3f}" if isinstance(ts, float) else str(ts))
    kind = record.get("kind")
    if kind is not None:
        parts.append(str(kind))
    for k in sorted(record):
        if k in ("ts", "kind"):
            continue
        v = record[k]
        if isinstance(v, dict):
            v = json.dumps(v, separators=(",", ":"),
                           default=_json_default)
        parts.append(f"{k}={v}")
    return " ".join(parts)


class LineSink:
    """Append-mode line writer with locked, flushed writes.

    Flushing every record is deliberate: telemetry exists to explain
    crashes and preemptions, so the stream must be complete up to the
    last event before the process died (buffered tails would vanish
    with exactly the records that matter)."""

    def __init__(self, path: str, fmt: str = "json"):
        if fmt not in ("json", "text"):
            raise ValueError(f"log_format must be json or text, got {fmt!r}")
        self.path = path
        self.fmt = fmt
        self._lock = threading.Lock()
        self._f: Optional[object] = open(path, "a", encoding="utf-8")

    def _drop(self, exc: BaseException) -> None:
        """Disable the sink after an IO failure: telemetry must never
        take training down (ENOSPC / NFS blip on the stream file is
        not a training error), and a raise from the run-teardown emit
        would mask the real exception. Noted once on stderr."""
        try:
            self._f.close()
        except (OSError, ValueError):
            pass
        self._f = None
        sys.stderr.write(
            f"telemetry: disabling sink {self.path}: "
            f"{type(exc).__name__}: {exc}\n")

    def write(self, record: Dict[str, object]) -> None:
        line = format_record(record, self.fmt)
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except (OSError, ValueError) as e:
                self._drop(e)

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                except (OSError, ValueError) as e:
                    self._drop(e)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except (OSError, ValueError):
                    pass
                self._f = None


def read_jsonl(path: str):
    """Parse a JSONL telemetry stream, skipping blank/corrupt lines
    (a run killed mid-write may leave a torn last line; the readable
    prefix is still the whole point of the stream). Yields dicts."""
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                yield rec
