"""Process health state: the single source of truth for `/healthz`.

One registry of (source -> reason) strings. A source that detects a
problem calls ``set_unhealthy``; when the condition clears it calls
``clear`` - so `/healthz` flips back to 200 exactly when every
detector has recovered (the hysteresis contract the alert engine and
watchdog both honor). Sources are namespaced strings ("watchdog",
"alert:<rule-name>") so independent detectors never clobber each
other's verdicts.

Stdlib-only and jax-free like the rest of the telemetry plane.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple


class HealthState:
    """Thread-safe (source -> reason) map; healthy iff empty."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._reasons: Dict[str, str] = {}

    def set_unhealthy(self, source: str, reason: str) -> None:
        with self._lock:
            self._reasons[source] = reason

    def clear(self, source: str) -> None:
        with self._lock:
            self._reasons.pop(source, None)

    def reset(self) -> None:
        with self._lock:
            self._reasons = {}

    @property
    def ok(self) -> bool:
        with self._lock:
            return not self._reasons

    def status(self) -> Tuple[bool, Dict[str, str]]:
        """(healthy?, {source: reason}) snapshot."""
        with self._lock:
            return (not self._reasons, dict(self._reasons))
