"""Telemetry: structured event log, metrics registry, span timers.

The central observability layer the reference lacks (its only signal
is a wall-clock round print, cxxnet_main.cpp:376-387). Three pieces:

- a process-wide **metrics registry** (`counter` / `gauge` /
  `histogram` with p50/p99). Rare-event counts (fault/retry/rollback,
  checkpoint) accumulate regardless of sinks and are always queryable
  in-process; per-step/per-batch instruments (train.*, io.prefetch.*)
  are recorded only while a sink is armed - their timing costs a
  device sync the disabled path must not pay;
- **span timers**: ``with span("train.step"): ...`` observes the
  duration into a histogram of the same name and, when an event sink
  is configured, emits a ``span`` event. Spans nest - the recorded
  name is the "/"-joined path of the enclosing spans on this thread.
  With no sink configured ``span()`` returns a shared no-op context,
  so the disabled path costs one attribute check;
- a **central logger** with JSONL event/metric sinks (``log_file=`` /
  ``metrics_file=`` config keys, ``log_format=json|text``, periodic
  ``heartbeat_secs=`` snapshots). ``stdout()`` / ``stderr()`` write
  the EXACT text the pre-telemetry code printed - byte-for-byte stderr
  parity when no sink is configured is a hard contract (tests pin it)
  - while mirroring a structured event when a sink is armed.

Every record carries {ts, host, pid, proc, device} tags so
multi-process runs produce mergeable streams. Config plumbing lives in
main.py; the full schema is docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import contextlib
import os
import socket
import sys
import threading
import time
from typing import Dict, Optional

from cxxnet_tpu.telemetry.registry import (
    Counter, Gauge, Histogram, MetricsRegistry)
from cxxnet_tpu.telemetry.sink import LineSink, read_jsonl

__all__ = [
    "Telemetry", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "LineSink", "read_jsonl", "get", "configure", "close", "enabled",
    "metrics_enabled", "counter", "gauge", "histogram", "inc",
    "set_gauge", "observe", "span", "event", "emit_metrics", "stdout",
    "stderr", "set_tags", "reset_for_tests",
]


class _NullSpan:
    """Reusable no-op context manager: the disabled span path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Timed span: pushes its name on the thread's span stack so
    nested spans record "outer/inner" paths."""

    __slots__ = ("_tel", "_name", "_fields", "_path", "_t0")

    def __init__(self, tel: "Telemetry", name: str, fields: Dict):
        self._tel = tel
        self._name = name
        self._fields = fields
        self._path = name
        self._t0 = 0.0

    def __enter__(self):
        stack = self._tel._span_stack()
        self._path = ("/".join(stack) + "/" + self._name if stack
                      else self._name)
        stack.append(self._name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        secs = time.perf_counter() - self._t0
        stack = self._tel._span_stack()
        if stack:
            stack.pop()
        self._tel.observe(self._path, secs)
        self._tel.event("span", name=self._path, secs=secs,
                        **self._fields)
        return False


class Telemetry:
    """One logger + registry + sinks bundle. A process normally uses
    the module-level singleton (`telemetry.get()`); separate instances
    exist for tests."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self._log: Optional[LineSink] = None
        self._metrics: Optional[LineSink] = None
        self.heartbeat_secs = 0.0
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._local = threading.local()
        self._tags: Dict[str, object] = {
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "proc": 0,
        }

    # -- configuration -----------------------------------------------------
    def configure(self, log_file: str = "", metrics_file: str = "",
                  log_format: str = "json", heartbeat_secs: float = 0.0,
                  tags: Optional[Dict[str, object]] = None) -> None:
        """(Re)arm the sinks. Idempotent and terminal for the previous
        configuration: earlier sinks are flushed and closed first, so a
        CLI process that runs several tasks back-to-back (the test
        suite does) never leaks file handles or cross-writes streams.
        Empty paths disarm - configure() with no arguments returns the
        process to the zero-overhead disabled state."""
        self._stop_heartbeat()
        if self._log is not None:
            self._log.close()
        if self._metrics is not None:
            self._metrics.close()
        self._log = LineSink(log_file, log_format) if log_file else None
        self._metrics = (LineSink(metrics_file, "json")
                         if metrics_file else None)
        if tags:
            self._tags.update(tags)
        self.heartbeat_secs = float(heartbeat_secs or 0.0)
        if self.heartbeat_secs > 0 and (self._log or self._metrics):
            self._start_heartbeat()

    def set_tags(self, **tags) -> None:
        """Late tag refinement (e.g. `proc` once jax.process_index()
        is known after distributed init)."""
        self._tags.update(tags)

    def close(self) -> None:
        """Flush + close sinks and stop the heartbeat; the registry
        keeps accumulating (counters outlive any one sink's life)."""
        self._stop_heartbeat()
        if self._log is not None:
            self._log.close()
            self._log = None
        if self._metrics is not None:
            self._metrics.close()
            self._metrics = None

    @property
    def enabled(self) -> bool:
        """True when ANY sink is armed (events or metrics stream)."""
        return self._log is not None or self._metrics is not None

    @property
    def metrics_enabled(self) -> bool:
        return self._metrics is not None

    # -- registry sugar ----------------------------------------------------
    def counter(self, name: str) -> Counter:
        return self.registry.counter(name)

    def gauge(self, name: str) -> Gauge:
        return self.registry.gauge(name)

    def histogram(self, name: str) -> Histogram:
        return self.registry.histogram(name)

    def inc(self, name: str, n: int = 1) -> None:
        self.registry.counter(name).inc(n)

    def set_gauge(self, name: str, v: float) -> None:
        self.registry.gauge(name).set(v)

    def observe(self, name: str, v: float) -> None:
        self.registry.histogram(name).observe(v)

    # -- spans -------------------------------------------------------------
    def _span_stack(self):
        stack = getattr(self._local, "spans", None)
        if stack is None:
            stack = self._local.spans = []
        return stack

    def span(self, name: str, **fields):
        """Timed context manager; no-op singleton when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, fields)

    # -- events ------------------------------------------------------------
    def _record(self, kind: str, fields: Dict) -> Dict[str, object]:
        # graftlint: disable=GL004 `ts` is a wall-clock TIMESTAMP by design - multi-host streams merge by absolute time (docs/OBSERVABILITY.md)
        rec: Dict[str, object] = {"ts": time.time(), "kind": kind}
        rec.update(self._tags)
        rec.update(fields)
        return rec

    def event(self, kind: str, **fields) -> None:
        """Emit a structured event to the event log (no-op unarmed)."""
        log = self._log
        if log is not None:
            log.write(self._record(kind, fields))

    def emit_metrics(self, kind: str = "metrics", **fields) -> None:
        """Emit a full registry snapshot record to the metrics stream
        (no-op when metrics_file is unarmed). Extra fields ride on the
        record - per-round emitters attach round/step/throughput."""
        sink = self._metrics
        if sink is not None:
            fields = dict(fields)
            fields["metrics"] = self.registry.snapshot()
            sink.write(self._record(kind, fields))

    def flush(self) -> None:
        if self._log is not None:
            self._log.flush()
        if self._metrics is not None:
            self._metrics.flush()

    # -- the central logger ------------------------------------------------
    def stdout(self, text: str) -> None:
        """Exactly `print(text)` - THE sanctioned stdout path for
        cxxnet_tpu outside tools/ (CI lints bare print() away). When an
        event sink is armed the line is mirrored as a `log` event."""
        print(text)  # noqa: T201 - the one sanctioned print
        log = self._log
        if log is not None:
            log.write(self._record("log", {"stream": "stdout",
                                           "text": text}))

    def stderr(self, text: str, event_kind: str = "", **fields) -> None:
        """Write `text` to sys.stderr byte-for-byte (stderr parity with
        the pre-telemetry CLI is a pinned contract), mirroring a
        structured event when a sink is armed: `event_kind` + fields if
        given, else a plain `log` record."""
        sys.stderr.write(text)
        log = self._log
        if log is not None:
            if event_kind:
                log.write(self._record(event_kind, fields))
            else:
                log.write(self._record("log", {"stream": "stderr",
                                               "text": text}))

    # -- heartbeat ---------------------------------------------------------
    def _start_heartbeat(self) -> None:
        # the thread binds ITS stop event + interval at spawn: a thread
        # that outlives _stop_heartbeat's bounded join (blocked on a
        # slow disk) must see its own, already-set event when it wakes
        # - re-reading self._hb_stop would pick up the NEXT config's
        # fresh event and loop forever as a duplicate-emitting zombie
        stop = self._hb_stop = threading.Event()
        interval = self.heartbeat_secs

        def run():
            while not stop.wait(interval):
                with contextlib.suppress(Exception):
                    # a dying heartbeat must never take training down
                    self.emit_metrics(kind="heartbeat")
                    self.event("heartbeat")
                    self.flush()

        self._hb_thread = threading.Thread(
            target=run, name="telemetry-heartbeat", daemon=True)
        self._hb_thread.start()

    def _stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=2.0)
        self._hb_thread = None


# ---------------------------------------------------------------------------
# process-wide singleton + module-level convenience API (the registry is
# process state, like utils/fault's registry)
# ---------------------------------------------------------------------------
_TEL = Telemetry()


def get() -> Telemetry:
    return _TEL


def configure(**kwargs) -> None:
    _TEL.configure(**kwargs)


def close() -> None:
    _TEL.close()


def enabled() -> bool:
    return _TEL.enabled


def metrics_enabled() -> bool:
    return _TEL.metrics_enabled


def counter(name: str) -> Counter:
    return _TEL.counter(name)


def gauge(name: str) -> Gauge:
    return _TEL.gauge(name)


def histogram(name: str) -> Histogram:
    return _TEL.histogram(name)


def inc(name: str, n: int = 1) -> None:
    _TEL.inc(name, n)


def set_gauge(name: str, v: float) -> None:
    _TEL.set_gauge(name, v)


def observe(name: str, v: float) -> None:
    _TEL.observe(name, v)


def span(name: str, **fields):
    return _TEL.span(name, **fields)


def event(kind: str, **fields) -> None:
    _TEL.event(kind, **fields)


def emit_metrics(kind: str = "metrics", **fields) -> None:
    _TEL.emit_metrics(kind, **fields)


def stdout(text: str) -> None:
    _TEL.stdout(text)


def stderr(text: str, event_kind: str = "", **fields) -> None:
    _TEL.stderr(text, event_kind, **fields)


def set_tags(**tags) -> None:
    _TEL.set_tags(**tags)


def reset_for_tests() -> None:
    """Close sinks, wipe the registry, and restore default tags -
    test isolation only (configure()/set_tags mutate the process-wide
    tag dict, which must not leak across tests)."""
    _TEL.close()
    _TEL.registry.reset()
    _TEL._tags = {"host": socket.gethostname(), "pid": os.getpid(),
                  "proc": 0}
