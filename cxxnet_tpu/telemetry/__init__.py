"""Telemetry: structured event log, metrics registry, span timers.

The central observability layer the reference lacks (its only signal
is a wall-clock round print, cxxnet_main.cpp:376-387). Three pieces:

- a process-wide **metrics registry** (`counter` / `gauge` /
  `histogram` with p50/p99). Rare-event counts (fault/retry/rollback,
  checkpoint) accumulate regardless of sinks and are always queryable
  in-process; per-step/per-batch instruments (train.*, io.prefetch.*)
  are recorded only while a sink is armed - their timing costs a
  device sync the disabled path must not pay;
- **span timers**: ``with span("train.step"): ...`` observes the
  duration into a histogram of the same name and, when an event sink
  is configured, emits a ``span`` event. Spans nest - the recorded
  name is the "/"-joined path of the enclosing spans on this thread.
  With no sink configured ``span()`` returns a shared no-op context,
  so the disabled path costs one attribute check;
- a **central logger** with JSONL event/metric sinks (``log_file=`` /
  ``metrics_file=`` config keys, ``log_format=json|text``, periodic
  ``heartbeat_secs=`` snapshots). ``stdout()`` / ``stderr()`` write
  the EXACT text the pre-telemetry code printed - byte-for-byte stderr
  parity when no sink is configured is a hard contract (tests pin it)
  - while mirroring a structured event when a sink is armed.

Every record carries {ts, host, pid, proc, device} tags so
multi-process runs produce mergeable streams. Config plumbing lives in
main.py; the full schema is docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import collections
import contextlib
import os
import socket
import sys
import threading
import time
from typing import Dict, Optional, Tuple

from cxxnet_tpu.telemetry.flight import (
    ExecutableRegistry, FlightRecorder)
from cxxnet_tpu.telemetry.health import HealthState
from cxxnet_tpu.telemetry.registry import (
    BucketHistogram, Counter, Gauge, Histogram, MetricsRegistry)
from cxxnet_tpu.telemetry.sink import LineSink, read_jsonl

__all__ = [
    "Telemetry", "Counter", "Gauge", "Histogram", "BucketHistogram",
    "MetricsRegistry", "FlightRecorder", "ExecutableRegistry",
    "HealthState", "LineSink", "read_jsonl", "get", "configure",
    "close", "enabled", "metrics_enabled", "counter", "gauge",
    "histogram", "inc", "set_gauge", "observe", "span", "event",
    "emit_metrics", "stdout", "stderr", "set_tags", "beacon",
    "beacons", "recent_spans", "flight", "executables",
    "arm_observability", "disarm_observability", "health",
    "reset_for_tests",
]

# completed spans kept for the watchdog's stall dump ("what ran last")
RECENT_SPANS = 64


class _NullSpan:
    """Reusable no-op context manager: the disabled span path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Timed span: pushes its name on the thread's span stack so
    nested spans record "outer/inner" paths."""

    __slots__ = ("_tel", "_name", "_fields", "_path", "_t0")

    def __init__(self, tel: "Telemetry", name: str, fields: Dict):
        self._tel = tel
        self._name = name
        self._fields = fields
        self._path = name
        self._t0 = 0.0

    def __enter__(self):
        stack = self._tel._span_stack()
        self._path = ("/".join(stack) + "/" + self._name if stack
                      else self._name)
        stack.append(self._name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        secs = time.perf_counter() - self._t0
        stack = self._tel._span_stack()
        if stack:
            stack.pop()
        self._tel.observe(self._path, secs)
        # event() also records the span into the recent-span ring
        self._tel.event("span", name=self._path, secs=secs,
                        **self._fields)
        return False


class Telemetry:
    """One logger + registry + sinks bundle. A process normally uses
    the module-level singleton (`telemetry.get()`); separate instances
    exist for tests."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self.health = HealthState()
        self._log: Optional[LineSink] = None
        self._metrics: Optional[LineSink] = None
        self.heartbeat_secs = 0.0
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # test hook: a fake-clock wait fn (signature of Event.wait)
        # injected by the heartbeat-hardening tests; None = real clock
        self._hb_waiter = None
        self._emit_lock = threading.Lock()
        # `final` snapshot emitted: the heartbeat must never write a
        # trailing snapshot after it (the stream's terminal record);
        # the flag is checked-and-written under _emit_lock
        # guarded-by: self._emit_lock
        self._finalized = False
        self._local = threading.local()
        # progress beacons (watchdog.py / absence alert rules):
        # name -> (count, monotonic ts of the newest mark); locked -
        # serve replicas mark the same beacon concurrently and an
        # unlocked read-modify-write would drop counts
        self._beacon_lock = threading.Lock()
        # guarded-by: self._beacon_lock
        self._beacons: Dict[str, Tuple[int, float]] = {}
        self._recent_spans: collections.deque = collections.deque(
            maxlen=RECENT_SPANS)
        # live observability plane handles (armed via
        # arm_observability; None = the zero-overhead default)
        self._http = None
        self._alerts = None
        self._watchdog = None
        # dispatch flight recorder + executable registry (flight.py):
        # the recorder arms with the plane (any sink / http / watchdog
        # / alerts, or flight_recorder=1) - unarmed dispatch sites pay
        # one attribute check; the registry registers unconditionally
        # (once per compiled program shape, no output)
        self.flight = FlightRecorder()
        self.executables = ExecutableRegistry()
        self._tags: Dict[str, object] = {
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "proc": 0,
        }

    # -- configuration -----------------------------------------------------
    def configure(self, log_file: str = "", metrics_file: str = "",
                  log_format: str = "json", heartbeat_secs: float = 0.0,
                  tags: Optional[Dict[str, object]] = None) -> None:
        """(Re)arm the sinks. Idempotent and terminal for the previous
        configuration: earlier sinks are flushed and closed first, so a
        CLI process that runs several tasks back-to-back (the test
        suite does) never leaks file handles or cross-writes streams.
        Empty paths disarm - configure() with no arguments returns the
        process to the zero-overhead disabled state."""
        self._stop_heartbeat()
        if self._log is not None:
            self._log.close()
        if self._metrics is not None:
            self._metrics.close()
        self._log = LineSink(log_file, log_format) if log_file else None
        self._metrics = (LineSink(metrics_file, "json")
                         if metrics_file else None)
        if tags:
            self._tags.update(tags)
        with self._emit_lock:
            # under the lock: a heartbeat that outlived its bounded
            # join (blocked on a slow disk) could still be inside
            # emit_metrics when the next run re-arms
            self._finalized = False
        self.heartbeat_secs = float(heartbeat_secs or 0.0)
        if self.heartbeat_secs > 0 and (self._log or self._metrics):
            self._start_heartbeat()
        self._refresh_flight()

    def _refresh_flight(self) -> None:
        """Re-derive the flight recorder's armed state: any consumer
        of its ring (a sink to mirror trace events into, the /varz
        and /executables endpoints, the watchdog's stall dump, an
        alert engine's forensics) arms it; an explicit
        ``flight_recorder = 1`` keeps it armed with everything else
        off. With no consumer the recorder stays disabled and every
        dispatch site pays one attribute check - the byte-parity
        contract's zero-overhead path."""
        self.flight.enabled = bool(
            self._log is not None or self._metrics is not None
            or self._http is not None or self._watchdog is not None
            or self._alerts is not None or self.flight.explicit)

    def set_tags(self, **tags) -> None:
        """Late tag refinement (e.g. `proc` once jax.process_index()
        is known after distributed init)."""
        self._tags.update(tags)

    def tags(self) -> Dict[str, object]:
        return dict(self._tags)

    # -- progress beacons --------------------------------------------------
    def beacon(self, name: str, n: int = 1) -> None:
        """Mark progress (one dict store + a monotonic read - no
        device sync, safe on every step). The watchdog and absence
        alert rules judge liveness by beacon age; the instrumented
        sites are train.step / eval.step / serve.batch /
        checkpoint.save."""
        with self._beacon_lock:
            prev = self._beacons.get(name)
            self._beacons[name] = (
                (prev[0] if prev else 0) + n, time.monotonic())

    def beacons(self) -> Dict[str, Tuple[int, float]]:
        """{name: (count, monotonic ts of newest mark)} snapshot."""
        with self._beacon_lock:
            return dict(self._beacons)

    def recent_spans(self):
        """Newest-last list of recently completed spans
        ({ts, name, secs}) - the watchdog's "what ran last" evidence."""
        return list(self._recent_spans)

    # -- live observability plane ------------------------------------------
    def arm_observability(self, metrics_port: Optional[int] = None,
                          alert_rules: str = "", alert_cmd: str = "",
                          watchdog_secs: float = 0.0,
                          metrics_host: str = ""):
        """Bring up the live plane: the hang watchdog
        (``watchdog_secs>0``), the alert engine (``alert_rules`` file,
        optional ``alert_cmd`` shell hook) and the HTTP exposition
        server (``metrics_port`` - 0 binds an ephemeral port; None =
        no server). With every knob off this returns without
        importing anything: no thread, no socket, no import-time side
        effects - the byte-parity contract's disabled path.

        Returns the ObservabilityServer (or None), whose ``.port`` is
        the resolved bind."""
        if (metrics_port is None and not alert_rules
                and not (watchdog_secs and watchdog_secs > 0)):
            return None
        self.disarm_observability()
        if watchdog_secs and watchdog_secs > 0:
            from cxxnet_tpu.telemetry.watchdog import Watchdog
            self._watchdog = Watchdog(self, float(watchdog_secs))
            self._watchdog.start()
        if alert_rules:
            from cxxnet_tpu.telemetry.alerts import (
                AlertEngine, load_rules)
            self._alerts = AlertEngine(self, load_rules(alert_rules),
                                       alert_cmd=alert_cmd)
            self._alerts.start()
        if metrics_port is not None:
            from cxxnet_tpu.telemetry.http import ObservabilityServer
            # default bind is all interfaces (cross-host scraping is
            # the point); metrics_host=127.0.0.1 restricts to
            # loopback - the endpoints are unauthenticated, see the
            # exposure note in docs/OBSERVABILITY.md
            self._http = ObservabilityServer(
                self, int(metrics_port),
                host=metrics_host or "0.0.0.0")
            self._http.start()
            self.event("observability", op="http_start",
                       port=self._http.port, host=self._http.host)
        self._refresh_flight()
        return self._http

    def disarm_observability(self) -> None:
        """Stop watchdog/alerts/http (reverse arm order: detectors
        first so a final scrape cannot observe a half-closed plane).
        Idempotent; firing detectors clear their health sources."""
        if self._watchdog is not None:
            self._watchdog.close()
            self._watchdog = None
        if self._alerts is not None:
            self._alerts.close()
            self._alerts = None
        if self._http is not None:
            self._http.close()
            self._http = None
        self._refresh_flight()

    def close(self) -> None:
        """Tear down the observability plane (watchdog/alerts/http),
        flush + close sinks and stop the heartbeat; the registry keeps
        accumulating (counters outlive any one sink's life)."""
        self.disarm_observability()
        self._stop_heartbeat()
        if self._log is not None:
            self._log.close()
            self._log = None
        if self._metrics is not None:
            self._metrics.close()
            self._metrics = None
        self._refresh_flight()

    @property
    def enabled(self) -> bool:
        """True when a consumer of the FULL instrumentation is armed:
        a JSONL sink, or the /metrics HTTP server (a scraper wants the
        per-step histograms - arming metrics_port opts into the same
        per-step device-sync cost a metrics_file does;
        telemetry_steps=0 still opts back out). Deliberately NOT the
        watchdog or alert engine alone: forensics and counter/beacon
        rules must not silently serialize async dispatch with
        per-step syncs - the diagnostic would perturb the thing it
        diagnoses. Rules over train.* step histograms need a sink or
        metrics_port armed too (docs/OBSERVABILITY.md)."""
        return (self._log is not None or self._metrics is not None
                or self._http is not None)

    @property
    def metrics_enabled(self) -> bool:
        return self._metrics is not None

    # -- registry sugar ----------------------------------------------------
    def counter(self, name: str) -> Counter:
        return self.registry.counter(name)

    def gauge(self, name: str) -> Gauge:
        return self.registry.gauge(name)

    def histogram(self, name: str) -> Histogram:
        return self.registry.histogram(name)

    def inc(self, name: str, n: int = 1) -> None:
        self.registry.counter(name).inc(n)

    def set_gauge(self, name: str, v: float) -> None:
        self.registry.gauge(name).set(v)

    def observe(self, name: str, v: float) -> None:
        self.registry.histogram(name).observe(v)

    # -- spans -------------------------------------------------------------
    def _span_stack(self):
        stack = getattr(self._local, "spans", None)
        if stack is None:
            stack = self._local.spans = []
        return stack

    def span(self, name: str, **fields):
        """Timed context manager; no-op singleton when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, fields)

    # -- events ------------------------------------------------------------
    def _record(self, kind: str, fields: Dict) -> Dict[str, object]:
        # graftlint: disable=GL004 `ts` is a wall-clock TIMESTAMP by design - multi-host streams merge by absolute time (docs/OBSERVABILITY.md)
        rec: Dict[str, object] = {"ts": time.time(), "kind": kind}
        rec.update(self._tags)
        rec.update(fields)
        return rec

    def event(self, kind: str, **fields) -> None:
        """Emit a structured event to the event log (no-op unarmed).
        ``span`` events also feed the recent-span ring: the trainer
        emits its per-step/per-chunk span records directly as events
        (not via span() contexts), and the watchdog's stall dump
        wants exactly those as its "what ran last" evidence."""
        if kind == "span" and "name" in fields:
            # graftlint: disable=GL004 ring keeps wall TIMESTAMPS like the streams
            ts = time.time()
            self._recent_spans.append(
                {"ts": ts, "name": fields["name"],
                 "secs": round(float(fields.get("secs") or 0.0), 6)})
        log = self._log
        if log is not None:
            log.write(self._record(kind, fields))

    def emit_metrics(self, kind: str = "metrics", **fields) -> None:
        """Emit a full registry snapshot record to the metrics stream
        (no-op when metrics_file is unarmed). Extra fields ride on the
        record - per-round emitters attach round/step/throughput.
        ``kind="final"`` marks the stream terminal: a heartbeat racing
        the shutdown must not append a trailing snapshot after it."""
        sink = self._metrics
        if sink is None:
            return
        # check-and-write under one lock: a heartbeat that passed an
        # unlocked check could be descheduled, lose the race to the
        # `final` write, and still append after the terminal record
        with self._emit_lock:
            if kind == "final":
                self._finalized = True
            elif kind == "heartbeat" and self._finalized:
                return
            fields = dict(fields)
            fields["metrics"] = self.registry.snapshot()
            sink.write(self._record(kind, fields))

    def snapshot_record(self, kind: str = "varz") -> Dict[str, object]:
        """One metrics-stream-schema record ({ts, tags..., kind,
        metrics}) without writing it anywhere - the `/varz` body, so
        live scrapes and file tails parse identically."""
        return self._record(kind, {"metrics": self.registry.snapshot()})

    def flush(self) -> None:
        if self._log is not None:
            self._log.flush()
        if self._metrics is not None:
            self._metrics.flush()

    # -- the central logger ------------------------------------------------
    def stdout(self, text: str) -> None:
        """Exactly `print(text)` - THE sanctioned stdout path for
        cxxnet_tpu outside tools/ (CI lints bare print() away). When an
        event sink is armed the line is mirrored as a `log` event."""
        print(text)  # noqa: T201 - the one sanctioned print
        log = self._log
        if log is not None:
            log.write(self._record("log", {"stream": "stdout",
                                           "text": text}))

    def stderr(self, text: str, event_kind: str = "", **fields) -> None:
        """Write `text` to sys.stderr byte-for-byte (stderr parity with
        the pre-telemetry CLI is a pinned contract), mirroring a
        structured event when a sink is armed: `event_kind` + fields if
        given, else a plain `log` record."""
        sys.stderr.write(text)
        log = self._log
        if log is not None:
            if event_kind:
                log.write(self._record(event_kind, fields))
            else:
                log.write(self._record("log", {"stream": "stderr",
                                               "text": text}))

    # -- heartbeat ---------------------------------------------------------
    def _start_heartbeat(self) -> None:
        # the thread binds ITS stop event + interval at spawn: a thread
        # that outlives _stop_heartbeat's bounded join (blocked on a
        # slow disk) must see its own, already-set event when it wakes
        # - re-reading self._hb_stop would pick up the NEXT config's
        # fresh event and loop forever as a duplicate-emitting zombie
        stop = self._hb_stop = threading.Event()
        interval = self.heartbeat_secs
        # test hook: a fake clock replaces the Event.wait sleep so the
        # hardening contract (prompt close(), no post-`final` beat) is
        # pinned without real time
        waiter = self._hb_waiter or stop.wait

        def run():
            while not waiter(interval):
                # re-check AFTER waking: a tick that raced close() or
                # the terminal `final` snapshot must emit nothing -
                # close() returns with the stream already terminal
                if stop.is_set() or self._finalized:
                    return
                with contextlib.suppress(Exception):
                    # a dying heartbeat must never take training down
                    self.emit_metrics(kind="heartbeat")
                    self.event("heartbeat")
                    self.flush()

        self._hb_thread = threading.Thread(
            target=run, name="telemetry-heartbeat", daemon=True)
        self._hb_thread.start()

    def _stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=2.0)
        self._hb_thread = None


# ---------------------------------------------------------------------------
# process-wide singleton + module-level convenience API (the registry is
# process state, like utils/fault's registry)
# ---------------------------------------------------------------------------
_TEL = Telemetry()


def get() -> Telemetry:
    return _TEL


def configure(**kwargs) -> None:
    _TEL.configure(**kwargs)


def close() -> None:
    _TEL.close()


def enabled() -> bool:
    return _TEL.enabled


def metrics_enabled() -> bool:
    return _TEL.metrics_enabled


def counter(name: str) -> Counter:
    return _TEL.counter(name)


def gauge(name: str) -> Gauge:
    return _TEL.gauge(name)


def histogram(name: str) -> Histogram:
    return _TEL.histogram(name)


def inc(name: str, n: int = 1) -> None:
    _TEL.inc(name, n)


def set_gauge(name: str, v: float) -> None:
    _TEL.set_gauge(name, v)


def observe(name: str, v: float) -> None:
    _TEL.observe(name, v)


def span(name: str, **fields):
    return _TEL.span(name, **fields)


def event(kind: str, **fields) -> None:
    _TEL.event(kind, **fields)


def emit_metrics(kind: str = "metrics", **fields) -> None:
    _TEL.emit_metrics(kind, **fields)


def stdout(text: str) -> None:
    _TEL.stdout(text)


def stderr(text: str, event_kind: str = "", **fields) -> None:
    _TEL.stderr(text, event_kind, **fields)


def set_tags(**tags) -> None:
    _TEL.set_tags(**tags)


def beacon(name: str, n: int = 1) -> None:
    _TEL.beacon(name, n)


def beacons() -> Dict[str, Tuple[int, float]]:
    return _TEL.beacons()


def recent_spans():
    return _TEL.recent_spans()


def flight() -> FlightRecorder:
    return _TEL.flight


def executables() -> ExecutableRegistry:
    return _TEL.executables


def arm_observability(**kwargs):
    return _TEL.arm_observability(**kwargs)


def disarm_observability() -> None:
    _TEL.disarm_observability()


def health() -> HealthState:
    return _TEL.health


def reset_for_tests() -> None:
    """Close sinks + the observability plane, wipe the registry,
    beacons, span ring and health state, and restore default tags -
    test isolation only (configure()/set_tags mutate the process-wide
    tag dict, which must not leak across tests)."""
    _TEL.close()
    _TEL.registry.reset()
    _TEL.health.reset()
    with _TEL._beacon_lock:
        _TEL._beacons = {}
    _TEL._recent_spans.clear()
    _TEL.flight.reset()
    _TEL.executables.reset()
    with _TEL._emit_lock:
        _TEL._finalized = False
    _TEL._hb_waiter = None
    _TEL._tags = {"host": socket.gethostname(), "pid": os.getpid(),
                  "proc": 0}
