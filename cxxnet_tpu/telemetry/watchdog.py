"""Hang watchdog: stack-dump forensics when progress stops.

The recent TPU bench rounds lost their chip numbers to a backend hang
with ZERO forensics - the process sat silent until the operator killed
it. This module is the missing black box: instrumented sites publish
cheap *progress beacons* (``telemetry.beacon("train.step")`` - a dict
store + monotonic read, no device sync), and a daemon thread watches
the newest beacon's age. When nothing has progressed for
``watchdog_secs``:

1. every Python thread's stack is captured (``sys._current_frames``)
   together with the last N completed spans and the flight recorder's
   dispatch tail (telemetry/flight.py) - exactly where the hang is,
   what ran last, and WHICH executable (fingerprint, bucket, request
   trace id) is still in flight;
2. the dump goes to **stderr** and, as a structured ``watchdog``
   event (op=``stall_dump``, with the stacks and spans as fields), to
   the event stream - so a post-mortem needs only the JSONL;
3. ``/healthz`` flips to 503 (health.py source "watchdog") until a
   beacon moves again, which emits op=``recovered`` and clears it.

One dump per stall episode (a 10-minute hang is one incident, not 600
dumps). Before the FIRST beacon the threshold is ``startup_secs``
(default 60): model build + jit compilation legitimately runs minutes
with no step progress, and a watchdog that cries during warmup would
be disarmed by every operator on day one.

Armed only via ``watchdog_secs=`` (or programmatically); never
imported otherwise.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import List, Optional

# threshold applied until the first beacon is seen (compile/init grace)
STARTUP_SECS = 60.0
# spans included in a stall dump
DUMP_SPANS = 20


def dump_stacks() -> str:
    """Every live Python thread's current stack, named."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {names.get(ident, '?')} "
                   f"(ident {ident}) ---")
        out.extend(line.rstrip("\n")
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


class Watchdog:
    """Progress monitor over the telemetry beacon table."""

    def __init__(self, tel, stall_secs: float,
                 poll_secs: Optional[float] = None,
                 startup_secs: float = STARTUP_SECS,
                 dump_spans: int = DUMP_SPANS) -> None:
        if stall_secs <= 0:
            raise ValueError("watchdog_secs must be > 0")
        self.tel = tel
        self.stall_secs = float(stall_secs)
        # poll a few times per threshold so a stall is seen promptly
        # without a hot loop; clamped for tiny test thresholds
        self.poll_secs = (float(poll_secs) if poll_secs is not None
                          else min(max(stall_secs / 4.0, 0.05), 1.0))
        self.startup_secs = max(float(startup_secs), self.stall_secs)
        self.dump_spans = int(dump_spans)
        self.stalled = False
        self._armed_at = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="telemetry-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.stalled:
            # a watchdog that dies while firing must not leave a
            # permanent 503 behind (the next run's server would
            # inherit it in-process)
            self.stalled = False
            self.tel.health.clear("watchdog")

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_secs):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 - forensics never kill training
                pass

    # -- the check ---------------------------------------------------------
    def _progress_age(self, now: float) -> tuple:
        """(seconds since newest beacon, threshold to judge it by)."""
        beacons = self.tel.beacons()
        if not beacons:
            return now - self._armed_at, self.startup_secs
        last = max(ts for _, ts in beacons.values())
        return now - last, self.stall_secs

    def check_now(self, now: Optional[float] = None) -> bool:
        """One evaluation (the thread calls this; tests drive it with
        a fake clock). Returns the stalled state after the check."""
        now = time.monotonic() if now is None else now
        age, threshold = self._progress_age(now)
        if age >= threshold and not self.stalled:
            self.stalled = True
            self._dump(age)
        elif age < threshold and self.stalled:
            self.stalled = False
            self.tel.health.clear("watchdog")
            self.tel.event("watchdog", op="recovered",
                           stalled_secs=round(age, 3))
        return self.stalled

    def _dump(self, age: float) -> None:
        self.tel.inc("watchdog.stalls")
        stacks = dump_stacks()
        spans = self.tel.recent_spans()[-self.dump_spans:]
        span_lines = "".join(
            f"  {s['secs']:.4f}s {s['name']}\n" for s in spans)
        # flight-recorder tail (telemetry/flight.py): the stall dump's
        # "which executable" half - in-flight entries name the exact
        # wedged dispatch (fingerprint, bucket, request trace id) the
        # thread stacks alone cannot. Same one-dump-per-episode rule:
        # this runs only on the stalled-edge transition above.
        flights = self.tel.flight.tail(self.dump_spans)
        text = (
            f"watchdog: no progress for {age:.1f}s "
            f"(threshold {self.stall_secs:g}s); dumping "
            f"{stacks.count('--- thread')} thread stacks\n"
            f"{stacks}"
            f"last {len(spans)} spans (newest last):\n{span_lines}"
            f"last {len(flights)} dispatches (flight recorder, "
            f"newest last):\n"
            f"{self.tel.flight.format_tail(rows=flights)}")
        # stderr first (the operator's console), then the structured
        # event - both BEFORE the absence alert fires on the same
        # stall, since the alert engine judges beacon age with a
        # threshold that should sit above watchdog_secs
        self.tel.stderr(text, event_kind="watchdog", op="stall_dump",
                        stalled_secs=round(age, 3), stacks=stacks,
                        spans=spans, flights=flights)
        self.tel.health.set_unhealthy(
            "watchdog",
            f"no progress for {age:.1f}s "
            f"(watchdog_secs={self.stall_secs:g})")
