"""Trainer core: NetConfig DAG parsing, functional network, jitted trainer."""

from cxxnet_tpu.nnet.net_config import LayerInfo, NetConfig
from cxxnet_tpu.nnet.network import Network, param_key

__all__ = ["LayerInfo", "NetConfig", "Network", "param_key"]
