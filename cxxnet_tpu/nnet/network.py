"""Network: NetConfig DAG -> functional forward + loss.

Replaces the reference's NeuralNet (neural_net-inl.hpp:23-297). The
in-place node/gradient machinery disappears: forward is a pure function
from (params, inputs, rng) to node values, connections run in declaration
order exactly like the reference (Forward :107-132), and the training loss
is differentiated by jax.grad - which reproduces the reference's reverse
declaration-order Backprop including gradient summing at forks.

Weight sharing (kSharedLayer): a shared connection reuses the primary
layer's entry in the params pytree, so autodiff automatically sums the
gradient contributions of every connection that uses it - the behavior the
reference gets from accumulating `gwmat_ +=` across connections.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from cxxnet_tpu.layers import create_layer
from cxxnet_tpu.layers.base import Layer, Shape
from cxxnet_tpu.layers.common import SplitLayer
from cxxnet_tpu.layers.loss import LossLayer
from cxxnet_tpu.nnet.net_config import NetConfig


def param_key(cfg: NetConfig, layer_index: int) -> str:
    """Stable pytree key for a layer's params: its name, else its index."""
    info = cfg.layers[layer_index]
    return info.name if info.name else f"layer_{layer_index}"


class Network:
    """Holds layer objects + inferred node shapes; provides pure forward."""

    def __init__(self, cfg: NetConfig, batch_size: int):
        self.cfg = cfg
        self.batch_size = batch_size
        self.layer_objs: List[Layer] = []
        self.node_shapes: List[Optional[Shape]] = [None] * cfg.num_nodes
        # per-layer compute-dtype plan stamped by the autocast graph
        # pass (nnet/passes.py); None = no plan, historic behavior
        # (the trainer casts wholesale to its compute dtype)
        self.dtype_plan: Optional[Dict[int, jnp.dtype]] = None

        # node 0 is the data input; in_1..in_k are extra data
        c, y, x = cfg.input_shape
        if c * y * x == 0:
            raise ValueError("input_shape must be set")
        self.node_shapes[0] = (batch_size, c, y, x)
        for i in range(cfg.extra_data_num):
            ec, ey, ex = cfg.extra_shape[3 * i: 3 * i + 3]
            self.node_shapes[i + 1] = (batch_size, ec, ey, ex)

        # build layer objects and run shape inference in declaration order
        for idx, info in enumerate(cfg.layers):
            if info.is_shared:
                layer = self.layer_objs[info.primary_layer_index]
            else:
                layer = create_layer(info.type_name, info.name)
                for k, v in cfg.defcfg:
                    layer.set_param(k, v)
                for k, v in cfg.layercfg[idx]:
                    layer.set_param(k, v)
            self.layer_objs.append(layer)

            if isinstance(layer, SplitLayer):
                layer.num_out = len(info.nindex_out)
            if isinstance(layer, LossLayer):
                if info.nindex_in != info.nindex_out:
                    raise ValueError(
                        f"{info.type_name}: loss layer must be a self-loop")
                if layer.target not in cfg.label_name_map:
                    raise ValueError(
                        f"LossLayer: unknown target={layer.target}")

            in_shapes = []
            for j in info.nindex_in:
                if self.node_shapes[j] is None:
                    raise ValueError(
                        f"node {cfg.node_names[j]} used before it is "
                        "produced")
                in_shapes.append(self.node_shapes[j])
            out_shapes = layer.infer_shapes(list(in_shapes))
            if len(out_shapes) != len(info.nindex_out):
                raise ValueError(
                    f"{info.type_name}: produced {len(out_shapes)} outputs "
                    f"for {len(info.nindex_out)} output nodes")
            for j, s in zip(info.nindex_out, out_shapes):
                self.node_shapes[j] = s

        self.loss_indices = [
            i for i, l in enumerate(self.layer_objs)
            if isinstance(l, LossLayer) and not cfg.layers[i].is_shared]

    # ------------------------------------------------------------------
    def init_params(self, key: jax.Array) -> Dict[str, Dict[str, jax.Array]]:
        params: Dict[str, Dict[str, jax.Array]] = {}
        for idx, info in enumerate(self.cfg.layers):
            if info.is_shared:
                continue
            in_shapes = [self.node_shapes[j] for j in info.nindex_in]
            p = self.layer_objs[idx].init_params(
                jax.random.fold_in(key, idx), list(in_shapes))
            if p:
                params[param_key(self.cfg, idx)] = p
        return params

    def param_tags(self) -> Dict[str, Dict[str, str]]:
        """pytree of updater scoping tags parallel to init_params()."""
        tags: Dict[str, Dict[str, str]] = {}
        for idx, info in enumerate(self.cfg.layers):
            if info.is_shared:
                continue
            t = self.layer_objs[idx].param_tags()
            if t:
                tags[param_key(self.cfg, idx)] = t
        return tags

    # ------------------------------------------------------------------
    def forward(
        self,
        params: Dict[str, Dict[str, jax.Array]],
        inputs: Dict[int, jax.Array],
        *,
        train: bool,
        rng: Optional[jax.Array] = None,
        labels: Optional[Dict[str, jax.Array]] = None,
        mask: Optional[jax.Array] = None,
        taps: Optional[Dict[int, Optional[jax.Array]]] = None,
    ) -> Tuple[List[jax.Array], jax.Array]:
        """Run all connections in declaration order.

        taps: optional {layer_index: None} dict, filled in place with
        each listed layer's (first) INPUT as that layer receives it -
        i.e. BEFORE a self-loop layer overwrites its node. The fold
        calibration (trainer._calibrate_staged) needs the batch_norm
        input, and reading `values[node]` after the forward would see
        the post-BN value for `layer[+0] = batch_norm` self-loops.

        inputs: node index -> array (node 0 data + extra-data nodes).
        labels: label field name -> (b, width) array; required when any
        loss layer runs with train semantics.
        mask: optional (b,) validity mask for padded short batches; the
        per-example losses of padding rows are zeroed (the functional
        replacement of AdjustBatchSize - neural_net-inl.hpp:266-277).

        Returns (node_values, total_loss) where total_loss is the sum over
        loss layers of grad_scale * sum(masked per-example loss). The
        trainer scales by 1/(batch_size*update_period) to match the
        reference's gradient scaling (loss_layer_base-inl.hpp:60-63).
        """
        cfg = self.cfg
        values: List[Optional[jax.Array]] = [None] * cfg.num_nodes
        for j, v in inputs.items():
            values[j] = v
        total_loss = jnp.zeros((), dtype=jnp.float32)

        for idx, info in enumerate(cfg.layers):
            layer = self.layer_objs[idx]
            pkey = param_key(
                cfg, info.primary_layer_index if info.is_shared else idx)
            p = params.get(pkey, {})
            xs = [values[j] for j in info.nindex_in]
            if self.dtype_plan is not None:
                want = self.dtype_plan.get(idx)
                if want is not None:
                    # autocast plan (nnet/passes.py): cast this
                    # layer's inputs + params to its stamped compute
                    # dtype; f32-stamped layers under a bf16 net thus
                    # run their math in f32 (the next bf16 layer
                    # casts back down)
                    xs = [x.astype(want)
                          if jnp.issubdtype(x.dtype, jnp.floating)
                          else x for x in xs]
                    p = {k: (v.astype(want)
                             if jnp.issubdtype(v.dtype, jnp.floating)
                             else v) for k, v in p.items()}
            if taps is not None and idx in taps:
                # post-cast snapshot: exactly what the layer's apply
                # receives (the docstring's tap contract)
                taps[idx] = xs[0]
            layer_rng = (jax.random.fold_in(rng, idx)
                         if rng is not None else None)

            if isinstance(layer, LossLayer):
                x = xs[0]
                b = x.shape[0]
                flat = x.reshape(b, -1)
                if labels is not None:
                    lbl = labels[layer.target]
                    per_ex = layer.per_example_loss(flat, lbl)
                    if mask is not None:
                        per_ex = per_ex * mask
                    total_loss = total_loss + layer.grad_scale * jnp.sum(
                        per_ex)
                out = layer.forward_transform(flat).reshape(x.shape)
                values[info.nindex_out[0]] = out
                continue

            if layer.has_aux:
                # layers with an auxiliary loss term (e.g. the MoE
                # load-balance loss, layers/moe.py) fold it into the
                # same total the loss layers accumulate (contract on
                # Layer.has_aux, layers/base.py)
                outs, aux = layer.apply_with_aux(p, xs, train=train,
                                                 rng=layer_rng, mask=mask)
                if train:
                    total_loss = total_loss + aux
            else:
                outs = layer.apply(p, xs, train=train, rng=layer_rng)
            for j, o in zip(info.nindex_out, outs):
                values[j] = o

        return values, total_loss

    # ------------------------------------------------------------------
    def node_index(self, name: str) -> int:
        """Resolve a node reference: name, or `top[-k]` counting from the
        last node (ExtractFeature syntax, nnet_impl-inl.hpp:200-223)."""
        if name.startswith("top[-") and name.endswith("]"):
            k = int(name[5:-1])
            return self.cfg.num_nodes - k
        if name in self.cfg.node_name_map:
            return self.cfg.node_name_map[name]
        raise KeyError(f"unknown node name {name}")
