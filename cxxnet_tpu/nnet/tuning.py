"""TVM-style persistent per-platform tuning cache (arXiv:1802.04799).

`tools/autotune.py` searches the dispatch/staging/serving knob space
against measured throughput and persists the winners here; the CLI
and wrapper pick them up via `tuning_cache = <path>` (docs/
GRAPH_PASSES.md "Autotuner"). Contract: tuned values are DEFAULTS -
a key the user's config sets explicitly always wins, and a cache
entry for a different platform (or an inapplicable knob) is silently
ignored, so shipping one cache file across a heterogeneous fleet is
safe.

Schema v2 steps the cache up from global knobs to PER-LAYER plans
(the Relay/TVM per-operator decision, arXiv:1810.00952): each
platform entry may carry a `layers` map of per-layer knob choices
(`space_to_depth` per conv, `layer_dtype` feeding the autocast
pass's dtype plan, `layer_quant` pinning the quantize_int8 pass's
per-layer int8-vs-float kernel route) and a `serve_ladder` - explicit
serving bucket
sizes shaped from the observed request-size histogram instead of the
fixed power-of-two set (serve/server.py `ladder_from_histogram`).
v1 caches (global knobs only) load through a one-shot in-memory
migration; anything structurally invalid still raises ConfigError.

File format (JSON, written atomically):

    {"version": 2,
     "platforms": {
       "cpu": {"knobs": {"steps_per_dispatch": 4, "prefetch_stage": 1,
                         "serve_max_batch": 32, "stage_dtype": ""},
               "layers": {"c1": {"space_to_depth": "1"},
                          "fc6": {"layer_dtype": "float32"}},
               "serve_ladder": [2, 6, 16, 32],
               "measured": {"default_ips": ..., "best_ips": ...},
               "device_kind": "...", "date": "YYYY-MM-DD"}}}
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from cxxnet_tpu.utils.config import ConfigError

VERSION = 2

#: every GLOBAL knob the autotuner may set, with the config key it
#: maps to. `stage_dtype` is the staged-input layout axis (f32 vs
#: bf16 H2D bytes - docs/PERFORMANCE.md); `serve_max_batch` is the
#: serving bucket-ladder ceiling (docs/SERVING.md).
TUNABLE_KEYS = ("steps_per_dispatch", "prefetch_stage",
                "serve_max_batch", "stage_dtype")

#: every PER-LAYER knob a v2 plan may carry (values are layer-config
#: stamps applied by the trainer under explicit-keys-win).
#: `layer_quant` (int8|float, the quantize_int8 pass's per-layer
#: kernel-route pin - docs/GRAPH_PASSES.md "Quantization") is a
#: compatible v2 extension: caches without it load unchanged, and a
#: cache carrying it is rejected by builds that predate the knob via
#: the unknown-per-layer-knob check below (regenerate with that
#: build's tools/autotune.py), never silently misapplied
LAYER_TUNABLE_KEYS = ("space_to_depth", "layer_dtype", "layer_quant")


def _check_ladder(path: str, plat: str, ladder) -> None:
    if (not isinstance(ladder, list) or not ladder
            or not all(isinstance(b, int) and not isinstance(b, bool)
                       and b >= 1 for b in ladder)
            or sorted(set(ladder)) != ladder):
        raise ConfigError(
            f"tuning_cache: {path} platform '{plat}' 'serve_ladder' "
            f"must be a strictly increasing list of positive ints, "
            f"got {ladder!r}")


def load_cache(path: str) -> dict:
    """Parse + schema-check a tuning-cache file (raises ConfigError:
    a cache the user POINTED AT must never be silently garbage).
    v1 caches migrate to the v2 shape in memory - one-shot, no write
    on read; save_entry persists v2."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            blob = json.load(f)
    except OSError as e:
        raise ConfigError(f"tuning_cache: cannot read {path}: {e}")
    except ValueError as e:
        raise ConfigError(f"tuning_cache: {path} is not JSON: {e}")
    if (not isinstance(blob, dict)
            or not isinstance(blob.get("platforms"), dict)):
        raise ConfigError(
            f"tuning_cache: {path} has no 'platforms' mapping (not a "
            "tools/autotune.py artifact?)")
    version = blob.get("version", 1)
    if not isinstance(version, int) or version not in (1, VERSION):
        raise ConfigError(
            f"tuning_cache: {path} carries schema version {version!r}"
            f"; this build reads versions 1-{VERSION} (re-run "
            "tools/autotune.py to regenerate)")
    for plat, entry in blob["platforms"].items():
        if entry is not None and not isinstance(entry, dict):
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' entry is "
                f"{type(entry).__name__}, expected an object")
        knobs = (entry or {}).get("knobs", {})
        if not isinstance(knobs, dict):
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' 'knobs' is "
                f"{type(knobs).__name__}, expected an object")
        unknown = [k for k in knobs if k not in TUNABLE_KEYS]
        if unknown:
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' carries "
                f"unknown knob(s) {unknown}; tunable keys are "
                f"{list(TUNABLE_KEYS)}")
        layers = (entry or {}).get("layers", {})
        if layers is None:
            layers = {}
        if not isinstance(layers, dict):
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' 'layers' is "
                f"{type(layers).__name__}, expected an object")
        for lname, kv in layers.items():
            if not isinstance(kv, dict):
                raise ConfigError(
                    f"tuning_cache: {path} platform '{plat}' layer "
                    f"'{lname}' plan is {type(kv).__name__}, expected "
                    "an object")
            bad = [k for k in kv if k not in LAYER_TUNABLE_KEYS]
            if bad:
                raise ConfigError(
                    f"tuning_cache: {path} platform '{plat}' layer "
                    f"'{lname}' carries unknown per-layer knob(s) "
                    f"{bad}; tunable keys are {list(LAYER_TUNABLE_KEYS)}")
        ladder = (entry or {}).get("serve_ladder")
        if ladder is not None:
            _check_ladder(path, plat, ladder)
    if version == 1:
        # one-shot migration: a global-only v1 cache becomes a v2
        # blob with empty per-layer plans - the structure every
        # consumer below reads
        blob["version"] = VERSION
        for entry in blob["platforms"].values():
            if isinstance(entry, dict):
                entry.setdefault("layers", {})
    return blob


def platform_entry(path: str,
                   platform: Optional[str] = None) -> dict:
    """The (validated, migrated) cache entry for `platform` (default:
    the live jax backend); {} when the cache has no entry for it."""
    blob = load_cache(path)
    if platform is None:
        import jax
        platform = jax.default_backend()
    return blob["platforms"].get(platform) or {}


def tuned_knobs(path: str,
                platform: Optional[str] = None) -> Dict[str, str]:
    """The cache's GLOBAL knob dict for `platform`, values
    stringified for set_param-style application. {} when the cache
    has no entry for this platform."""
    entry = platform_entry(path, platform)
    return {k: str(v) for k, v in entry.get("knobs", {}).items()}


def tuned_layer_plan(path: str, platform: Optional[str] = None
                     ) -> Dict[str, Dict[str, str]]:
    """The cache's per-layer plan for `platform`:
    {layer_name: {knob: value}}, values stringified. {} for v1 caches
    or platforms without an entry."""
    entry = platform_entry(path, platform)
    return {ln: {k: str(v) for k, v in kv.items()}
            for ln, kv in (entry.get("layers") or {}).items()}


def tuned_serve_ladder(path: str, platform: Optional[str] = None
                       ) -> Optional[List[int]]:
    """The cache's serving bucket ladder for `platform`, or None when
    absent (the Server then falls back to the power-of-two set)."""
    entry = platform_entry(path, platform)
    ladder = entry.get("serve_ladder")
    return list(ladder) if ladder else None


def int_knob(knobs: Dict[str, str], key: str, explicit,
             minimum: int) -> Optional[int]:
    """THE apply rule for integer tunables, shared by every consumer
    (main.LearnTask and NetTrainer) so they can never disagree on
    the same cache file: the knob must be present, not explicitly
    set by the config (`explicit` = the keys the config named),
    parseable as int, and >= minimum - anything else returns None
    (a malformed value in an otherwise-valid shared cache skips,
    never errors)."""
    if key not in knobs or key in explicit:
        return None
    try:
        v = int(knobs[key])
    except ValueError:
        return None
    return v if v >= minimum else None


def save_entry(path: str, platform: str, knobs: Dict[str, object],
               measured: Optional[Dict[str, float]] = None,
               device_kind: str = "",
               layers: Optional[Dict[str, Dict[str, object]]] = None,
               serve_ladder: Optional[List[int]] = None) -> dict:
    """Merge one platform's tuned knobs (plus the optional v2
    per-layer plan and serve ladder) into the cache file (atomic
    write via tmp + replace; other platforms' entries are
    preserved)."""
    unknown = [k for k in knobs if k not in TUNABLE_KEYS]
    if unknown:
        raise ValueError(f"untunable knob(s) {unknown}")
    for lname, kv in (layers or {}).items():
        bad = [k for k in kv if k not in LAYER_TUNABLE_KEYS]
        if bad:
            raise ValueError(
                f"untunable per-layer knob(s) {bad} for layer "
                f"'{lname}'")
    if serve_ladder is not None:
        serve_ladder = sorted({int(b) for b in serve_ladder})
        _check_ladder(path, platform, serve_ladder)
    if os.path.exists(path):
        # an EXISTING cache must parse before we merge into it: a
        # corrupt file (or one written by a newer version with knobs
        # this build doesn't know) raises instead of being silently
        # replaced - the atomic write below would otherwise destroy
        # every other platform's entries
        blob = load_cache(path)
    else:
        blob = {"version": VERSION, "platforms": {}}
    blob["version"] = VERSION
    entry = {
        "knobs": dict(knobs),
        "layers": {ln: {k: str(v) for k, v in kv.items()}
                   for ln, kv in (layers or {}).items()},
        "measured": dict(measured or {}),
        "device_kind": device_kind,
        "date": time.strftime("%Y-%m-%d"),
    }
    if serve_ladder is not None:
        entry["serve_ladder"] = serve_ladder
    blob["platforms"][platform] = entry
    from cxxnet_tpu.utils.fault import atomic_writer
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with atomic_writer(path, "w") as f:
        json.dump(blob, f, indent=1, sort_keys=True)
        f.write("\n")
    return blob
