"""TVM-style persistent per-platform tuning cache (arXiv:1802.04799).

`tools/autotune.py` searches the dispatch/staging/serving knob space
against measured throughput and persists the winners here; the CLI
and wrapper pick them up via `tuning_cache = <path>` (docs/
GRAPH_PASSES.md "Autotuner"). Contract: tuned values are DEFAULTS -
a key the user's config sets explicitly always wins, and a cache
entry for a different platform (or an inapplicable knob) is silently
ignored, so shipping one cache file across a heterogeneous fleet is
safe.

File format (JSON, written atomically):

    {"version": 1,
     "platforms": {
       "cpu": {"knobs": {"steps_per_dispatch": 4, "prefetch_stage": 1,
                         "serve_max_batch": 32, "stage_dtype": ""},
               "measured": {"default_ips": ..., "best_ips": ...},
               "device_kind": "...", "date": "YYYY-MM-DD"}}}
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

from cxxnet_tpu.utils.config import ConfigError

VERSION = 1

#: every knob the autotuner may set, with the config key it maps to.
#: `stage_dtype` is the staged-input layout axis (f32 vs bf16 H2D
#: bytes - docs/PERFORMANCE.md); `serve_max_batch` is the serving
#: bucket-ladder ceiling (docs/SERVING.md).
TUNABLE_KEYS = ("steps_per_dispatch", "prefetch_stage",
                "serve_max_batch", "stage_dtype")


def load_cache(path: str) -> dict:
    """Parse + schema-check a tuning-cache file (raises ConfigError:
    a cache the user POINTED AT must never be silently garbage)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            blob = json.load(f)
    except OSError as e:
        raise ConfigError(f"tuning_cache: cannot read {path}: {e}")
    except ValueError as e:
        raise ConfigError(f"tuning_cache: {path} is not JSON: {e}")
    if (not isinstance(blob, dict)
            or not isinstance(blob.get("platforms"), dict)):
        raise ConfigError(
            f"tuning_cache: {path} has no 'platforms' mapping (not a "
            "tools/autotune.py artifact?)")
    for plat, entry in blob["platforms"].items():
        if entry is not None and not isinstance(entry, dict):
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' entry is "
                f"{type(entry).__name__}, expected an object")
        knobs = (entry or {}).get("knobs", {})
        if not isinstance(knobs, dict):
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' 'knobs' is "
                f"{type(knobs).__name__}, expected an object")
        unknown = [k for k in knobs if k not in TUNABLE_KEYS]
        if unknown:
            raise ConfigError(
                f"tuning_cache: {path} platform '{plat}' carries "
                f"unknown knob(s) {unknown}; tunable keys are "
                f"{list(TUNABLE_KEYS)}")
    return blob


def tuned_knobs(path: str,
                platform: Optional[str] = None) -> Dict[str, str]:
    """The cache's knob dict for `platform` (default: the live jax
    backend), values stringified for set_param-style application.
    {} when the cache has no entry for this platform."""
    blob = load_cache(path)
    if platform is None:
        import jax
        platform = jax.default_backend()
    entry = blob["platforms"].get(platform)
    if not entry:
        return {}
    return {k: str(v) for k, v in entry.get("knobs", {}).items()}


def int_knob(knobs: Dict[str, str], key: str, explicit,
             minimum: int) -> Optional[int]:
    """THE apply rule for integer tunables, shared by every consumer
    (main.LearnTask and NetTrainer) so they can never disagree on
    the same cache file: the knob must be present, not explicitly
    set by the config (`explicit` = the keys the config named),
    parseable as int, and >= minimum - anything else returns None
    (a malformed value in an otherwise-valid shared cache skips,
    never errors)."""
    if key not in knobs or key in explicit:
        return None
    try:
        v = int(knobs[key])
    except ValueError:
        return None
    return v if v >= minimum else None


def save_entry(path: str, platform: str, knobs: Dict[str, object],
               measured: Optional[Dict[str, float]] = None,
               device_kind: str = "") -> dict:
    """Merge one platform's tuned knobs into the cache file
    (atomic write via tmp + replace; other platforms' entries are
    preserved)."""
    unknown = [k for k in knobs if k not in TUNABLE_KEYS]
    if unknown:
        raise ValueError(f"untunable knob(s) {unknown}")
    if os.path.exists(path):
        # an EXISTING cache must parse before we merge into it: a
        # corrupt file (or one written by a newer version with knobs
        # this build doesn't know) raises instead of being silently
        # replaced - the atomic write below would otherwise destroy
        # every other platform's entries
        blob = load_cache(path)
    else:
        blob = {"version": VERSION, "platforms": {}}
    blob["version"] = VERSION
    blob["platforms"][platform] = {
        "knobs": dict(knobs),
        "measured": dict(measured or {}),
        "device_kind": device_kind,
        "date": time.strftime("%Y-%m-%d"),
    }
    from cxxnet_tpu.utils.fault import atomic_writer
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with atomic_writer(path, "w") as f:
        json.dump(blob, f, indent=1, sort_keys=True)
        f.write("\n")
    return blob
