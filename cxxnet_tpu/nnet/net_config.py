"""NetConfig: `key = value` config stream -> layer DAG.

Behavioral parity with src/nnet/nnet_config.h:26-411:

- `netconfig = start/end` brackets the net block; `layer[...] = type[:name]`
  lines declare connections and switch subsequent params into that layer's
  private config; params outside any layer go into `defcfg` and are replayed
  into EVERY layer (global defaults like random_type).
- Layer syntax (nnet_config.h:303-360):
    layer[+1]          input = top node, fresh anonymous output node
    layer[+0]          self-loop (in == out), e.g. dropout/loss layers
    layer[+1:name]     fresh output node named `name`
    layer[a->b]        explicit nodes; `a`/`b` may be comma lists
    layer[a,b->c]      multi-input connection
  Node names may be arbitrary strings; node "0"/"in" is the data input.
  Input nodes must already exist; output nodes are allocated on first use.
- `share[tag]` layers reuse the params of the primary layer named `tag`
  (weight sharing; kSharedLayer).
- Global params captured here: `updater`, `sync`, `label_vec[a,b) = name`
  (label column slicing), `input_shape = c,h,w`, `extra_data_num`,
  `extra_data_shape[i] = c,h,w`.
- Structure equality is validated when configuring on top of a loaded net
  (model file vs config consistency - nnet_config.h:266-271).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

ConfigPairs = List[Tuple[str, str]]

_LAYER_KEY_RE = re.compile(r"^layer\[")


@dataclass
class LayerInfo:
    """One connection declaration (nnet_config.h LayerInfo)."""

    type_name: str = ""
    primary_layer_index: int = -1  # >= 0 for shared layers
    name: str = ""
    nindex_in: List[int] = field(default_factory=list)
    nindex_out: List[int] = field(default_factory=list)

    @property
    def is_shared(self) -> bool:
        return self.primary_layer_index >= 0

    def structure_equals(self, other: "LayerInfo") -> bool:
        return (self.type_name == other.type_name
                and self.primary_layer_index == other.primary_layer_index
                and self.name == other.name
                and self.nindex_in == other.nindex_in
                and self.nindex_out == other.nindex_out)


class NetConfig:
    """Parses and holds the network structure + per-layer configs."""

    def __init__(self) -> None:
        self.input_shape: Tuple[int, int, int] = (0, 0, 0)  # (c, y, x)
        self.extra_data_num = 0
        self.extra_shape: List[int] = []
        self.layers: List[LayerInfo] = []
        self.node_names: List[str] = []
        self.node_name_map: Dict[str, int] = {}
        self.layer_name_map: Dict[str, int] = {}
        self.updater_type = "sgd"
        self.sync_type = "simple"
        self.label_name_map: Dict[str, int] = {"label": 0}
        self.label_range: List[Tuple[int, int]] = [(0, 1)]
        self.defcfg: ConfigPairs = []
        self.layercfg: List[ConfigPairs] = []
        self.init_end = False

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    @property
    def num_layers(self) -> int:
        return len(self.layers)

    # ------------------------------------------------------------------
    def set_global_param(self, name: str, val: str) -> None:
        if name == "updater":
            self.updater_type = val
        if name == "sync":
            self.sync_type = val
        m = re.match(r"^label_vec\[(\d+),(\d+)\)$", name)
        if m:
            self.label_range.append((int(m.group(1)), int(m.group(2))))
            self.label_name_map[val] = len(self.label_range) - 1

    # ------------------------------------------------------------------
    def configure(self, cfg: ConfigPairs) -> None:
        """Replay an ordered config into the structure (Configure)."""
        self._clear_config()
        if not self.node_names and not self.node_name_map:
            self.node_names.append("in")
            self.node_name_map["in"] = 0
        self.node_name_map["0"] = 0

        netcfg_mode = 0
        cfg_top_node = 0
        cfg_layer_index = 0
        for name, val in cfg:
            if name == "extra_data_num":
                num = int(val)
                for i in range(num):
                    nname = f"in_{i + 1}"
                    if nname not in self.node_name_map:
                        self.node_names.append(nname)
                        self.node_name_map[nname] = i + 1
                self.extra_data_num = num
            if name.startswith("extra_data_shape["):
                x, y, z = (int(t) for t in val.split(","))
                self.extra_shape.extend([x, y, z])
            if not self.init_end and name == "input_shape":
                c, y, x = (int(t) for t in val.split(","))
                self.input_shape = (c, y, x)
            if netcfg_mode != 2:
                self.set_global_param(name, val)
            if name == "netconfig" and val == "start":
                netcfg_mode = 1
            if name == "netconfig" and val == "end":
                netcfg_mode = 0
            if _LAYER_KEY_RE.match(name):
                info = self._get_layer_info(name, val, cfg_top_node,
                                            cfg_layer_index)
                netcfg_mode = 2
                if not self.init_end:
                    assert len(self.layers) == cfg_layer_index, \
                        "NetConfig inconsistent"
                    self.layers.append(info)
                    self.layercfg.append([])
                else:
                    if cfg_layer_index >= len(self.layers):
                        raise ValueError("config layer index exceeds bound")
                    if not info.structure_equals(self.layers[cfg_layer_index]):
                        raise ValueError(
                            "config setting does not match existing network "
                            "structure")
                cfg_top_node = (info.nindex_out[0]
                                if len(info.nindex_out) == 1 else -1)
                cfg_layer_index += 1
                continue
            if netcfg_mode == 2:
                if self.layers[cfg_layer_index - 1].is_shared:
                    raise ValueError(
                        "please do not set parameters in shared layer, "
                        "set them in primary layer")
                self.layercfg[cfg_layer_index - 1].append((name, val))
            else:
                self.defcfg.append((name, val))
        if not self.init_end:
            self._init_net()

    # ------------------------------------------------------------------
    def get_layer_index(self, name: str) -> int:
        if name not in self.layer_name_map:
            raise KeyError(f"unknown layer name {name}")
        return self.layer_name_map[name]

    def get_node_index(self, name: str, alloc_unknown: bool) -> int:
        if name in self.node_name_map:
            return self.node_name_map[name]
        if not alloc_unknown:
            raise ValueError(
                f"ConfigError: undefined node name {name}; the input node "
                "of a layer must be the output of an earlier layer")
        value = len(self.node_names)
        self.node_name_map[name] = value
        self.node_names.append(name)
        return value

    # ------------------------------------------------------------------
    def _get_layer_info(self, name: str, val: str, top_node: int,
                        cfg_layer_index: int) -> LayerInfo:
        info = LayerInfo()
        # --- node spec ---
        m = re.match(r"^layer\[\+(\d+)(?::([^\]]+))?\]$", name)
        if m:
            if top_node < 0:
                raise ValueError(
                    "ConfigError: layer[+1] used, but the last layer has "
                    "more than one output; use layer[in->out] instead")
            inc = int(m.group(1))
            info.nindex_in.append(top_node)
            if m.group(2):
                info.nindex_out.append(
                    self.get_node_index(m.group(2), True))
            elif inc == 0:
                info.nindex_out.append(top_node)
            else:
                # key anonymous nodes by the LAYER index, not the top
                # node: two `layer[+1]` declarations whose top is the
                # same node (after an explicit re-target) must allocate
                # distinct output nodes, as the reference's positional
                # allocation does
                tag = f"!node-of-layer-{cfg_layer_index}"
                info.nindex_out.append(self.get_node_index(tag, True))
        else:
            m = re.match(r"^layer\[([^\]>]+)->([^\]]+)\]$", name)
            if not m:
                raise ValueError(f"ConfigError: invalid layer format {name}")
            for tok in m.group(1).split(","):
                info.nindex_in.append(self.get_node_index(tok, False))
            for tok in m.group(2).split(","):
                info.nindex_out.append(self.get_node_index(tok, True))

        # --- type spec: `type`, `type:name`, `share[tag]` ---
        if ":" in val:
            ltype, layer_name = val.split(":", 1)
        else:
            ltype, layer_name = val, ""
        if ltype.startswith("share"):
            m = re.match(r"^share\[([^\]]+)\]$", ltype)
            if not m:
                raise ValueError(
                    "ConfigError: shared layer must specify the tag of the "
                    "layer to share with")
            tag = m.group(1)
            if tag not in self.layer_name_map:
                raise ValueError(
                    f"ConfigError: shared layer tag {tag} is not defined "
                    "before")
            info.type_name = "share"
            info.primary_layer_index = self.layer_name_map[tag]
        else:
            info.type_name = ltype
            if layer_name:
                if layer_name in self.layer_name_map:
                    if self.layer_name_map[layer_name] != cfg_layer_index:
                        raise ValueError(
                            "ConfigError: layer name in the configuration "
                            "file does not match the name stored in model")
                else:
                    self.layer_name_map[layer_name] = cfg_layer_index
                info.name = layer_name
        return info

    # ------------------------------------------------------------------
    def _init_net(self) -> None:
        num_nodes = 0
        for info in self.layers:
            for j in info.nindex_in + info.nindex_out:
                num_nodes = max(j + 1, num_nodes)
        assert num_nodes == len(self.node_names), \
            "num_nodes inconsistent with node_names"
        self.init_end = True

    def _clear_config(self) -> None:
        self.defcfg = []
        self.layercfg = [[] for _ in self.layercfg]

    # ------------------------------------------------------------------
    def clone(self) -> "NetConfig":
        """Deep structural copy INCLUDING the replayed per-layer
        configs and label maps (to_dict is structure-only, by the
        checkpoint contract) - what the graph-pass pipeline
        (nnet/passes.py) transforms, so the trainer's own NetConfig
        never mutates under an inference-only rewrite."""
        cfg = NetConfig()
        cfg.input_shape = tuple(self.input_shape)
        cfg.extra_data_num = self.extra_data_num
        cfg.extra_shape = list(self.extra_shape)
        cfg.node_names = list(self.node_names)
        cfg.node_name_map = dict(self.node_name_map)
        cfg.layer_name_map = dict(self.layer_name_map)
        cfg.updater_type = self.updater_type
        cfg.sync_type = self.sync_type
        cfg.label_name_map = dict(self.label_name_map)
        cfg.label_range = list(self.label_range)
        cfg.defcfg = list(self.defcfg)
        cfg.layercfg = [list(c) for c in self.layercfg]
        cfg.layers = [
            LayerInfo(type_name=li.type_name,
                      primary_layer_index=li.primary_layer_index,
                      name=li.name,
                      nindex_in=list(li.nindex_in),
                      nindex_out=list(li.nindex_out))
            for li in self.layers]
        cfg.init_end = self.init_end
        return cfg

    # ------------------------------------------------------------------
    # structure (de)serialization for checkpoints
    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        """Structure-only snapshot (SaveNet analog; training params like
        updater_type are NOT saved, matching nnet_config.h:126-145)."""
        return {
            "input_shape": list(self.input_shape),
            "extra_data_num": self.extra_data_num,
            "extra_shape": list(self.extra_shape),
            "node_names": list(self.node_names),
            "layers": [
                {
                    "type": li.type_name,
                    "primary_layer_index": li.primary_layer_index,
                    "name": li.name,
                    "nindex_in": list(li.nindex_in),
                    "nindex_out": list(li.nindex_out),
                }
                for li in self.layers
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NetConfig":
        cfg = cls()
        cfg.input_shape = tuple(d["input_shape"])
        cfg.extra_data_num = d["extra_data_num"]
        cfg.extra_shape = list(d["extra_shape"])
        cfg.node_names = list(d["node_names"])
        cfg.node_name_map = {n: i for i, n in enumerate(cfg.node_names)}
        for i, ld in enumerate(d["layers"]):
            li = LayerInfo(
                type_name=ld["type"],
                primary_layer_index=ld["primary_layer_index"],
                name=ld["name"],
                nindex_in=list(ld["nindex_in"]),
                nindex_out=list(ld["nindex_out"]),
            )
            cfg.layers.append(li)
            cfg.layercfg.append([])
            if li.name and not li.is_shared:
                if li.name in cfg.layer_name_map:
                    raise ValueError(
                        f"invalid model file, duplicated layer name: "
                        f"{li.name}")
                cfg.layer_name_map[li.name] = i
        cfg.init_end = True
        return cfg
