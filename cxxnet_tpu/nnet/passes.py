"""Graph-level optimizing passes over the NetConfig DAG.

NetConfig already parses configs into a layer DAG; this module treats
that DAG as an IR with Relay-style optimizing passes (PAPERS.md:
arXiv:1810.00952) run by the trainer before the Network is built -
`PassPipeline` of named `GraphPass`es over a shared pattern-rewrite
engine (docs/GRAPH_PASSES.md). Shipped passes:

- **space_to_depth** (graph stage): the input-conv space-to-depth
  rewrite, previously an auto heuristic buried inside `ops.conv2d`,
  re-expressed as a pattern rewrite: the pass evaluates the SAME
  predicate (`ops.conv.s2d_auto` - one definition, so the pass and
  the op cannot disagree) against the inferred node shapes and stamps
  an explicit `space_to_depth = 0|1` onto each conv's layer config.
  An explicit per-layer `space_to_depth` always wins.
- **autocast** (graph stage): the bf16/f32 mixed-precision policy as
  ONE pass instead of per-layer flags: under `dtype = bfloat16` it
  stamps a compute dtype per layer (`GraphModule.dtype_plan`,
  consumed by `Network.forward`) - matmul/conv-heavy layers run
  bf16, numerically fragile layers (batch_norm, lrn, the loss heads)
  stay f32. The existing flags become overrides: `dtype` sets the
  policy, a per-layer `layer_dtype = float32|bfloat16` pins a layer.
- **dead_layer_elim** (infer stage): prune every layer not on a path
  to the requested output node - the extract/finetune/serve subgraph.
  jax's jit DCEs the *lowered* module already (measured: the compiled
  HLO of an early-node infer is byte-identical with or without the
  dead tail), so the honest wins are the traced program (strictly
  fewer jaxpr equations), trace/lowering latency, and keeping the
  fold pass's pattern space small. Kept `share[...]` layers whose
  primary is pruned are promoted to primaries (their params arrive
  via the param map, so no dead ancestor is retained).
- **fold_conv_bn** (infer stage): fold a batch_norm following a conv
  or fullc into that layer's weights/bias so the donation-free
  `infer_step` executes a single fused matmul/conv with NO moment or
  variance computation. This repo's BN normalizes with *minibatch*
  statistics even at eval (reference quirk), so the fold freezes the
  statistics captured from ONE calibration batch (the trainer's
  first inference batch, or an explicit
  `trainer.calibrate_graph_passes(batch)`); `rsqrt(var + eps)` is
  precomputed on the host so the folded jaxpr carries no rsqrt
  either. The folded weights stay a LIVE function of the params
  argument (`W' = W * slope * rstd` inside the jit), so a
  checkpoint load or set_weight is picked up without re-folding;
  only the frozen statistics are calibration-time constants.
  Semantics note (docs/GRAPH_PASSES.md "when folding loses"):
  frozen stats make inference batch-composition-INDEPENDENT - for
  serving that is a correctness win (a request's answer no longer
  depends on what else was coalesced into its bucket); parity with
  the unfolded path is exact (~ULP contraction change) when the
  calibration batch IS the inference batch and approximate
  otherwise.

- **cse_share** (infer stage): common-subexpression sharing - two
  sibling layers reading the SAME input nodes and computing the same
  function (both fed by one primary's params via share[...], or both
  param-less with identical configs) produce identical values at
  eval, so the duplicate is deduped: its consumers re-read the kept
  layer's output node and shares of a dropped primary re-point to the
  kept duplicate's param source (the dead-primary promotion idea of
  dead_layer_elim applied sideways). Layers with their OWN params are
  never deduped against each other - equal weights cannot be proven
  from the graph.
- **merge_conv_1x1** (infer stage): two adjacent convs where the
  second is 1x1/stride-1/pad-0/ungrouped collapse into ONE conv via
  weight contraction `W' = W2 . W1` (`b' = W2 . b1 + b2`), computed
  in-jit from the LIVE params like fold_conv_bn's make_param_fn
  treatment - the traced infer program carries exactly one fewer
  conv (the weight-side contraction is a tiny dot, not a data-sized
  conv). Sites where the intermediate activation is the requested
  output, either conv is weight-shared/grouped, or an activation
  sits between the convs are excluded.
- **fuse_activation** (infer stage): a conv/fullc followed by a
  chain of separate `bias` layers and/or one `relu` gets the
  activation STAMPED into the producer (`fused_act = relu`, consumed
  by the layer's apply) and the bias layers' params absorbed into
  the producer's bias (`b' = b + sum(b_i)`, live in-jit) - the infer
  jaxpr loses the separate per-layer elementwise equations (a
  standalone bias layer costs a broadcast + a data-sized add; the
  absorbed form is one vector add inside the param function).
- **elim_reshape** (infer stage): a `flatten` layer whose output
  feeds exactly one fullc is eliminated - the fullc consumes the
  4-D node directly (its apply flattens anyway; the pass stamps
  `flatten_input = 1` so shape inference accepts it). Bitwise
  value-identical (same memory-order flatten), one reshape
  equation fewer in the traced program per site.
- **quantize_int8** (infer stage): int8 post-training quantization
  (TVM/Relay's quantize pass shape - arXiv:1810.00952) of eligible
  conv/fullc layers. A calibration sweep (the fold's
  pass_calibration machinery) records each eligible layer's
  activation absmax; the pass then stamps a per-TENSOR activation
  scale (absmax / 127) per site, and the trainer freezes a
  per-CHANNEL symmetric weight scale from the TRANSFORMED float
  weights (post fold/merge/fuse - `_fill_quant_scales`). Execution:
  `make_param_fn` gains a quantize stage computing the int8 weights
  IN-JIT from the live params (one fused round/clip/convert pass -
  the scales are the only frozen constants, invalidated by the same
  epoch-bump eviction as fold stats on set_weight/reload), and the
  conv/fullc apply routes through ops/int8.py (Pallas TPU dot
  kernel with int32 accumulation; lax preferred-element-type
  fallback on CPU). `layer_quant = int8|float` pins a layer;
  BN/LRN/loss heads are never eligible (not conv/fullc). See
  docs/GRAPH_PASSES.md "Quantization" for the scale scheme and
  "when int8 loses".

Passes never touch the training graph structure or the checkpoint
format: graph-stage passes only stamp layer configs / dtype
annotations (NetConfig.to_dict is structure-only), and infer-stage
passes run on a clone consumed solely by the inference executables.

On top, the TVM-style tuning cache (arXiv:1802.04799) lives in
`nnet/tuning.py` and `tools/autotune.py` - since cache schema v2 it
carries per-layer plans (s2d per conv, per-layer dtype) and a
telemetry-shaped serve bucket ladder next to the global knobs.
"""

from __future__ import annotations

import difflib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from cxxnet_tpu.nnet.net_config import NetConfig

# layer types whose math is one big contraction - the autocast
# policy's bf16 set is "everything except the fragile ones", this set
# only documents the headline beneficiaries
_F32_SENSITIVE_TYPES = frozenset((
    "batch_norm", "lrn", "softmax", "l2_loss", "multi_logistic"))

# fold pattern: the producing layer types a batch_norm folds into
_FOLDABLE_TYPES = frozenset(("conv", "fullc"))

# fuse_activation pattern: producers that accept a `fused_act` stamp,
# and the elementwise layer types that fuse into them (bias layers
# absorb into the producer's bias; ONE activation ends the chain)
_ACT_PRODUCER_TYPES = frozenset(("conv", "fullc"))
_ACT_CHAIN_TYPES = frozenset(("bias", "relu"))
_ACT_TYPES = frozenset(("relu",))

# quantize_int8 pattern: the layer types whose data-path contraction
# has an int8 kernel (ops/int8.py); everything else - BN, LRN, the
# loss heads - stays float by construction
_QUANT_TYPES = frozenset(("conv", "fullc"))

# elim_reshape pattern: reshape-only layers, and the consumers that
# can absorb the flatten (fullc's apply flattens its input anyway -
# the `flatten_input = 1` stamp makes its shape inference agree)
_RESHAPE_TYPES = frozenset(("flatten",))
_RESHAPE_CONSUMER_TYPES = frozenset(("fullc",))


# ---------------------------------------------------------------------------
# the IR the passes transform
# ---------------------------------------------------------------------------
@dataclass
class FoldSite:
    """One folded conv/fullc + batch_norm pair: the live-params keys
    of both layers plus the frozen per-channel calibration statistics
    (mean of the BN input, rsqrt(var + eps))."""

    conv_key: str
    bn_key: str
    mean: np.ndarray
    rstd: np.ndarray


@dataclass
class MergeSite:
    """One conv + 1x1-conv pair collapsed into the first conv: the
    live-params keys of both convs. make_param_fn contracts
    `W' = W2 . W1` / `b' = W2 . b1 + b2` from the LIVE weights."""

    first_key: str
    second_key: str


@dataclass
class ActFuseSite:
    """One producer whose trailing bias layers were absorbed: the
    producer's live-params key plus the absorbed bias layers' keys
    (in chain order). The activation itself is a config stamp
    (`fused_act`), not a param transform."""

    producer_key: str
    bias_keys: List[str]


@dataclass
class QuantSite:
    """One int8-quantized conv/fullc: the live-params key, the frozen
    per-tensor activation scale (calibration absmax / 127), and the
    frozen per-channel weight scale. `wscale` is filled by the
    TRAINER after the pipeline runs (`_fill_quant_scales`) from the
    TRANSFORMED float weights - a folded or merged weight is
    quantized at its folded/merged values, not its raw checkpoint
    values; a site whose wscale was never filled executes float
    (make_param_fn skips its quantize stage)."""

    key: str
    act_scale: float
    wscale: Optional[np.ndarray] = None


@dataclass
class GraphModule:
    """A NetConfig DAG in flight through the pass pipeline.

    `param_keys[i]` is the LIVE params-pytree key layer i's weights
    come from (None for param-less or shared layers) - structural
    passes keep it aligned so `make_param_fn` can rebuild the
    transformed graph's params from the live train params no matter
    how indices shifted."""

    cfg: NetConfig
    batch_size: int
    compute_dtype: Any = None
    param_keys: List[Optional[str]] = field(default_factory=list)
    folds: List[FoldSite] = field(default_factory=list)
    merges: List[MergeSite] = field(default_factory=list)
    act_fuses: List[ActFuseSite] = field(default_factory=list)
    quants: List[QuantSite] = field(default_factory=list)
    dtype_plan: Dict[int, Any] = field(default_factory=dict)
    log: List[str] = field(default_factory=list)

    @classmethod
    def from_net_config(cls, cfg: NetConfig, batch_size: int,
                        compute_dtype: Any = None) -> "GraphModule":
        from cxxnet_tpu.nnet.network import param_key
        keys: List[Optional[str]] = []
        for idx, info in enumerate(cfg.layers):
            keys.append(None if info.is_shared
                        else param_key(cfg, idx))
        return cls(cfg=cfg, batch_size=batch_size,
                   compute_dtype=compute_dtype, param_keys=keys)

    # -- structural edits -------------------------------------------------
    def remove_layers(self, indices: Sequence[int]) -> None:
        """Drop layers by index, remapping share back-references and
        keeping layercfg/param_keys/dtype_plan aligned."""
        drop = set(indices)
        if not drop:
            return
        cfg = self.cfg
        remap: Dict[int, int] = {}
        for old in range(len(cfg.layers)):
            if old not in drop:
                remap[old] = len(remap)
        for old in drop:
            info = cfg.layers[old]
            if any(li.primary_layer_index == old
                   for i, li in enumerate(cfg.layers)
                   if i not in drop and li.is_shared):
                raise ValueError(
                    f"cannot remove layer {old} "
                    f"({info.type_name}): a kept share[...] layer "
                    "references it as primary")
        cfg.layers = [li for i, li in enumerate(cfg.layers)
                      if i not in drop]
        cfg.layercfg = [c for i, c in enumerate(cfg.layercfg)
                        if i not in drop]
        self.param_keys = [k for i, k in enumerate(self.param_keys)
                           if i not in drop]
        self.dtype_plan = {remap[i]: d for i, d in
                           self.dtype_plan.items() if i in remap}
        for li in cfg.layers:
            if li.is_shared:
                li.primary_layer_index = remap[li.primary_layer_index]
        cfg.layer_name_map = {
            li.name: i for i, li in enumerate(cfg.layers)
            if li.name and not li.is_shared}

    def param_map(self) -> Dict[str, str]:
        """Transformed-graph param key -> live-params key."""
        from cxxnet_tpu.nnet.network import param_key
        out: Dict[str, str] = {}
        for idx, info in enumerate(self.cfg.layers):
            if info.is_shared or self.param_keys[idx] is None:
                continue
            out[param_key(self.cfg, idx)] = self.param_keys[idx]
        return out


@dataclass
class PassContext:
    """Per-run inputs the passes read (never mutate)."""

    #: requested output node for infer-stage passes (None = train
    #: graph, where only graph-stage passes apply)
    target_node: Optional[int] = None
    #: bn live-params key -> (mean, rstd) calibration stats; None =
    #: not calibrated yet (fold defers)
    fold_stats: Optional[Dict[str, Tuple[np.ndarray, np.ndarray]]] = None
    #: quant-eligible live-params key -> activation absmax from the
    #: calibration sweep; None = not calibrated yet (quantize defers)
    quant_stats: Optional[Dict[str, float]] = None


# ---------------------------------------------------------------------------
# pattern-rewrite engine: DAG queries shared by every pass
# ---------------------------------------------------------------------------
def node_consumers(cfg: NetConfig) -> Dict[int, List[int]]:
    """node index -> layer indices reading it (declaration order)."""
    cons: Dict[int, List[int]] = {}
    for idx, info in enumerate(cfg.layers):
        for j in info.nindex_in:
            cons.setdefault(j, []).append(idx)
    return cons


def share_primaries(cfg: NetConfig) -> set:
    """Layer indices that are the primary of some share[...] layer."""
    return {li.primary_layer_index for li in cfg.layers if li.is_shared}


def find_fold_sites(cfg: NetConfig) -> List[Tuple[int, int]]:
    """(producer_idx, bn_idx) pairs matching the fold pattern: a
    non-shared conv/fullc whose single output node feeds EXACTLY one
    batch_norm (self-loop BN allowed - later readers then see the
    post-BN value, which the folded layer reproduces). Weight-shared
    layers are excluded on both sides: folding a shared weight would
    specialize it per site."""
    sites: List[Tuple[int, int]] = []
    primaries = share_primaries(cfg)
    cons = node_consumers(cfg)
    for j, bn in enumerate(cfg.layers):
        if (bn.type_name != "batch_norm" or bn.is_shared
                or j in primaries):
            continue
        if len(bn.nindex_in) != 1 or len(bn.nindex_out) != 1:
            continue
        a = bn.nindex_in[0]
        writers = [i for i, li in enumerate(cfg.layers)
                   if a in li.nindex_out and i != j]
        if len(writers) != 1:
            continue
        i = writers[0]
        conv = cfg.layers[i]
        if (i > j or conv.type_name not in _FOLDABLE_TYPES
                or conv.is_shared or i in primaries):
            continue
        if len(conv.nindex_out) != 1 or conv.nindex_out[0] != a:
            continue
        readers = [c for c in cons.get(a, ()) if c != j]
        if bn.nindex_out[0] == a:
            # self-loop BN overwrites a: only a reader BETWEEN the
            # conv and the bn would see the raw conv output
            if any(i < c < j for c in readers):
                continue
        elif readers:
            continue
        sites.append((i, j))
    return sites


def layer_quant_pin(cfg: NetConfig, idx: int) -> str:
    """The effective `layer_quant` config of layer `idx` ("" = no
    pin, policy applies). Shared layers resolve through their
    primary's config like every other structured param."""
    src = (cfg.layers[idx].primary_layer_index
           if cfg.layers[idx].is_shared else idx)
    pin = ""
    for k, v in cfg.defcfg + cfg.layercfg[src]:
        if k == "layer_quant":
            pin = v
    return pin


def find_quant_sites(cfg: NetConfig) -> List[int]:
    """Layer indices matching the quantize_int8 pattern: non-shared,
    non-primary conv/fullc layers not pinned `layer_quant = float`.
    The ONE definition - the pass matches the transformed graph with
    it and the trainer matches the live graph for calibration taps,
    so the two can never disagree on what needs an activation
    range."""
    primaries = share_primaries(cfg)
    out: List[int] = []
    for idx, info in enumerate(cfg.layers):
        if (info.type_name not in _QUANT_TYPES or info.is_shared
                or idx in primaries):
            continue
        if layer_quant_pin(cfg, idx) == "float":
            continue
        out.append(idx)
    return out


def node_writers(cfg: NetConfig, node: int) -> List[int]:
    """Layer indices writing a node (declaration order)."""
    return [k for k, li in enumerate(cfg.layers)
            if node in li.nindex_out]


def layer_obj(cfg: NetConfig, idx: int):
    """Instantiate layer `idx` with its effective (defcfg + layercfg)
    config - the pattern matchers' way to read structured layer
    params (kernel size, stride, groups) without building a Network.
    Shared layers resolve to their primary's object. None when the
    config is rejected (an invalid layer cannot match a pattern)."""
    from cxxnet_tpu.layers import create_layer
    info = cfg.layers[idx]
    src = info.primary_layer_index if info.is_shared else idx
    try:
        lay = create_layer(cfg.layers[src].type_name,
                           cfg.layers[src].name)
        for k, v in cfg.defcfg + cfg.layercfg[src]:
            lay.set_param(k, v)
    except (KeyError, ValueError):
        return None
    return lay


def next_fusable_link(cfg: NetConfig, cons, primaries, node: int,
                      last_writer: int,
                      target: Optional[int]) -> Optional[int]:
    """The single fusable elementwise consumer of `node` downstream
    of `last_writer`, or None. Mirrors find_fold_sites' reader rules:
    a self-loop layer may have later readers (they see the post-layer
    value the fused producer reproduces) but none between the writer
    and itself; a new-node layer must be the node's sole reader."""
    if node == target:
        return None  # the caller asked for this intermediate value
    readers = sorted(cons.get(node, ()))
    after = [c for c in readers if c > last_writer]
    if not after:
        return None
    j = after[0]
    info = cfg.layers[j]
    if (info.is_shared or j in primaries
            or info.type_name not in _ACT_CHAIN_TYPES
            or len(info.nindex_in) != 1 or len(info.nindex_out) != 1
            or info.nindex_in[0] != node):
        return None
    if any(last_writer < w < j for w in node_writers(cfg, node)):
        return None  # a foreign writer clobbers the chain value
    if info.nindex_out[0] == node:
        if any(last_writer < c < j for c in readers if c != j):
            return None
        return j
    if len(after) > 1:
        return None  # a second reader needs the raw value
    return j


def find_act_chains(cfg: NetConfig, target: Optional[int],
                    dtype_plan: Optional[Dict[int, Any]] = None,
                    ) -> List[Tuple[int, List[int]]]:
    """(producer_idx, [chain layer indices]) for every conv/fullc
    whose output feeds a fusable bias*/relu chain. Bias layers absorb
    until ONE activation ends the chain; weight-shared layers are
    excluded on both sides, and a chain stops at the first layer
    whose per-layer dtype stamp differs from the producer's (a fused
    layer runs at the producer's dtype - a `layer_dtype` pin on the
    bias/relu must survive)."""
    primaries = share_primaries(cfg)
    cons = node_consumers(cfg)
    out: List[Tuple[int, List[int]]] = []
    claimed: set = set()
    for i, prod in enumerate(cfg.layers):
        if (prod.type_name not in _ACT_PRODUCER_TYPES or prod.is_shared
                or i in primaries or len(prod.nindex_out) != 1):
            continue
        if any(k == "fused_act"
               for k, _ in cfg.defcfg + cfg.layercfg[i]):
            continue  # already carries a stamp: nothing to add
        node, last = prod.nindex_out[0], i
        chain: List[int] = []
        while True:
            j = next_fusable_link(cfg, cons, primaries, node, last,
                                  target)
            if (j is None or j in claimed
                    or (dtype_plan or {}).get(j)
                    != (dtype_plan or {}).get(i)):
                break
            chain.append(j)
            node, last = cfg.layers[j].nindex_out[0], j
            if cfg.layers[j].type_name in _ACT_TYPES:
                break  # bias past the activation must stay separate
        if chain:
            out.append((i, chain))
            claimed.update(chain)
    return out


def find_merge_site(cfg: NetConfig, target: Optional[int],
                    dtype_plan: Optional[Dict[int, Any]] = None,
                    ) -> Optional[Tuple[int, int]]:
    """First (conv_idx, onexone_idx) pair matching the 1x1-merge
    pattern, or None: an ungrouped conv whose single output node
    feeds EXACTLY one ungrouped 1x1/stride-1/pad-0 conv, neither
    weight-shared, no activation stamped on either, and the
    intermediate node not the requested output. Convs with DIFFERENT
    per-layer dtype stamps never merge - the merged conv runs at the
    first conv's dtype, which would silently override the other
    layer's `layer_dtype` pin (explicit-keys-always-win)."""
    primaries = share_primaries(cfg)
    cons = node_consumers(cfg)
    for j, second in enumerate(cfg.layers):
        if (second.type_name != "conv" or second.is_shared
                or j in primaries or len(second.nindex_in) != 1
                or len(second.nindex_out) != 1
                or second.nindex_out[0] == second.nindex_in[0]):
            continue
        a = second.nindex_in[0]
        if a == target:
            continue
        obj2 = layer_obj(cfg, j)
        if (obj2 is None or obj2.param.kernel_height != 1
                or obj2.param.kernel_width != 1
                or obj2.param.stride != 1
                or obj2.param.pad_y or obj2.param.pad_x
                or obj2.param.num_group != 1
                or getattr(obj2, "fused_act", "")):
            continue
        writers = node_writers(cfg, a)
        if len(writers) != 1 or writers[0] >= j:
            continue
        i = writers[0]
        first = cfg.layers[i]
        if (first.type_name != "conv" or first.is_shared
                or i in primaries or len(first.nindex_out) != 1):
            continue
        if (dtype_plan or {}).get(i) != (dtype_plan or {}).get(j):
            continue  # differing dtype stamps: a pin must survive
        if ((layer_quant_pin(cfg, i) == "float")
                != (layer_quant_pin(cfg, j) == "float")):
            # the merged conv runs at ONE quantization setting, and
            # only "float" excludes a site (find_quant_sites) - ""
            # and an explicit "int8" are the same effective route,
            # so only a float-vs-quantized mismatch would silently
            # override a pin (explicit-keys-always-win, the
            # layer_dtype exclusion rule applied to the quant axis)
            continue
        if [c for c in cons.get(a, ()) if c != j]:
            continue  # another reader needs the intermediate value
        obj1 = layer_obj(cfg, i)
        if (obj1 is None or obj1.param.num_group != 1
                or getattr(obj1, "fused_act", "")):
            continue
        return i, j
    return None


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------
class GraphPass:
    """One named transform over a GraphModule. `stage` declares when
    it runs: "graph" passes apply to the train+eval network at build
    time and must preserve values and checkpoint structure; "infer"
    passes apply per requested output node to the clone the inference
    executables are built from."""

    name: str = ""
    stage: str = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        raise NotImplementedError


PASS_REGISTRY: Dict[str, Type[GraphPass]] = {}

# canonical application order (infer passes prune first so the fold
# never sees - or folds - a dead subgraph; elim_reshape/cse next so
# cleanup/dedupe exposes single-consumer fold/merge sites;
# fuse_activation after the structural rewrites so chains uncovered
# by the fold and the 1x1 merge still fuse; quantize_int8 LAST so it
# quantizes the final transformed layers - a folded/merged conv is
# quantized once, at its composed weights)
_CANONICAL_ORDER = ("space_to_depth", "autocast",
                    "dead_layer_elim", "elim_reshape", "cse_share",
                    "fold_conv_bn", "merge_conv_1x1",
                    "fuse_activation", "quantize_int8")


def register_pass(cls: Type[GraphPass]) -> Type[GraphPass]:
    assert cls.name, "pass class must define a name"
    PASS_REGISTRY[cls.name] = cls
    return cls


def resolve_pass_name(name: str) -> str:
    """Validate a pass name with did-you-mean (the `serve_max_batchh`
    precedent applied to pass names: a typo'd pass must cost an error
    with a suggestion, never a silently-unoptimized run)."""
    if name in PASS_REGISTRY:
        return name
    hint = difflib.get_close_matches(name, PASS_REGISTRY.keys(), n=1,
                                     cutoff=0.6)
    msg = f"unknown graph pass '{name}'"
    if hint:
        msg += f" (did you mean '{hint[0]}'?)"
    raise ValueError(
        msg + f"; available passes: {', '.join(sorted(PASS_REGISTRY))}")


@register_pass
class SpaceToDepthPass(GraphPass):
    """Stamp the space-to-depth input-conv rewrite decision onto the
    DAG (module docstring). Value-identical to the in-op auto
    heuristic by construction: both evaluate `ops.conv.s2d_auto`."""

    name = "space_to_depth"
    stage = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        from cxxnet_tpu.ops.conv import s2d_auto

        def unstamped(idx, info):
            return (info.type_name == "conv" and not info.is_shared
                    and not any(k == "space_to_depth"
                                for k, _ in (gm.cfg.defcfg
                                             + gm.cfg.layercfg[idx])))

        if not any(unstamped(i, li)
                   for i, li in enumerate(gm.cfg.layers)):
            # nothing to stamp: skip the shape-inference Network
            # build entirely (the common MLP/no-conv case)
            return gm
        from cxxnet_tpu.nnet.network import Network
        net = Network(gm.cfg, gm.batch_size)
        for idx, info in enumerate(gm.cfg.layers):
            if not unstamped(idx, info):
                continue
            lay = net.layer_objs[idx]
            in_ch = net.node_shapes[info.nindex_in[0]][1]
            on = s2d_auto(in_ch, lay.param.stride,
                          lay.param.kernel_height,
                          lay.param.kernel_width, lay.param.num_group)
            gm.cfg.layercfg[idx].append(
                ("space_to_depth", "1" if on else "0"))
            gm.log.append(
                f"space_to_depth: conv[{idx}] in_ch={in_ch} "
                f"stride={lay.param.stride} -> {int(on)}")
        return gm


@register_pass
class AutocastPass(GraphPass):
    """Stamp a compute dtype per layer (module docstring). A no-op
    under f32 compute; under bf16 the fragile layer types stay f32
    and `layer_dtype = float32|bfloat16` pins individual layers."""

    name = "autocast"
    stage = "graph"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        import jax.numpy as jnp
        if gm.compute_dtype is None or gm.compute_dtype == jnp.float32:
            gm.log.append("autocast: f32 compute, nothing to stamp")
            return gm
        parse = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}
        for idx, info in enumerate(gm.cfg.layers):
            src = (info.primary_layer_index if info.is_shared else idx)
            ltype = gm.cfg.layers[src].type_name
            override = ""
            for k, v in gm.cfg.defcfg + gm.cfg.layercfg[src]:
                if k == "layer_dtype":
                    override = v
            if override:
                if override not in parse:
                    raise ValueError(
                        "layer_dtype must be float32 or bfloat16, "
                        f"got {override!r}")
                d = parse[override]
            elif ltype in _F32_SENSITIVE_TYPES:
                d = jnp.float32
            else:
                d = gm.compute_dtype
            gm.dtype_plan[idx] = d
            gm.log.append(f"autocast: layer[{idx}] {ltype} -> "
                          f"{jnp.dtype(d).name}")
        return gm


@register_pass
class DeadLayerElimPass(GraphPass):
    """Prune layers not on a path to the requested output node
    (module docstring)."""

    name = "dead_layer_elim"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        if ctx.target_node is None:
            return gm
        cfg = gm.cfg
        needed = {ctx.target_node}
        keep: set = set()
        for idx in reversed(range(len(cfg.layers))):
            info = cfg.layers[idx]
            if any(o in needed for o in info.nindex_out):
                keep.add(idx)
                needed.update(info.nindex_in)
        if ctx.target_node >= cfg.num_nodes:
            raise ValueError(
                f"dead_layer_elim: unknown target node "
                f"{ctx.target_node}")
        # kept share layers whose primary died: promote to primary -
        # the weights arrive through the param map, so the dead
        # ancestor chain need not be retained for them
        for idx in sorted(keep):
            info = cfg.layers[idx]
            if not info.is_shared:
                continue
            prim = info.primary_layer_index
            if prim in keep:
                continue
            primary = cfg.layers[prim]
            info.type_name = primary.type_name
            info.primary_layer_index = -1
            info.name = ""
            cfg.layercfg[idx] = list(cfg.layercfg[prim])
            gm.param_keys[idx] = gm.param_keys[prim]
            gm.log.append(
                f"dead_layer_elim: promoted share[{idx}] to primary "
                f"(its primary {prim} is dead)")
        dropped = [i for i in range(len(cfg.layers)) if i not in keep]
        if dropped:
            gm.log.append(
                f"dead_layer_elim: pruned {len(dropped)}/"
                f"{len(cfg.layers)} layers not reaching node "
                f"{ctx.target_node}")
        gm.remove_layers(dropped)
        return gm


@register_pass
class FoldConvBNPass(GraphPass):
    """Fold conv/fullc + batch_norm chains using frozen calibration
    statistics (module docstring). Defers (logs, no rewrite) until
    `ctx.fold_stats` exists; skips any site whose raw pre-BN value is
    the requested output."""

    name = "fold_conv_bn"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        sites = find_fold_sites(gm.cfg)
        if not sites:
            return gm
        if ctx.fold_stats is None:
            gm.log.append(
                f"fold_conv_bn: {len(sites)} site(s) deferred - no "
                "calibration stats yet")
            return gm
        drop: List[int] = []
        for i, j in sites:
            conv, bn = gm.cfg.layers[i], gm.cfg.layers[j]
            bn_key, conv_key = gm.param_keys[j], gm.param_keys[i]
            stats = ctx.fold_stats.get(bn_key)
            if stats is None:
                gm.log.append(
                    f"fold_conv_bn: no stats for {bn_key}, skipped")
                continue
            if (bn.nindex_out[0] != bn.nindex_in[0]
                    and bn.nindex_in[0] == ctx.target_node):
                # the caller asked for the RAW conv output
                gm.log.append(
                    f"fold_conv_bn: target node is {conv_key}'s raw "
                    "output, site skipped")
                continue
            conv.nindex_out = list(bn.nindex_out)
            gm.folds.append(FoldSite(conv_key=conv_key, bn_key=bn_key,
                                     mean=stats[0], rstd=stats[1]))
            drop.append(j)
            gm.log.append(
                f"fold_conv_bn: folded {bn_key} into {conv_key}")
        gm.remove_layers(drop)
        return gm


@register_pass
class CseSharePass(GraphPass):
    """Common-subexpression sharing (module docstring): dedupe
    sibling layers that provably compute the same value - same input
    nodes AND same function (same live-params source for weighted
    layers, or identical type+config for param-less ones). Runs to a
    fixpoint so a dedupe that makes two downstream siblings identical
    cascades."""

    name = "cse_share"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        while self._sweep(gm, ctx):
            pass
        return gm

    @staticmethod
    def _signature(gm: GraphModule, idx: int):
        from cxxnet_tpu.layers.loss import LossLayer
        cfg = gm.cfg
        info = cfg.layers[idx]
        if (len(info.nindex_out) != 1
                or info.nindex_out[0] in info.nindex_in):
            return None  # multi-output or self-loop: not a candidate
        if node_writers(cfg, info.nindex_out[0]) != [idx]:
            return None  # aliased output node
        obj = layer_obj(cfg, idx)
        if obj is None or isinstance(obj, LossLayer):
            return None
        src = info.primary_layer_index if info.is_shared else idx
        # layers stamped with different compute dtypes produce
        # different values - never "the same function"
        plan_d = gm.dtype_plan.get(idx)
        if obj.param_tags():
            # weighted layer: identical only when the params COME from
            # the same place (a primary and its share[...], or two
            # shares of one primary) - equal weights of two distinct
            # primaries cannot be proven from the graph
            return ("params", src, tuple(info.nindex_in), plan_d)
        return ("pure", cfg.layers[src].type_name,
                tuple(cfg.layercfg[src]), tuple(info.nindex_in),
                plan_d)

    def _sweep(self, gm: GraphModule, ctx: PassContext) -> bool:
        cfg = gm.cfg
        groups: Dict[Any, List[int]] = {}
        for idx in range(len(cfg.layers)):
            sig = self._signature(gm, idx)
            if sig is not None:
                groups.setdefault(sig, []).append(idx)
        drops: List[int] = []
        remap: Dict[int, int] = {}
        for members in groups.values():
            if len(members) < 2:
                continue
            kept = members[0]
            kept_info = cfg.layers[kept]
            kept_src = (kept_info.primary_layer_index
                        if kept_info.is_shared else kept)
            for j in members[1:]:
                dj = cfg.layers[j].nindex_out[0]
                if dj == ctx.target_node:
                    continue  # the duplicate's node IS the output
                # shares of a dropped primary re-point to the kept
                # duplicate's param source (same params by the
                # signature) - the dead-primary promotion machinery's
                # rule applied sideways
                for s_li in cfg.layers:
                    if (s_li.is_shared
                            and s_li.primary_layer_index == j):
                        s_li.primary_layer_index = kept_src
                remap[dj] = kept_info.nindex_out[0]
                drops.append(j)
                gm.log.append(
                    f"cse_share: layer[{j}] duplicates layer[{kept}]"
                    f" ({cfg.layers[kept_src].type_name}); consumers "
                    f"re-read node {kept_info.nindex_out[0]}")
        if not drops:
            return False
        for li in cfg.layers:
            li.nindex_in = [remap.get(n, n) for n in li.nindex_in]
        gm.remove_layers(drops)
        return True


@register_pass
class MergeConv1x1Pass(GraphPass):
    """Collapse conv + 1x1-conv chains into one conv via live weight
    contraction (module docstring). Runs to a fixpoint so a
    conv->1x1->1x1 tower folds flat."""

    name = "merge_conv_1x1"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        while True:
            site = find_merge_site(gm.cfg, ctx.target_node,
                                   gm.dtype_plan)
            if site is None:
                return gm
            i, j = site
            cfg = gm.cfg
            first_key, second_key = gm.param_keys[i], gm.param_keys[j]
            obj2 = layer_obj(cfg, j)
            # the merged conv keeps the first conv's geometry (kernel,
            # stride, pad, s2d stamp) and takes the second's output
            # width; its weights/bias arrive contracted via the param
            # function, so no init-time config beyond nchannel changes
            cfg.layercfg[i].append(
                ("nchannel", str(obj2.param.num_channel)))
            cfg.layers[i].nindex_out = list(cfg.layers[j].nindex_out)
            gm.merges.append(MergeSite(first_key=first_key,
                                       second_key=second_key))
            gm.remove_layers([j])
            gm.log.append(
                f"merge_conv_1x1: contracted {second_key} (1x1) into "
                f"{first_key}")


@register_pass
class FuseActivationPass(GraphPass):
    """Stamp trailing relu chains into their conv/fullc producer and
    absorb separate bias layers into the producer's bias (module
    docstring). Runs LAST in canonical order so chains exposed by
    fold_conv_bn / merge_conv_1x1 fuse too."""

    name = "fuse_activation"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        cfg = gm.cfg
        chains = find_act_chains(cfg, ctx.target_node, gm.dtype_plan)
        if not chains:
            return gm
        drops: List[int] = []
        for i, chain in chains:
            bias_keys = [gm.param_keys[j] for j in chain
                         if cfg.layers[j].type_name == "bias"]
            act = next((cfg.layers[j].type_name for j in chain
                        if cfg.layers[j].type_name in _ACT_TYPES), "")
            cfg.layers[i].nindex_out = list(
                cfg.layers[chain[-1]].nindex_out)
            if act:
                cfg.layercfg[i].append(("fused_act", act))
            if bias_keys:
                gm.act_fuses.append(ActFuseSite(
                    producer_key=gm.param_keys[i],
                    bias_keys=bias_keys))
            drops.extend(chain)
            gm.log.append(
                f"fuse_activation: {gm.param_keys[i]} absorbs "
                f"{len(bias_keys)} bias layer(s)"
                + (f" + {act}" if act else ""))
        gm.remove_layers(drops)
        return gm


@register_pass
class ElimReshapePass(GraphPass):
    """Eliminate flatten layers feeding a single fullc (module
    docstring): the consumer re-reads the flatten's input node and
    gets a `flatten_input = 1` stamp so its shape inference accepts
    the 4-D node (its apply flattens in the same memory order, so the
    rewrite is bitwise value-identical). Runs to a fixpoint."""

    name = "elim_reshape"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        while True:
            hit = self._find(gm.cfg, ctx.target_node)
            if hit is None:
                return gm
            i, j = hit
            cfg = gm.cfg
            gm.log.append(
                f"elim_reshape: dropped {cfg.layers[i].type_name}"
                f"[{i}]; fullc[{j}] consumes node "
                f"{cfg.layers[i].nindex_in[0]} directly")
            cfg.layers[j].nindex_in = [cfg.layers[i].nindex_in[0]]
            cfg.layercfg[j].append(("flatten_input", "1"))
            gm.remove_layers([i])

    @staticmethod
    def _find(cfg: NetConfig,
              target: Optional[int]) -> Optional[Tuple[int, int]]:
        primaries = share_primaries(cfg)
        cons = node_consumers(cfg)
        for i, info in enumerate(cfg.layers):
            if (info.type_name not in _RESHAPE_TYPES or info.is_shared
                    or i in primaries or len(info.nindex_in) != 1
                    or len(info.nindex_out) != 1
                    or info.nindex_out[0] == info.nindex_in[0]):
                continue
            a = info.nindex_out[0]
            if a == target:
                continue  # the caller asked for the flat view
            if node_writers(cfg, a) != [i]:
                continue  # aliased output node
            readers = cons.get(a, [])
            if len(readers) != 1:
                continue  # a second reader still needs the flat node
            j = readers[0]
            cinfo = cfg.layers[j]
            if (j <= i or cinfo.is_shared or j in primaries
                    or cinfo.type_name not in _RESHAPE_CONSUMER_TYPES
                    or len(cinfo.nindex_in) != 1):
                continue
            if any(i < w < j
                   for w in node_writers(cfg, info.nindex_in[0])):
                # a self-loop between flatten and the fullc rewrites
                # the input node; the fullc would read the wrong value
                continue
            return i, j
        return None


@register_pass
class QuantizeInt8Pass(GraphPass):
    """Int8 post-training quantization of eligible conv/fullc layers
    (module docstring). Defers (logs, no sites) until the calibration
    sweep recorded activation ranges (`ctx.quant_stats`); the
    per-channel weight scales are filled by the trainer AFTER the
    pipeline runs, from the transformed float weights."""

    name = "quantize_int8"
    stage = "infer"

    def run(self, gm: GraphModule, ctx: PassContext) -> GraphModule:
        from cxxnet_tpu.ops.int8 import _SCALE_FLOOR
        sites = find_quant_sites(gm.cfg)
        if not sites:
            return gm
        if ctx.quant_stats is None:
            gm.log.append(
                f"quantize_int8: {len(sites)} site(s) deferred - no "
                "calibration stats yet")
            return gm
        for idx in sites:
            key = gm.param_keys[idx]
            amax = (ctx.quant_stats.get(key)
                    if key is not None else None)
            if amax is None:
                gm.log.append(
                    f"quantize_int8: no activation stats for {key}, "
                    "site stays float")
                continue
            gm.quants.append(QuantSite(
                key=key,
                act_scale=float(max(amax, _SCALE_FLOOR)) / 127.0))
            gm.log.append(
                f"quantize_int8: {key} -> int8 (activation absmax "
                f"{float(amax):.4g})")
        return gm


# ---------------------------------------------------------------------------
# params of a transformed graph, from the live train params
# ---------------------------------------------------------------------------
def make_param_fn(gm: GraphModule, quantize: bool = True):
    """jax-traceable function: live train params -> the transformed
    graph's params. Key remaps are free; fold sites compute
    `W' = W * (slope * rstd)` and `b' = (b - mean) * k + beta` from
    the LIVE weights (the folded weights track checkpoint loads and
    set_weight), with only mean/rstd frozen at calibration - and
    rstd precomputed, so no rsqrt (let alone a moment reduction)
    appears in the folded jaxpr. Merge sites contract
    `W' = W2 . W1` / `b' = W2 . b1 + b2` and act-fuse sites absorb
    separate bias-layer params (`b' = b + sum(b_i)`) - applied in
    stages AFTER the folds so a folded conv that later merged (or
    grew a fused activation) composes: each stage reads the previous
    stage's transform of the same live key. Quant sites run LAST:
    the int8 weights are one fused round/clip/convert of the staged
    float weight against the FROZEN per-channel scale (ops/int8.py),
    so they too stay live functions of the params argument - only
    the scales are calibration constants. `quantize=False` yields
    the float view of the same transforms (the trainer evaluates it
    once to freeze the weight scales)."""
    import jax.numpy as jnp
    pairs = list(gm.param_map().items())

    def param_fn(params):
        cur: Dict[str, Any] = {}

        def live(key):
            return cur.get(key, params.get(key))

        for site in gm.folds:
            if site.conv_key not in params:
                continue
            conv_p, bn_p = params[site.conv_key], params[site.bn_key]
            k = bn_p["slope"] * jnp.asarray(site.rstd)
            w = conv_p["wmat"]
            kw = k.reshape((-1,) + (1,) * (w.ndim - 1))
            bias = conv_p.get("bias", jnp.zeros_like(k))
            cur[site.conv_key] = {
                "wmat": w * kw.astype(w.dtype),
                "bias": (bias - jnp.asarray(site.mean)) * k
                        + bn_p["bias"],
            }
        for site in gm.merges:
            # BOTH convs read through live(): either side may carry
            # an earlier fold's transform (conv->1x1->bn folds into
            # the 1x1 BEFORE the merge contracts it), and a missing
            # key skips the transform like the fold guard above
            p1, p2 = live(site.first_key), live(site.second_key)
            if p1 is None or p2 is None:
                continue
            w1, w2 = p1["wmat"], p2["wmat"]
            # (O2, O1, 1, 1) -> (O2, O1); contract over the first
            # conv's output channels - a weight-sized dot, never a
            # data-sized conv
            k2 = w2.reshape(w2.shape[0], w2.shape[1])
            entry = {"wmat": jnp.einsum("oi,i...->o...",
                                        k2.astype(w1.dtype), w1)}
            b1, b2 = p1.get("bias"), p2.get("bias")
            if b1 is not None:
                b = k2 @ b1
                entry["bias"] = b + b2 if b2 is not None else b
            elif b2 is not None:
                entry["bias"] = b2
            cur[site.first_key] = entry
        for site in gm.act_fuses:
            src = live(site.producer_key)
            if src is None or any(bk not in params
                                  for bk in site.bias_keys):
                continue
            p = dict(src)
            b = p.get("bias")
            for bk in site.bias_keys:
                extra = params[bk]["bias"]
                b = extra if b is None else b + extra
            if b is not None:
                p["bias"] = b
            cur[site.producer_key] = p
        if quantize:
            from cxxnet_tpu.ops import int8 as int8_ops
            for site in gm.quants:
                if site.wscale is None:
                    continue  # scales never frozen: the site executes
                    # float (the trainer fills wscale post-pipeline)
                src = live(site.key)
                if src is None or "wmat" not in src:
                    continue
                entry = {
                    "wmat_q": int8_ops.quantize_weight(src["wmat"],
                                                       site.wscale),
                    "wscale": jnp.asarray(site.wscale, jnp.float32),
                    "ascale": jnp.asarray(site.act_scale,
                                          jnp.float32),
                }
                b = src.get("bias")
                if b is not None:
                    entry["bias"] = b
                cur[site.key] = entry

        out = {}
        for new_key, live_key in pairs:
            v = live(live_key)
            if v is not None:
                out[new_key] = v
        return out

    return param_fn


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------
class PassPipeline:
    """An ordered set of GraphPasses (canonical order, module
    docstring). Built from the `graph_passes = a,b,...` config key
    plus the per-pass `pass_<name> = 0|1` toggles; unknown names get
    did-you-mean errors."""

    def __init__(self, passes: Sequence[GraphPass]):
        order = {n: i for i, n in enumerate(_CANONICAL_ORDER)}
        self.passes = sorted(passes,
                             key=lambda p: order.get(p.name, 99))

    @classmethod
    def from_config(cls, spec: str,
                    toggles: Optional[Dict[str, int]] = None,
                    ) -> "PassPipeline":
        spec = (spec or "").strip()
        if spec in ("0", "none", "off"):
            spec = ""
        if spec == "all":
            # every REGISTERED pass - not the canonical-order tuple,
            # which only sorts: a pass added via @register_pass must
            # not be silently excluded from `graph_passes = all`
            enabled = set(PASS_REGISTRY)
        else:
            enabled = {resolve_pass_name(t.strip())
                       for t in spec.split(",") if t.strip()}
        for name, on in (toggles or {}).items():
            resolve_pass_name(name)
            if on:
                enabled.add(name)
            else:
                enabled.discard(name)
        return cls([PASS_REGISTRY[n]() for n in enabled])

    @property
    def graph_passes(self) -> List[GraphPass]:
        return [p for p in self.passes if p.stage == "graph"]

    @property
    def infer_passes(self) -> List[GraphPass]:
        return [p for p in self.passes if p.stage == "infer"]

    def has(self, name: str) -> bool:
        return any(p.name == name for p in self.passes)

    def run_graph(self, gm: GraphModule,
                  ctx: Optional[PassContext] = None) -> GraphModule:
        ctx = ctx or PassContext()
        for p in self.graph_passes:
            gm = p.run(gm, ctx)
        return gm

    def run_infer(self, gm: GraphModule,
                  ctx: PassContext) -> GraphModule:
        for p in self.infer_passes:
            gm = p.run(gm, ctx)
        return gm

    def names(self) -> List[str]:
        return [p.name for p in self.passes]
